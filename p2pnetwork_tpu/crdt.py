"""State-based CRDTs over the sockets backend — merge, don't coordinate.

The third classic consistency discipline in this package, beside causal
delivery (causal.py: order the updates) and Merkle reconciliation
(sync.py: diff the stores): make the DATA TYPES conflict-free, so
replicas accept writes locally, gossip their state, and a commutative /
associative / idempotent ``merge`` guarantees convergence no matter how
messages interleave, duplicate, or arrive late. The reference gives its
users dict transport and nothing above it [ref: README.md:20,
p2pnetwork/nodeconnection.py:128-143]; these are the structures
(Shapiro et al. 2011) they end up reimplementing:

- :class:`GCounter` — grow-only counter (per-replica tallies, merge =
  elementwise max);
- :class:`PNCounter` — increment/decrement (two GCounters);
- :class:`LWWRegister` — last-writer-wins register (max by
  ``(timestamp, replica_id)`` — ties break deterministically);
- :class:`ORSet` — observed-remove set (adds tagged uniquely; a remove
  tombstones exactly the tags it has SEEN, so a concurrent re-add
  survives — the add-wins semantics naive tombstone sets get wrong).

All four are plain Python values with ``to_dict`` / ``from_dict`` wire
forms and an algebra the tests pin directly (commutativity,
associativity, idempotence — the convergence theorem's premises).

:class:`CRDTNode` hosts named instances: ``counter/register/set_``
accessors create-or-get, every local mutation broadcasts the full state
(state-based gossip — duplication-safe by idempotence), and inbound
states merge on the event loop. ``sync_all()`` rebroadcasts everything,
the anti-entropy catch-up for peers that joined late.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Set, Tuple

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

CRDT_KEY = "_crdt"


class GCounter:
    """Grow-only counter: one tally per replica, merge by max."""

    kind = "gcounter"

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    def increment(self, replica: str, by: int = 1) -> None:
        if by < 0:
            raise ValueError("GCounter cannot decrement (use PNCounter)")
        self.counts[replica] = self.counts.get(replica, 0) + by

    @property
    def value(self) -> int:
        return sum(self.counts.values())

    def merge(self, other: "GCounter") -> "GCounter":
        out = dict(self.counts)
        for k, v in other.counts.items():
            out[k] = max(out.get(k, 0), v)
        return GCounter(out)

    def to_dict(self) -> dict:
        return {"counts": dict(self.counts)}

    @classmethod
    def from_dict(cls, d: dict) -> "GCounter":
        return cls(d.get("counts", {}))


class PNCounter:
    """Increment/decrement counter: a positive and a negative GCounter."""

    kind = "pncounter"

    def __init__(self, p: Optional[GCounter] = None,
                 n: Optional[GCounter] = None):
        self.p = p or GCounter()
        self.n = n or GCounter()

    def increment(self, replica: str, by: int = 1) -> None:
        self.p.increment(replica, by)

    def decrement(self, replica: str, by: int = 1) -> None:
        self.n.increment(replica, by)

    @property
    def value(self) -> int:
        return self.p.value - self.n.value

    def merge(self, other: "PNCounter") -> "PNCounter":
        return PNCounter(self.p.merge(other.p), self.n.merge(other.n))

    def to_dict(self) -> dict:
        return {"p": self.p.to_dict(), "n": self.n.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "PNCounter":
        return cls(GCounter.from_dict(d.get("p", {})),
                   GCounter.from_dict(d.get("n", {})))


class LWWRegister:
    """Last-writer-wins register; ties break by replica id, so merges
    agree everywhere even at equal timestamps."""

    kind = "lww"

    def __init__(self, value: Any = None, ts: float = 0.0,
                 replica: str = ""):
        self.value = value
        self.ts = ts
        self.replica = replica

    def set(self, replica: str, value: Any,
            ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        if (ts, replica) >= (self.ts, self.replica):
            self.value, self.ts, self.replica = value, ts, replica

    def merge(self, other: "LWWRegister") -> "LWWRegister":
        a, b = (self, other) if (self.ts, self.replica) >= \
            (other.ts, other.replica) else (other, self)
        return LWWRegister(a.value, a.ts, a.replica)

    def to_dict(self) -> dict:
        return {"value": self.value, "ts": self.ts,
                "replica": self.replica}

    @classmethod
    def from_dict(cls, d: dict) -> "LWWRegister":
        return cls(d.get("value"), d.get("ts", 0.0), d.get("replica", ""))


class ORSet:
    """Observed-remove set: adds carry unique tags; a remove tombstones
    only tags it has OBSERVED, so concurrent re-adds win."""

    kind = "orset"

    def __init__(self,
                 adds: Optional[Dict[str, Set[Tuple[str, int]]]] = None,
                 tombs: Optional[Set[Tuple[str, int]]] = None):
        self.adds: Dict[str, Set[Tuple[str, int]]] = {
            k: set(v) for k, v in (adds or {}).items()}
        self.tombs: Set[Tuple[str, int]] = set(tombs or ())
        self._next = 0

    def add(self, replica: str, elem: str) -> None:
        self._next += 1
        self.adds.setdefault(elem, set()).add((replica, self._next))

    def remove(self, elem: str) -> None:
        self.tombs |= self.adds.get(elem, set())

    def __contains__(self, elem: str) -> bool:
        return bool(self.adds.get(elem, set()) - self.tombs)

    def elements(self) -> Set[str]:
        return {e for e, tags in self.adds.items() if tags - self.tombs}

    def merge(self, other: "ORSet") -> "ORSet":
        adds: Dict[str, Set[Tuple[str, int]]] = {
            k: set(v) for k, v in self.adds.items()}
        for k, v in other.adds.items():
            adds.setdefault(k, set()).update(v)
        out = ORSet(adds, self.tombs | other.tombs)
        # Tag counters are per-replica-instance; keep the max so a
        # merged-into instance never reissues a live tag of its own.
        out._next = max(self._next, other._next)
        return out

    def to_dict(self) -> dict:
        return {"adds": {k: [list(tag) for tag in sorted(v)]
                         for k, v in self.adds.items()},
                "tombs": [list(t) for t in sorted(self.tombs)],
                "next": self._next}

    @classmethod
    def from_dict(cls, d: dict) -> "ORSet":
        adds = {k: {(str(a), int(b)) for a, b in v}
                for k, v in d.get("adds", {}).items()}
        tombs = {(str(a), int(b)) for a, b in d.get("tombs", [])}
        out = cls(adds, tombs)
        out._next = int(d.get("next", 0))
        return out


_KINDS = {c.kind: c for c in (GCounter, PNCounter, LWWRegister, ORSet)}


class CRDTNode(Node):
    """A :class:`Node` hosting named CRDTs with state-based gossip.

    Local mutations go through the ``update`` helper (runs the mutation
    on the event loop, then broadcasts the full state); inbound states
    merge on arrival. Convergence needs no ordering, no dedup, and no
    acks — the merge algebra is the whole protocol."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._crdts: Dict[str, Any] = {}
        # Accessors create-on-miss from ANY thread while merges replace
        # entries on the loop; unguarded, a reader's lazy insert could
        # clobber a concurrently merged state (a lost-update race a
        # poll loop can actually hit). One lock covers every
        # check-then-insert and merge-then-replace.
        self._crdt_lock = concurrency.lock()

    # ------------------------------------------------------------ access

    def _get(self, name: str, cls):
        with self._crdt_lock:
            cur = self._crdts.get(name)
        if cur is None:
            # Construct the empty CRDT outside the lock (open-call
            # discipline); setdefault re-checks, so two racing getters
            # agree on one instance and the loser's empty candidate —
            # never published, never mutated — is garbage.
            candidate = cls()
            with self._crdt_lock:
                cur = self._crdts.setdefault(name, candidate)
        if not isinstance(cur, cls):
            raise TypeError(
                f"CRDT {name!r} is a {type(cur).__name__}, "
                f"not {cls.__name__}")
        return cur

    def gcounter(self, name: str) -> GCounter:
        return self._get(name, GCounter)

    def counter(self, name: str) -> PNCounter:
        return self._get(name, PNCounter)

    def register(self, name: str) -> LWWRegister:
        return self._get(name, LWWRegister)

    def set_(self, name: str) -> ORSet:
        return self._get(name, ORSet)

    # ---------------------------------------------------------- mutation

    def update(self, name: str, kind: str, fn,
               done: Optional[Any] = None,
               error: Optional[list] = None) -> None:
        """Run ``fn(crdt)`` on the event loop, then broadcast the state.
        ``kind`` is one of gcounter/pncounter/lww/orset. Thread-safe.
        ``done`` is set even when ``fn`` raises (the exception lands in
        ``error`` — without that, a raising mutation would vanish into
        asyncio's handler and a waiting caller would time out blaming
        the wrong thing)."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")
        cls = _KINDS[kind]

        def _do():
            try:
                crdt = self._get(name, cls)
                fn(crdt)
                self._broadcast(name, crdt)
            except Exception as e:  # noqa: BLE001 — reported to caller
                if error is not None:
                    error.append(e)
                else:
                    raise
            finally:
                if done is not None:
                    done.set()

        loop.call_soon_threadsafe(_do)

    def mutate(self, name: str, kind: str, fn,
               timeout: float = 10.0) -> None:
        """:meth:`update`, but blocks until the mutation has applied
        locally (the broadcast is still asynchronous); re-raises
        whatever ``fn`` raised."""
        ev = concurrency.event()
        err: list = []
        self.update(name, kind, fn, done=ev, error=err)
        if not ev.wait(timeout):
            raise TimeoutError(f"mutation of {name!r} never ran")
        if err:
            raise err[0]

    def sync_all(self) -> None:
        """Rebroadcast every hosted CRDT — catch-up for late joiners.
        Thread-safe; duplication is harmless by idempotence."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")

        def _do():
            # Snapshot under the lock: accessors (gcounter/counter/
            # register/set_) create-on-miss from foreign threads, and a
            # concurrent insert during this loop would raise "dictionary
            # changed size during iteration".
            with self._crdt_lock:
                items = list(self._crdts.items())
            for name, crdt in items:
                self._broadcast(name, crdt)

        loop.call_soon_threadsafe(_do)

    def _broadcast(self, name: str, crdt) -> None:
        self.send_to_nodes({CRDT_KEY: name, "kind": crdt.kind,
                            "state": crdt.to_dict()})

    def crdt_merged(self, name: str, crdt) -> None:
        """An inbound state was merged into ``name``. Extension hook."""
        self.debug_print(f"crdt_merged: {name}")
        self._dispatch("crdt_merged", None, {"name": name})

    # ------------------------------------------------------ interception

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict) and CRDT_KEY in data:
            kind = data.get("kind")
            cls = _KINDS.get(kind)
            if cls is None:
                self.debug_print(f"unknown CRDT kind {kind!r} dropped")
                return
            name = data[CRDT_KEY]
            incoming = cls.from_dict(data.get("state", {}))
            # Empty-CRDT construction and I/O (debug_print) both happen
            # outside the lock; only the check + merge-then-replace —
            # the lost-update window the lock exists for — stay inside.
            # The hot path (name already known) takes the lock ONCE; the
            # first message for a name releases it, constructs the empty
            # CRDT, and retries — the open-call shape _get() uses. A
            # racing insert between iterations just orphans `fresh`.
            fresh = None
            conflict = False
            merged = None
            while True:
                with self._crdt_lock:
                    mine = self._crdts.get(name)
                    if mine is None:
                        mine = fresh
                    if mine is not None:
                        if isinstance(mine, cls):
                            # merge() under the lock is the atomicity
                            # this lock exists for (check + merge +
                            # replace as one step); graftrace refuted
                            # the open-call hazard dynamically — merge
                            # is pure CRDT algebra, acquires no locks
                            # and never blocks, verified across the
                            # seeded crdt_merge_storm schedule battery
                            # (tests/test_graftrace.py pins it).
                            merged = self._crdts[name] = mine.merge(incoming)  # graftlint: ignore[lock-open-call] -- graftrace-refuted: merge() is pure (no locks, no blocking); see crdt_merge_storm scenario
                        else:
                            conflict = True
                        break
                fresh = cls()
            if conflict:
                self.debug_print(f"CRDT kind conflict for {name!r} dropped")
                return
            self.crdt_merged(name, merged)
            return
        super().node_message(node, data)
