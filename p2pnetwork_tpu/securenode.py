"""SecureNode: signed, integrity-checked messaging on top of ``Node``.

The reference README advertises a ``SecureNode`` showcase ("uses JSON,
hashing and signing to communicate between the nodes",
[ref: README.md:224-238]) and its examples directory describes the design:
"All nodes have a private/public key and signs all the messages they send.
These messages are also verified... checked on integrity and
non-repudiation" [ref: examples/README.md:10-16]. The class itself is
absent from the reference snapshot (SURVEY.md section 2.2, documented-but-
absent) — this module actually ships it.

Design (new, not a port — the reference's showcase used pycryptodome RSA):

- Every node holds an Ed25519 keypair; the public key travels with each
  message, so receivers verify without any key exchange protocol.
- The envelope is a plain dict (so it rides the existing dict wire path,
  JSON + EOT framing [ref: nodeconnection.py:128-143]):
  ``{"_secure": 1, "scheme": ..., "payload": ..., "hash": sha512-hex,
  "signature": hex, "public_key": hex, "signer": node-id, "nonce": hex}``
- ``hash`` covers the canonical JSON of ``(payload, signer, nonce)``;
  the signature covers the hash. Tampering with any of payload, claimed
  signer id, or nonce invalidates the message.
- **Signer identity is bound to a key by pinning.** A traveling key alone
  proves nothing (anyone can sign "alice"'s messages with their own key),
  so receivers hold a ``signer id -> public key`` table: pre-pin with
  :meth:`trust_key` (out-of-band distribution — the strong mode), or rely
  on the default trust-on-first-use (the first verified envelope from a
  signer pins its key; later envelopes under a different key are
  rejected). The verified key is handed to the ``secure_message`` hook so
  applications can enforce stricter policies.
- **Replay protection**: each verified (signer, nonce) pair is remembered
  in a bounded window (``replay_window``, drop-oldest); a captured envelope
  re-sent inside the window is rejected as ``"replayed nonce"``.
  Applications needing protection beyond the window should timestamp their
  payloads.
- Valid messages fire the ``secure_message`` hook (and the ``"secure_message"``
  callback event); invalid ones fire ``secure_message_invalid``, count into
  ``message_count_rerr``, and are never delivered as payload.

Ed25519 comes from the ``cryptography`` package when available; otherwise
SecureNode falls back to HMAC-SHA512 with a shared ``network_key`` (still
integrity-checked, no longer third-party-verifiable — the fallback is
explicit in ``self.scheme``).
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
from typing import Any, Callable, Optional

from p2pnetwork_tpu.node import Node

try:  # asymmetric path (preferred): Ed25519 via `cryptography`
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _HAVE_ED25519 = True
except ImportError:  # pragma: no cover - exercised only without cryptography
    _HAVE_ED25519 = False

import hmac as _hmac


def canonical_json(data: Any) -> bytes:
    """Deterministic JSON bytes: sorted keys, no whitespace."""
    return json.dumps(data, sort_keys=True, separators=(",", ":")).encode("utf-8")


def payload_digest(payload: Any, signer: str, nonce: str) -> str:
    """SHA-512 hex over the canonical (payload, signer, nonce) triple."""
    body = canonical_json({"payload": payload, "signer": signer, "nonce": nonce})
    return hashlib.sha512(body).hexdigest()


class SecureNode(Node):
    """A :class:`Node` whose dict messages are signed and verified.

    Extra hooks beyond the base ten-event vocabulary:

    - ``secure_message(node, payload, signer_id, public_key_hex)`` — a
      verified message (callback event ``"secure_message"``).
    - ``secure_message_invalid(node, envelope, reason)`` — failed
      verification (callback event ``"secure_message_invalid"``); also
      increments ``message_count_rerr``.

    Non-envelope messages still reach the plain ``node_message`` hook, so a
    SecureNode can talk to plain nodes (their traffic is just unverified).
    """

    def __init__(self, host: str, port: int, id: Optional[str] = None,
                 callback: Optional[Callable] = None, max_connections: int = 0,
                 private_key: Optional[bytes] = None,
                 network_key: Optional[bytes] = None, **kw):
        # Key setup first: a key error must not leave a bound socket behind.
        if _HAVE_ED25519:
            self.scheme = "ed25519"
            self._private = (
                Ed25519PrivateKey.from_private_bytes(private_key)
                if private_key is not None else Ed25519PrivateKey.generate()
            )
            self._public_hex = self._private.public_key().public_bytes_raw().hex()
        else:
            self.scheme = "hmac-sha512"
            if network_key is None:
                raise ValueError(
                    "without the `cryptography` package SecureNode needs a "
                    "shared network_key for the HMAC fallback"
                )
            self._network_key = network_key
            self._public_hex = ""
        # Pinned signer id -> public key hex (see trust_key / TOFU).
        # Explicitly trusted pins are never evicted; TOFU-learned entries
        # are bounded (oldest-learned evicted) — without a cap any peer
        # could mint signer ids until memory runs out.
        self.known_keys: dict = {}
        self.max_known_keys = 65536
        self._explicit_pins: set = set()
        # Replay window: the most recent verified nonces per signer. A
        # captured envelope re-sent within the window is rejected; the
        # window is bounded (drop-oldest), so indefinite storage is not
        # required and very old replays are an application-level concern
        # (e.g. timestamp payloads if that matters).
        self.replay_window = 4096
        # Signer entries are themselves bounded (FIFO eviction): under
        # TOFU any peer can mint fresh signer ids, and an unbounded
        # signer->window dict would be a memory-exhaustion vector.
        self.max_tracked_signers = 1024
        self._seen_nonces: dict = {}  # signer -> (set, deque), insertion-ordered
        super().__init__(host, port, id=id, callback=callback,
                         max_connections=max_connections, **kw)
        if self.scheme == "ed25519":
            self.known_keys[self.id] = self._public_hex
            self._explicit_pins.add(self.id)  # own key is never evicted

    def trust_key(self, signer_id: str, public_key_hex: str) -> None:
        """Pin ``signer_id`` to a public key (out-of-band distribution).

        Envelopes claiming that signer under any other key are rejected.
        Explicit pins are permanent (never evicted from the bounded TOFU
        table). Without a pin, the first verified envelope pins its key
        (trust-on-first-use)."""
        self.known_keys[str(signer_id)] = public_key_hex
        self._explicit_pins.add(str(signer_id))

    def _tofu_pin(self, signer: str, public_key_hex: str) -> None:
        """Learn a key on first use, evicting the oldest learned (never an
        explicitly trusted) entry when the table is full."""
        if len(self.known_keys) >= self.max_known_keys:
            for k in self.known_keys:
                if k not in self._explicit_pins:
                    del self.known_keys[k]
                    break
        self.known_keys[signer] = public_key_hex

    # ------------------------------------------------------------------ keys

    @property
    def public_key_hex(self) -> str:
        """This node's public key (hex), empty under the HMAC fallback."""
        return self._public_hex

    def _sign(self, digest_hex: str) -> str:
        if self.scheme == "ed25519":
            return self._private.sign(digest_hex.encode()).hex()
        return _hmac.new(self._network_key, digest_hex.encode(),
                         hashlib.sha512).hexdigest()

    def _verify(self, digest_hex: str, signature_hex: str,
                public_key_hex: str) -> bool:
        if self.scheme == "ed25519":
            try:
                pub = Ed25519PublicKey.from_public_bytes(bytes.fromhex(public_key_hex))
                pub.verify(bytes.fromhex(signature_hex), digest_hex.encode())
                return True
            except Exception:
                return False
        if not isinstance(signature_hex, str):
            return False  # compare_digest raises on non-str; a forgery must
            # count as invalid, not crash the verification path
        expect = _hmac.new(self._network_key, digest_hex.encode(),
                           hashlib.sha512).hexdigest()
        return _hmac.compare_digest(expect, signature_hex)

    # ------------------------------------------------------------------ send

    def make_envelope(self, payload: Any) -> dict:
        """Sign ``payload`` into a self-verifying envelope dict."""
        nonce = os.urandom(16).hex()
        digest = payload_digest(payload, self.id, nonce)
        return {
            "_secure": 1,
            "scheme": self.scheme,
            "payload": payload,
            "signer": self.id,
            "nonce": nonce,
            "hash": digest,
            "signature": self._sign(digest),
            "public_key": self._public_hex,
        }

    def send_to_nodes_signed(self, payload: Any, exclude=None,
                             compression: str = "none") -> None:
        """Broadcast a signed payload (JSON-representable data)."""
        self.send_to_nodes(self.make_envelope(payload), exclude=exclude,
                           compression=compression)

    def send_to_node_signed(self, peer, payload: Any,
                            compression: str = "none") -> None:
        """Unicast a signed payload to one connected peer."""
        self.send_to_node(peer, self.make_envelope(payload),
                          compression=compression)

    # --------------------------------------------------------------- receive

    def check_envelope(self, envelope: Any) -> Optional[str]:
        """Return None when the envelope verifies, else the failure reason.

        Verification = scheme match, hash integrity, signature validity
        under the embedded key, and signer-to-key binding (pinned or TOFU).
        A verified first-seen signer gets its key pinned here.
        """
        if not isinstance(envelope, dict) or envelope.get("_secure") != 1:
            return "not a secure envelope"
        for field in ("payload", "signer", "nonce", "hash", "signature"):
            if field not in envelope:
                return f"missing field {field!r}"
        if not isinstance(envelope["nonce"], str):
            # A list nonce is JSON-legal and would verify, but an unhashable
            # nonce must read as invalid, not blow up the replay tracking.
            return "nonce must be a string"
        scheme = envelope.get("scheme", "ed25519")
        if scheme != self.scheme:
            return f"scheme mismatch: envelope {scheme}, local {self.scheme}"
        digest = payload_digest(envelope["payload"], envelope["signer"],
                                envelope["nonce"])
        if digest != envelope["hash"]:
            return "hash mismatch"
        public_key = envelope.get("public_key", "")
        if not self._verify(digest, envelope["signature"], public_key):
            return "bad signature"
        signer = str(envelope["signer"])
        if self.scheme == "ed25519":
            pinned = self.known_keys.get(signer)
            if pinned is None:
                self._tofu_pin(signer, public_key)  # trust-on-first-use
            elif pinned != public_key:
                return f"key mismatch for signer {signer!r}"
        if not self._record_nonce(signer, envelope["nonce"]):
            return "replayed nonce"
        return None

    def _record_nonce(self, signer: str, nonce) -> bool:
        """Track ``nonce`` in the signer's replay window; False if seen.

        Signer entries are evicted least-recently-ACTIVE (each accepted
        message refreshes its signer), so flushing a victim's window by
        minting fresh signers requires outpacing the victim's own traffic
        — plain FIFO would let one burst of new ids evict an active signer
        and reopen replays of its captured envelopes.
        """
        entry = self._seen_nonces.pop(signer, None)
        if entry is None:
            while len(self._seen_nonces) >= self.max_tracked_signers:
                self._seen_nonces.pop(next(iter(self._seen_nonces)))
            entry = (set(), collections.deque())
        self._seen_nonces[signer] = entry  # (re)insert at the fresh end
        seen, order = entry
        if nonce in seen:
            return False
        seen.add(nonce)
        order.append(nonce)
        if len(order) > self.replay_window:
            seen.discard(order.popleft())
        return True

    def node_message(self, node, data) -> None:
        """Route envelopes through verification; pass other traffic through."""
        if isinstance(data, dict) and data.get("_secure") == 1:
            reason = self.check_envelope(data)
            if reason is None:
                self.secure_message(node, data["payload"], data["signer"],
                                    data.get("public_key", ""))
            else:
                self.message_count_rerr += 1
                self.secure_message_invalid(node, data, reason)
            return
        super().node_message(node, data)

    # ----------------------------------------------------------------- hooks

    def secure_message(self, node, payload, signer_id: str,
                       public_key_hex: str = "") -> None:
        """A verified signed message arrived. Override me."""
        self.debug_print(f"secure_message from {signer_id}: {payload}")
        self.event_log.record("secure_message", peer_id=getattr(node, "id", None),
                              data=payload)
        if self.callback is not None:
            self.callback("secure_message", self, node, payload)

    def secure_message_invalid(self, node, envelope, reason: str) -> None:
        """A signed message failed verification. Override me."""
        self.debug_print(f"secure_message_invalid: {reason}")
        self.event_log.record("secure_message_invalid",
                              peer_id=getattr(node, "id", None), data=reason)
        if self.callback is not None:
            self.callback("secure_message_invalid", self, node, envelope)
