"""Chandy–Lamport consistent global snapshots over the sockets backend.

The sim backend can checkpoint because its whole world is one array
state (sim/checkpoint.py); a REAL overlay of reference-style nodes has
no such luxury — state is spread across processes with messages in
flight, and naively asking every node to dump state records a cut that
never existed (a message counted at neither or both ends). The
reference has no answer at all — no persistence of any kind [ref:
p2pnetwork/node.py:85-90, ids regenerated per run; SURVEY.md section 5
"Checkpoint / resume — Absent"]. Chandy–Lamport (1985) is THE classic
fix, and its one hard requirement — FIFO channels — is exactly what the
per-connection TCP stream already provides.

:class:`SnapshotNode` extends :class:`~p2pnetwork_tpu.node.Node` with
the marker discipline:

- ``take_snapshot()``: record local state (:meth:`capture_state`), then
  send a marker on every channel and start recording every incoming
  channel;
- first marker for a snapshot id: same local start, and that channel's
  state is empty;
- later markers: stop recording that channel — the recorded messages
  ARE the channel state of the cut;
- markers received on every channel: the local snapshot is complete —
  :meth:`snapshot_complete` fires (and dispatches the
  ``"snapshot_complete"`` callback event, extending the reference's
  ten-event vocabulary).

Atomicity contract: everything runs on the node's single event loop
(the same design that removed the reference's cross-thread races,
node.py module docstring). ``capture_state`` is invoked on the loop
thread, back-to-back with marker emission, so application state
mutated only from event handlers — or from closures passed to
:meth:`post` — is captured atomically with respect to the cut. State
mutated from foreign threads is outside the contract (the mutation and
its sends could straddle the markers); route such writes through
``post``.

Application traffic moves to the :meth:`app_message` hook — override it
instead of ``node_message`` (which now intercepts markers); its default
preserves the reference behavior (debug print + ``"node_message"``
callback dispatch). A peer that dies mid-snapshot releases its channel
with whatever was recorded (the cut degrades like the network did,
instead of hanging). Concurrent snapshots with distinct ids interleave
safely — recording is tracked per id, the standard generalization.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

#: Payload key marking a snapshot marker frame. Dict payloads carrying it
#: are consumed by the algorithm and never reach ``app_message``.
MARKER_KEY = "_cl_marker"


class _Pending:
    """Book-keeping for one in-progress snapshot id on one node."""

    __slots__ = ("state", "recording", "channels")

    def __init__(self, state: Any):
        self.state = state
        self.recording: Dict[NodeConnection, list] = {}
        self.channels: Dict[str, list] = {}


class SnapshotNode(Node):
    """A :class:`Node` that can take part in consistent global snapshots.

    Override :meth:`capture_state` to say what your node's state IS, and
    :meth:`app_message` for application traffic. Any participant may call
    :meth:`take_snapshot`; every reachable participant completes its local
    snapshot, retrievable via :meth:`get_snapshot` / :meth:`wait_snapshot`.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Only mutated on the event loop; read via get_snapshot anywhere.
        self._snap_pending: Dict[str, _Pending] = {}
        self._snap_done: Dict[str, dict] = {}
        # Completion events, keyed by sid; created lazily from ANY thread
        # (setdefault under the GIL) — waiting must work even before the
        # posted _local_start has run, or before this node has ever heard
        # of the id (a remote participant awaiting the initiator's cut).
        self._snap_events: Dict[str, Any] = {}  # sid -> seam event

    # ------------------------------------------------------------ app API

    def capture_state(self) -> Any:
        """The node state the snapshot should record; called on the event
        loop at the cut instant. Default: the reference's counters."""
        return {
            "message_count_send": self.message_count_send,
            "message_count_recv": self.message_count_recv,
        }

    def app_message(self, node: NodeConnection, data) -> None:
        """Application traffic (everything that is not a marker). Default
        keeps reference behavior: debug print + callback dispatch."""
        super().node_message(node, data)

    def post(self, fn: Callable[[], None]) -> None:
        """Run ``fn()`` on the node's event loop — the supported way to
        mutate snapshot-visible state (and send its messages) from outside
        an event handler, keeping the mutation atomic w.r.t. the cut."""
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")
        loop.call_soon_threadsafe(fn)

    def take_snapshot(self, snapshot_id: Optional[str] = None) -> str:
        """Initiate a global snapshot; returns its id immediately.

        Thread-safe (posts onto the loop). The local result lands in
        :meth:`get_snapshot`; remote participants each complete their own
        local snapshot under the same id."""
        sid = snapshot_id if snapshot_id is not None else uuid.uuid4().hex
        if sid in self._snap_done or sid in self._snap_pending:
            # A reused id would silently no-op (_local_start's idempotency
            # exists for duplicate MARKERS) and hand back the stale cut as
            # if fresh. Periodic callers: generate ids, or discard_snapshot
            # the old cut first.
            raise ValueError(f"snapshot id {sid!r} was already used")
        self.post(lambda: self._local_start(sid))
        return sid

    def get_snapshot(self, sid: str) -> Optional[dict]:
        """The completed local snapshot for ``sid``, or None if not done:
        ``{"id", "node_id", "state", "channels": {peer_id: [messages]}}``."""
        return self._snap_done.get(sid)

    def wait_snapshot(self, sid: str, timeout: Optional[float] = None
                      ) -> Optional[dict]:
        """Block the calling thread until ``sid`` completes locally (or
        ``timeout`` elapses — then returns None)."""
        self._snap_events.setdefault(sid, concurrency.event()).wait(timeout)
        return self.get_snapshot(sid)

    def discard_snapshot(self, sid: str) -> Optional[dict]:
        """Return the completed snapshot for ``sid`` (or None) and release
        its retained state. Completed cuts — recorded channel payloads
        included — are otherwise kept forever so late ``get_snapshot``
        readers work; a periodic checkpointer must discard each cut after
        consuming it or the retention is a slow leak."""
        snap = self._snap_done.get(sid)

        def _drop():
            self._snap_done.pop(sid, None)
            self._snap_events.pop(sid, None)

        self.post(_drop)
        return snap

    def snapshot_complete(self, snapshot: dict) -> None:
        """Local snapshot for one id is complete (markers arrived on every
        channel). Extension hook + ``"snapshot_complete"`` callback event."""
        self.debug_print(f"snapshot_complete: {snapshot['id']}")
        self._dispatch("snapshot_complete", None, snapshot)

    # ----------------------------------------------------- marker machine

    def _local_start(self, sid: str) -> None:
        """Record state, mark every channel, start recording — the atomic
        local cut (runs as one uninterrupted loop callback)."""
        if sid in self._snap_pending or sid in self._snap_done:
            return
        pend = _Pending(self.capture_state())
        for conn in self.all_nodes:
            pend.recording[conn] = []
        self._snap_pending[sid] = pend
        self.send_to_nodes({MARKER_KEY: sid})
        if not pend.recording:  # no peers: the cut is just local state
            self._finish(sid, pend)

    def _release_channel(self, pend: _Pending, node: NodeConnection) -> None:
        # extend, not assign: two connections can share a peer id (a
        # simultaneous mutual dial races the outbound duplicate guard), and
        # assignment would clobber the first channel's recorded messages —
        # losing them from the cut. The merged list is still the channel
        # state of the cut for that peer.
        pend.channels.setdefault(node.id, []).extend(pend.recording.pop(node))

    def _on_marker(self, node: NodeConnection, sid: str) -> None:
        self._local_start(sid)  # no-op if this id already started here
        pend = self._snap_pending.get(sid)
        if pend is None or node not in pend.recording:
            return  # duplicate marker, or a post-cut connection
        self._release_channel(pend, node)
        if not pend.recording:
            self._finish(sid, pend)

    def _finish(self, sid: str, pend: _Pending) -> None:
        snapshot = {
            "id": sid,
            "node_id": self.id,
            "state": pend.state,
            "channels": pend.channels,
        }
        self._snap_done[sid] = snapshot
        del self._snap_pending[sid]
        self._snap_events.setdefault(sid, concurrency.event()).set()
        self.snapshot_complete(snapshot)

    # ------------------------------------------------------ interceptions

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict) and MARKER_KEY in data:
            self._on_marker(node, data[MARKER_KEY])
            return
        # Pre-marker messages are the channel state of the cut.
        for pend in self._snap_pending.values():
            rec = pend.recording.get(node)
            if rec is not None:
                rec.append(data)
        self.app_message(node, data)

    def node_disconnected(self, node: NodeConnection) -> None:
        # A dead peer can never deliver its marker: release its channel
        # with what was recorded so the snapshot completes instead of
        # hanging — the cut reflects the failure, like the network does.
        for sid in list(self._snap_pending):
            pend = self._snap_pending[sid]
            if node in pend.recording:
                self._release_channel(pend, node)
                if not pend.recording:
                    self._finish(sid, pend)
        super().node_disconnected(node)
