"""JaxSimNode — the bridge between the Node extension API and the sim engine.

This is the north-star integration point (BASELINE.json): a ``Node``
subclass slotting into the same extend-or-callback seam as every other node,
whose "peers" are a simulated population in HBM instead of socket threads.
It is still a real sockets node — it binds a port, accepts connections, and
can broadcast to live peers — but its population-scale traffic happens as
batched graph propagation.

The semantic bridge, stated honestly (SURVEY.md section 7 "hard parts" 1):
socket peers deliver asynchronous per-message callbacks; the simulated
population advances in synchronous rounds. Events about the population
arrive through the standard ``node_message`` hook [ref: p2pnetwork/
node.py:334-338] with a :class:`SimPeer` stand-in as the connected node and
one dict per completed round — so existing callback-based applications
observe the simulation with no new API.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.sim import checkpoint as ckpt
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim.graph import Graph


class SimPeer:
    """Stand-in for ``NodeConnection`` representing the simulated population.

    Carries the connection surface events expose (``id``, ``host``, ``port``,
    ``info``, ``set_info/get_info`` [ref: nodeconnection.py:231-235]) so
    callbacks written against socket peers work unchanged. ``send`` is a
    debug no-op: messages enter the simulation through protocol state, not a
    socket."""

    def __init__(self, main_node: Node, n_nodes: int):
        self.main_node = main_node
        self.id = f"sim:{n_nodes}-nodes"
        self.host = "hbm"
        self.port = 0
        self.info: dict = {}

    def send(self, data, encoding_type=None, compression="none") -> None:
        self.main_node.debug_print(
            "SimPeer.send: the simulated population is driven by protocol "
            "state, not socket sends"
        )

    def stop(self) -> None:  # parity surface; nothing to stop
        pass

    def set_info(self, key: str, value: Any) -> None:
        self.info[key] = value

    def get_info(self, key: str) -> Any:
        return self.info[key]

    def __str__(self) -> str:
        return f"SimPeer({self.id})"

    __repr__ = __str__


class JaxSimNode(Node):
    """A ``Node`` whose population-scale peers live in HBM.

    Usage::

        node = JaxSimNode("127.0.0.1", 0, graph=g, protocol=Flood(source=0))
        node.start()                  # normal sockets lifecycle
        stats = node.run_rounds(10)   # 10 batched propagation rounds
        node.stop(); node.join()

    Pass ``mesh=jax.make_mesh(...)`` (or ``parallel.mesh.ring_mesh()``) to
    run the population on the MULTI-CHIP backend: same events, same
    stepping/churn/checkpoint methods, with the graph partitioned over the
    device ring (parallel/sharded.py) — the reference's whole API surface
    at the scale one chip cannot hold.

    Each completed round fires ``node_message`` with
    ``{"sim_round": r, **round_stats}``. ``sim_message_count`` accumulates
    the simulated message volume — the population-scale analog of
    ``message_count_send`` [ref: node.py:64-67]; the socket counters stay
    reserved for real socket traffic.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 graph: Optional[Graph] = None, protocol=None, seed: int = 0,
                 mesh=None, dynamic_edges: int = 0, rng: Optional[str] = None,
                 layout: str = "hybrid", adaptive_k: int = 0,
                 **node_kwargs):
        super().__init__(host, port, **node_kwargs)
        self.sim_graph: Optional[Graph] = None
        self.sim_protocol = None
        self.sim_state = None
        self.sim_round = 0
        self.sim_message_count = 0
        self.sim_peer: Optional[SimPeer] = None
        self.sim_mesh = None
        self.sim_sharded = None
        self._sim_rng: Optional[str] = None
        self._sim_key: Optional[jax.Array] = None
        self._sim_adaptive_k = 0
        self._churn_count = 0
        if graph is not None and protocol is not None:
            self.attach_simulation(graph, protocol, seed=seed, mesh=mesh,
                                   dynamic_edges=dynamic_edges, rng=rng,
                                   layout=layout, adaptive_k=adaptive_k)

    # ------------------------------------------------------------- plumbing

    def attach_simulation(self, graph: Graph, protocol, seed: int = 0,
                          mesh=None, dynamic_edges: int = 0,
                          rng: Optional[str] = None,
                          layout: str = "hybrid",
                          adaptive_k: int = 0) -> None:
        """Attach (or replace) the simulated population.

        ``mesh`` switches the node onto the multi-chip backend
        (parallel/sharded.py): the population is partitioned over the
        device ring and every stepping, churn, and checkpoint operation
        below drives the sharded representation — same Node event surface,
        same semantics, proven bit-exact against the engine in
        tests/test_sharded.py. On that backend ``sim_graph`` remains the
        PRISTINE attach-time construction (the seed for re-shards and
        checkpoint templates); the live topology is ``sim_sharded``, and
        backend-agnostic introspection goes through ``sim_node_alive``.
        ``dynamic_edges`` reserves runtime link capacity on the sharded
        graph; ``rng`` picks the sharded RNG mode ('exact' | 'tile' |
        'fold', default tile when aligned); ``layout`` picks the sharded
        edge layout — 'hybrid' (ring-decomposed diagonals + MXU remainder,
        the fast default), 'mxu', or 'segment' (BENCH.md has the measured
        ladder). All layouts are bit-exact. ``adaptive_k > 0`` additionally
        builds the sender-CSR view and runs Flood's ``run_until_coverage``
        through the frontier-adaptive loop (small-frontier rounds skip the
        ring; bit-identical results).
        """
        if layout not in ("hybrid", "mxu", "segment"):
            # Validate regardless of backend: a typo'd layout must not be
            # silently accepted just because no mesh is attached yet.
            raise ValueError(
                f"layout must be 'hybrid', 'mxu' or 'segment', got "
                f"{layout!r}"
            )
        if adaptive_k > 0:
            from p2pnetwork_tpu.models.flood import Flood as _Flood
            from p2pnetwork_tpu.models.hopdist import (
                HopDistance as _HopDistance,
            )

            # A silent no-op would be worse than an error: the flag only
            # drives the mesh backend's Flood/HopDistance loops.
            if mesh is None:
                raise ValueError(
                    "adaptive_k drives the mesh backend's coverage loop; "
                    "on the single-device backend use "
                    "protocol=AdaptiveFlood(...) on a source_csr=True graph"
                )
            if not isinstance(protocol, (_Flood, _HopDistance)):
                raise ValueError(
                    f"adaptive_k applies to Flood and HopDistance on the "
                    f"mesh backend; got {type(protocol).__name__}"
                )
        self.sim_graph = graph
        self.sim_protocol = protocol
        self._sim_key = jax.random.key(seed)
        self.sim_mesh = mesh
        self._sim_rng = rng
        self._sim_adaptive_k = adaptive_k
        if mesh is not None:
            from p2pnetwork_tpu.parallel import sharded

            sg = sharded.shard_graph(graph, mesh, mxu=layout == "mxu",
                                     hybrid=layout == "hybrid",
                                     source_csr=adaptive_k > 0)
            if dynamic_edges:
                sg = sharded.with_capacity(sg, dynamic_edges)
            self.sim_sharded = sg
            self.sim_state = sharded.init_state(sg, protocol, self._sim_key)
        else:
            self.sim_sharded = None
            self.sim_state = protocol.init(graph, self._sim_key)
        self.sim_round = 0
        self.sim_message_count = 0
        self._churn_count = 0
        self.sim_peer = SimPeer(self, graph.n_nodes)
        self.debug_print(
            f"attach_simulation: {graph.n_nodes} nodes / {graph.n_edges} edges, "
            f"protocol {type(protocol).__name__}"
            + (f", {mesh.devices.size}-device mesh" if mesh is not None else "")
        )

    def _require_sim(self):
        if self.sim_graph is None:
            raise RuntimeError("JaxSimNode: no simulation attached; call attach_simulation()")

    @property
    def sim_node_alive(self):
        """Liveness of the simulated population (bool, one entry per padded
        node) from whichever backend is active. On the mesh backend the
        live topology is ``sim_sharded`` — ``sim_graph`` stays the pristine
        attach-time construction (it seeds re-shards and checkpoint
        templates), so topology introspection must go through this
        property, not ``sim_graph.node_mask``."""
        self._require_sim()
        if self.sim_mesh is not None:
            return np.asarray(self.sim_sharded.node_mask).reshape(-1)
        return np.asarray(self.sim_graph.node_mask)

    # ------------------------------------------------------------- stepping

    def _run_rounds_sharded(self, rounds: int, seg_key):
        """Dispatch a run_rounds segment onto the sharded backend."""
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.models.gossip import Gossip
        from p2pnetwork_tpu.models.hopdist import HopDistance
        from p2pnetwork_tpu.models.pagerank import PageRank
        from p2pnetwork_tpu.models.pushsum import PushSum
        from p2pnetwork_tpu.models.sir import SIR
        from p2pnetwork_tpu.parallel import sharded

        sg, mesh, proto = self.sim_sharded, self.sim_mesh, self.sim_protocol
        if isinstance(proto, Flood):
            return sharded.flood(sg, mesh, proto.source, rounds,
                                 state0=self.sim_state, return_state=True)
        if isinstance(proto, SIR):
            return sharded.sir(sg, mesh, proto, seg_key, rounds,
                               rng=self._sim_rng, status0=self.sim_state)
        if isinstance(proto, Gossip):
            return sharded.gossip(sg, mesh, proto, seg_key, rounds,
                                  rng=self._sim_rng, values0=self.sim_state)
        if isinstance(proto, HopDistance):
            return sharded.hopdist(sg, mesh, proto, rounds,
                                   state0=self.sim_state)
        if isinstance(proto, PageRank):
            return sharded.pagerank(sg, mesh, proto, rounds,
                                    ranks0=self.sim_state)
        if isinstance(proto, PushSum):
            return sharded.pushsum(sg, mesh, proto, seg_key, rounds,
                                   state0=self.sim_state)
        raise ValueError(
            f"the sharded backend implements Flood, SIR, Gossip, "
            f"HopDistance, PageRank and PushSum; got {type(proto).__name__}"
        )

    def run_rounds(self, rounds: int) -> dict:
        """Advance the population ``rounds`` synchronous rounds.

        One compiled ``lax.scan`` on device; afterwards fires ``node_message``
        once per round (aggregate stats dict) through the standard event
        path. Returns the stacked stats as numpy arrays."""
        self._require_sim()
        # Per-segment key: deterministic in (seed, segment start).
        seg_key = jax.random.fold_in(self._sim_key, self.sim_round)
        if self.sim_mesh is not None:
            self.sim_state, stats = self._run_rounds_sharded(rounds, seg_key)
        else:
            self.sim_state, stats = engine.run_from(
                self.sim_graph, self.sim_protocol, self.sim_state, seg_key,
                rounds,
            )
        host_stats = {k: np.asarray(v) for k, v in stats.items()}
        for r in range(rounds):
            round_stats = {k: host_stats[k][r].item() for k in host_stats}  # graftlint: ignore[host-sync-in-loop] -- host_stats is numpy (one transfer above the loop)
            if "messages" in round_stats:
                self.sim_message_count += int(round_stats["messages"])  # graftlint: ignore[host-sync-in-loop] -- already a Python scalar
            self.sim_round += 1
            self.node_message(self.sim_peer, {"sim_round": self.sim_round, **round_stats})
        return host_stats

    def _finish_run(self, out: dict) -> dict:
        """Shared tail of the run-to-* loops: host summary, round/message
        accounting, and the single summary ``node_message`` event."""
        summary = {k: np.asarray(v).item() for k, v in out.items()}
        self.sim_round += int(summary["rounds"])
        self.sim_message_count += int(summary["messages"])
        self.node_message(self.sim_peer, {"sim_run": True, **summary})
        return summary

    def run_until_coverage(self, coverage_target: float = 0.99,
                           max_rounds: int = 1024) -> dict:
        """Device-side run-to-coverage continuing from the current state
        (no per-round events; one summary ``node_message`` at the end).
        On the mesh backend this is the multi-chip while_loop
        (sharded.flood_until_coverage / sharded.sir_until_coverage)."""
        self._require_sim()
        seg_key = jax.random.fold_in(self._sim_key, self.sim_round)
        if self.sim_mesh is not None:
            from p2pnetwork_tpu.models.flood import Flood
            from p2pnetwork_tpu.models.hopdist import HopDistance
            from p2pnetwork_tpu.models.sir import SIR
            from p2pnetwork_tpu.parallel import sharded

            if isinstance(self.sim_protocol, Flood):
                self.sim_state, out = sharded.flood_until_coverage(
                    self.sim_sharded, self.sim_mesh, self.sim_protocol.source,
                    coverage_target=coverage_target, max_rounds=max_rounds,
                    state0=self.sim_state, return_state=True,
                    adaptive_k=self._sim_adaptive_k,
                )
            elif isinstance(self.sim_protocol, HopDistance):
                self.sim_state, out = sharded.hopdist_until_coverage(
                    self.sim_sharded, self.sim_mesh, self.sim_protocol,
                    coverage_target=coverage_target, max_rounds=max_rounds,
                    state0=self.sim_state,
                    adaptive_k=self._sim_adaptive_k,
                )
            elif isinstance(self.sim_protocol, SIR):
                self.sim_state, out = sharded.sir_until_coverage(
                    self.sim_sharded, self.sim_mesh, self.sim_protocol,
                    seg_key, coverage_target=coverage_target,
                    max_rounds=max_rounds, rng=self._sim_rng,
                    status0=self.sim_state,
                )
            else:
                raise ValueError(
                    "run_until_coverage on the sharded backend implements "
                    "Flood, SIR and HopDistance; the protocol must expose "
                    "a coverage stat"
                )
        else:
            self.sim_state, out = engine.run_until_coverage_from(
                self.sim_graph, self.sim_protocol, self.sim_state, seg_key,
                coverage_target=coverage_target, max_rounds=max_rounds,
            )
        return self._finish_run(out)

    def run_until_converged(self, stat: str, threshold: float,
                            max_rounds: int = 1024) -> dict:
        """Device-side run-to-convergence continuing from the current state
        (engine.run_until_converged): advance until ``stats[stat]`` drops
        below ``threshold`` — PageRank to a residual, PushSum/Gossip to a
        variance. On the mesh backend, PageRank (stat='residual') and
        PushSum (stat='variance') ride the multi-chip loops
        (sharded.pagerank_until_residual / pushsum_until_variance)."""
        self._require_sim()
        seg_key = jax.random.fold_in(self._sim_key, self.sim_round)
        if self.sim_mesh is not None:
            from p2pnetwork_tpu.models.pagerank import PageRank
            from p2pnetwork_tpu.models.pushsum import PushSum
            from p2pnetwork_tpu.parallel import sharded

            if isinstance(self.sim_protocol, PageRank) and stat == "residual":
                self.sim_state, out = sharded.pagerank_until_residual(
                    self.sim_sharded, self.sim_mesh, self.sim_protocol,
                    tol=threshold, max_rounds=max_rounds,
                    ranks0=self.sim_state,
                )
            elif isinstance(self.sim_protocol, PushSum) and stat == "variance":
                self.sim_state, out = sharded.pushsum_until_variance(
                    self.sim_sharded, self.sim_mesh, self.sim_protocol,
                    seg_key, tol=threshold, max_rounds=max_rounds,
                    state0=self.sim_state,
                )
            else:
                raise ValueError(
                    "run_until_converged on the sharded backend implements "
                    "PageRank (stat='residual') and PushSum "
                    "(stat='variance'); run other protocols on the "
                    "single-device backend or step them with run_rounds"
                )
        else:
            self.sim_state, out = engine.run_until_converged(
                self.sim_graph, self.sim_protocol, seg_key, stat=stat,
                threshold=threshold, max_rounds=max_rounds,
                state0=self.sim_state,
            )
        return self._finish_run(out)

    # ------------------------------------------------------------- topology

    def _sim_topology_event(self, change: str) -> None:
        """Population topology changes surface through ``node_message``
        (like round stats) — SimPeer is not in the socket registries, so
        the inbound/outbound disconnect dispatcher correctly ignores it."""
        mask = (self.sim_sharded.node_mask if self.sim_mesh is not None
                else self.sim_graph.node_mask)
        alive = int(np.asarray(mask.sum()))
        self.node_message(
            self.sim_peer, {"sim_topology": change, "alive_nodes": alive}
        )

    def fail_sim_nodes(self, node_ids) -> None:
        """Fail-stop simulated peers (sim/failures.py, or the sharded
        mirror on the mesh backend) — the population analog of peers
        dropping [ref: node.py:307-319]."""
        self._require_sim()
        if self.sim_mesh is not None:
            from p2pnetwork_tpu.parallel import sharded

            self.sim_sharded = sharded.fail_nodes(self.sim_sharded, node_ids)
        else:
            from p2pnetwork_tpu.sim import failures

            self.sim_graph = failures.fail_nodes(self.sim_graph, node_ids)
        self._sim_topology_event("fail_nodes")

    def inject_sim_churn(self, frac: float, seed: Optional[int] = None) -> None:
        """Randomly fail ``frac`` of the live simulated population.

        Each call draws fresh randomness by default (an internal counter
        folds into the node's sim key) — a fixed seed would re-select the
        same, already-dead nodes on every call after the first. Pass
        ``seed`` only to reproduce one specific churn event.
        """
        self._require_sim()
        if seed is not None:
            key = jax.random.key(seed)
        else:
            self._churn_count += 1
            key = jax.random.fold_in(
                jax.random.fold_in(self._sim_key, 0x0C0C), self._churn_count
            )
        if self.sim_mesh is not None:
            from p2pnetwork_tpu.parallel import sharded

            self.sim_sharded = sharded.random_node_failures(
                self.sim_sharded, key, frac
            )
        else:
            from p2pnetwork_tpu.sim import failures

            self.sim_graph = failures.random_node_failures(
                self.sim_graph, key, frac
            )
        self._sim_topology_event("churn")

    def connect_sim_nodes(self, senders, receivers) -> None:
        """Add links between simulated peers at runtime (sim/topology.py,
        or the sharded mirror; the population analog of
        ``connect_with_node`` [ref: node.py:122]). Needs dynamic capacity
        (``topology.with_capacity`` / ``dynamic_edges=`` at attach)."""
        self._require_sim()
        if self.sim_mesh is not None:
            from p2pnetwork_tpu.parallel import sharded

            self.sim_sharded = sharded.connect(
                self.sim_sharded, senders, receivers
            )
        else:
            from p2pnetwork_tpu.sim import topology

            self.sim_graph = topology.connect(self.sim_graph, senders, receivers)
        self._sim_topology_event("connect")

    # ----------------------------------------------------------- checkpoint

    def save_checkpoint(self, path: str) -> None:
        """Persist protocol state, PRNG key, round/message counters, AND the
        topology mutation state (failed nodes, cut edges, runtime links,
        churn counter) — see sim/checkpoint.py. Topology is state here for
        the same reason the reference keeps its peer lists on the node
        object [ref: p2pnetwork/node.py:46-52]: a restored run must see the
        network as it was, not as it was built."""
        self._require_sim()
        payload = {
            "protocol": self.sim_state,
            "topology": self._topology_state(),
            "churn_count": np.int64(self._churn_count),
        }
        ckpt.save(path, payload, self._sim_key, self.sim_round,
                  self.sim_message_count)

    def _topology_state(self):
        if self.sim_mesh is not None:
            from p2pnetwork_tpu.parallel import sharded

            return sharded.topology_state(self.sim_sharded)
        return ckpt.topology_state(self.sim_graph)

    def load_checkpoint(self, path: str) -> None:
        """Restore a checkpoint taken from a node with the same (pristine)
        graph construction and protocol.

        The attached graph supplies the static arrays; the checkpoint's
        topology state is re-applied onto it, so a run that failed nodes or
        grew links resumes on exactly the damaged/grown network — and the
        churn counter is restored, so the next ``inject_sim_churn()`` draws
        fresh randomness instead of replaying pre-checkpoint draws."""
        self._require_sim()
        if self.sim_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from p2pnetwork_tpu.parallel import sharded

            template = {
                "protocol": sharded.init_state(
                    self.sim_sharded, self.sim_protocol, jax.random.key(0)
                ),
                "topology": sharded.topology_state(self.sim_sharded),
                "churn_count": np.int64(0),
            }
            payload, key, rnd, msgs = ckpt.load(path, template)
            new_sharded = sharded.apply_topology_state(
                self.sim_sharded, payload["topology"]
            )
            shard = NamedSharding(self.sim_mesh,
                                  P(self.sim_mesh.axis_names[0]))
            replicated = NamedSharding(self.sim_mesh, P())

            def put(x):
                # Scalar leaves (HopDistance's round counter) replicate —
                # a rank-1 spec on a 0-d array is invalid.
                arr = jax.numpy.asarray(x)
                return jax.device_put(arr,
                                      shard if arr.ndim >= 1 else replicated)

            self.sim_state = jax.tree.map(put, payload["protocol"])
            self.sim_sharded = new_sharded
        else:
            proto_template = self.sim_protocol.init(self.sim_graph,
                                                    jax.random.key(0))
            payload, key, rnd, msgs = ckpt.load_node_payload(
                path, self.sim_graph, proto_template
            )
            # Validate everything (including topology shapes) BEFORE
            # mutating the node — a rejected load must leave it untouched,
            # not holding a foreign protocol state against its own graph.
            new_graph = ckpt.apply_topology_state(self.sim_graph,
                                                  payload["topology"])
            # Device-put the protocol leaves (npz gives numpy): raw numpy
            # would re-pay host->device transfer on every jit dispatch.
            self.sim_state = jax.tree.map(jax.numpy.asarray,
                                          payload["protocol"])
            self.sim_graph = new_graph
        self._sim_key = key
        self.sim_round = rnd
        self.sim_message_count = msgs
        self._churn_count = int(payload["churn_count"])
