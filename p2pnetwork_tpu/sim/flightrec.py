"""graftscope flight recorder: a device-side per-round ring buffer.

The run-to-* loops are single compiled programs with zero host
synchronization per round — exactly what makes them fast, and exactly
what makes "why was round 37 slow/stuck" unanswerable after the fact:
the packed summary (utils/accum.py) carries per-RUN aggregates only.
This module adds the flight-recorder middle ground: a bounded
``f32[capacity, K]`` ring of per-round records accumulated INSIDE the
compiled ``lax.while_loop``/``lax.scan`` carries (one
``dynamic_update_slice`` row write per round — no host sync, no shape
growth with round count) and transferred once per run alongside the
packed summary. Off by default; when enabled the ring is an explicit
donated carry leaf (the graftaudit donation audit covers the
recorder-enabled loops), and run RESULTS are bit-identical to
recorder-off runs — the recorder only ever writes its own ring.

Column schema (``REC_COLS``, one row per executed round):

- ``round``     — 1-based global round index of this call (the wrap
  key: with ``rounds > capacity`` the ring keeps the LAST ``capacity``
  rounds; :func:`trim` re-orders oldest-first on the host).
- ``occupancy`` — frontier occupancy (ops/frontier.py ints; the batch
  loops record the union frontier's occupancy).
- ``new``       — messages sent this round.
- ``total``     — running message total (two-limb fold, f32 view — the
  EXACT total stays in the packed summary; past 2^24 this column is an
  approximation by construction).
- ``coverage``  — the coverage numerator's loop-native form: the
  engine's single-message loops record the coverage FRACTION (their
  stat), the sharded flood loop the psum'd covered-node COUNT, the
  batch loops the masked seen-count total over lanes.
- ``active_lanes`` — running lanes (1 while a single-message loop
  runs; the batch loops' admitted-and-unfinished count).
- ``ici_bytes`` — the per-round ICI byte estimate of the loop's comm
  backend (commviz census model; 0 on single-chip loops). Static per
  compiled program — recorded in-row so a ring row is self-describing
  after export.

Everything here is shape-static: ``FlightRecorder`` is a frozen
hashable config (a jit static argument), the ring an ordinary array
leaf.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["REC_COLS", "FlightRecorder", "FlightRecord", "write_row",
           "trim"]

#: Column order of one per-round record (module docstring).
REC_COLS = ("round", "occupancy", "new", "total", "coverage",
            "active_lanes", "ici_bytes")


@dataclasses.dataclass(frozen=True)
class FlightRecorder:
    """Static flight-recorder configuration: hashable, so the
    recorder-enabled loop variants key jit caches on it like any other
    static hyperparameter. ``capacity`` bounds the ring — a run longer
    than it keeps the last ``capacity`` rounds (oldest rows
    overwritten; ``FlightRecord.dropped`` reports how many)."""

    capacity: int = 256

    def __post_init__(self):
        if self.capacity < 1:
            raise ValueError(
                f"flight-recorder capacity must be >= 1, got "
                f"{self.capacity}")

    def init(self) -> jax.Array:
        """A fresh zeroed ring — built EAGERLY by the entry points so
        the ring is a real donated input of the recorder-enabled loops
        (a ring born inside the jit would be invisible to the donation
        audit and double-buffer in HBM for the run)."""
        return jnp.zeros((self.capacity, len(REC_COLS)), dtype=jnp.float32)


def write_row(ring: jax.Array, round_index, *, occupancy, new, total,
              coverage, active_lanes, ici_bytes) -> jax.Array:
    """Write one per-round record at ``round_index % capacity``
    (jittable; ``round_index`` is the 0-based count of rounds executed
    BEFORE this one — the row's ``round`` column is 1-based). All
    values are cast to f32 — this is telemetry, the exact counters stay
    in the packed summary."""
    row = jnp.stack([
        jnp.float32(round_index + 1),
        jnp.float32(occupancy),
        jnp.float32(new),
        jnp.float32(total),
        jnp.float32(coverage),
        jnp.float32(active_lanes),
        jnp.float32(ici_bytes),
    ])
    slot = jnp.mod(jnp.int32(round_index), ring.shape[0])
    return jax.lax.dynamic_update_slice(ring, row[None, :],
                                        (slot, jnp.int32(0)))


def total_f32(hi, lo) -> jax.Array:
    """The two-limb message accumulator as one f32 (the ``total``
    column's view — approximate past 2^24 by construction)."""
    return (hi.astype(jnp.float32) * jnp.float32(2.0 ** 32)
            + lo.astype(jnp.float32))


@dataclasses.dataclass(frozen=True)
class FlightRecord:
    """Host-side view of one run's ring: rows oldest-first, trimmed to
    the rounds actually executed. ``dropped`` counts rounds whose rows
    were overwritten (``rounds > capacity``)."""

    rows: np.ndarray        # f32[min(rounds, capacity), len(REC_COLS)]
    rounds: int             # rounds executed this call
    capacity: int
    dropped: int

    @property
    def columns(self):
        return REC_COLS

    def column(self, name: str) -> np.ndarray:
        return self.rows[:, REC_COLS.index(name)]

    def as_dict(self) -> dict:
        """JSON-able form (artifacts, /trace tooling): column lists
        keyed by name plus the wrap accounting."""
        return {
            "rounds": self.rounds,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "columns": {name: self.column(name).tolist()
                        for name in REC_COLS},
        }


def trim(ring: np.ndarray, rounds: int) -> FlightRecord:
    """Re-order a transferred ring oldest-first and trim to the rounds
    executed (host-side inverse of the in-loop wrap)."""
    ring = np.asarray(ring)
    capacity = int(ring.shape[0])
    rounds = int(rounds)
    if rounds <= capacity:
        rows = np.array(ring[:rounds])
        dropped = 0
    else:
        start = rounds % capacity
        rows = np.roll(ring, -start, axis=0)
        dropped = rounds - capacity
    return FlightRecord(rows=rows, rounds=rounds, capacity=capacity,
                        dropped=dropped)
