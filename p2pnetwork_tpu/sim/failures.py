"""Fault injection: node and edge failures as first-class, testable inputs.

The reference's failure story is reactive — a send/recv error tears down
that connection [ref: nodeconnection.py:123-126, :201-204] and reconnect
policy decides retry-vs-giveup [ref: node.py:203-225]. There is no way to
*inject* failures. In the sim backend failure is a feature (SURVEY.md
section 5 "Failure detection"): killing nodes or links flips mask bits in
device arrays — same shapes, no recompile, the next round simply routes
around (or into) the damage. That makes partition tolerance, epidemic
die-out, and coverage-under-churn testable properties (SURVEY.md section 7
hard part 4: capacity-padded adjacency + active masks).

Every function returns a NEW Graph with every carried representation
(COO masks, degrees, neighbor table, blocked kernel layout, hybrid
diagonals) consistently re-masked, entirely device-side. Failures are
fail-stop and one-way on the returned copy — keep the original Graph
object around to "restore" (it is immutable and untouched).
"""

from __future__ import annotations

import dataclasses

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.sim.graph import Graph


def _count_injected(kind: str, ids=None) -> None:
    """Injected failures are experiment inputs; counting them in the same
    registry as the protocol's own metrics lets a churn run report "N
    failures injected, coverage held at X" from one snapshot. For the
    deterministic APIs the increment is the entity count; for traced ids or
    the random_* draws (whose realized count lives on device) it is the
    injection-call count, under a distinct ``<kind>_draw`` label."""
    n = 1
    if ids is not None:
        try:
            n = int(np.asarray(ids).size)
        except Exception:
            n = 1  # traced ids: count the injection, not the entities
    telemetry.default_registry().counter(
        "sim_injected_failures_total",
        "Failures injected into sim graphs, by kind (entity counts for "
        "deterministic kinds, draw counts for *_draw).",
        ("kind",)).labels(kind).inc(n)


def _check_ids_in_range(ids, bound: int, what: str) -> None:
    """Host-side bounds check (JAX scatter silently drops out-of-bounds
    indices — a typo'd id would silently leave the graph undamaged).
    Skipped for traced ids, which cannot be inspected."""
    try:
        arr = np.asarray(ids)
    except Exception:
        return
    if arr.size and (arr.min() < 0 or arr.max() >= bound):
        raise ValueError(f"{what} id out of range [0, {bound})")


def _degrees(graph: Graph, edge_mask: jax.Array,
             dyn_mask: Optional[jax.Array] = None):
    """(in_degree, out_degree) recomputed from surviving-edge masks —
    static COO plus the dynamic region (sim/topology.py), if present."""
    live = edge_mask.astype(jnp.int32)
    in_degree = jax.ops.segment_sum(
        live, graph.receivers,
        num_segments=graph.n_nodes_padded, indices_are_sorted=True,
    )
    out_degree = jnp.zeros(graph.n_nodes_padded, jnp.int32).at[
        graph.senders].add(live)
    if dyn_mask is not None:
        dlive = dyn_mask.astype(jnp.int32)
        in_degree = in_degree.at[graph.dyn_receivers].add(dlive)
        out_degree = out_degree.at[graph.dyn_senders].add(dlive)
    return in_degree, out_degree


def _remask_blocked(blocked, node_alive: jax.Array):
    """Re-mask a BlockedEdges for the given per-node liveness."""
    if blocked is None:
        return None
    nb, w = blocked.src.shape
    block_base = jnp.arange(nb, dtype=jnp.int32)[:, None] * blocked.block
    global_dst = jnp.minimum(block_base + blocked.local_dst,
                             node_alive.shape[0] - 1)
    mask = blocked.mask & node_alive[blocked.src] & node_alive[global_dst]
    return dataclasses.replace(blocked, mask=mask)


def _remask_hybrid(hybrid, node_alive: jax.Array):
    """Re-mask a HybridEdges: diagonal masks need both endpoints alive."""
    if hybrid is None:
        return None
    core = node_alive[: hybrid.n]
    if len(hybrid.offsets):
        # mask[d, v] needs v alive and (v + off) % n alive.
        src_alive = jnp.stack(
            [jnp.roll(core, -off) for off in hybrid.offsets], axis=0
        )
        masks = hybrid.masks & core[None, :] & src_alive
    else:
        masks = hybrid.masks
    return dataclasses.replace(
        hybrid,
        masks=masks,
        remainder=_remask_blocked(hybrid.remainder, node_alive),
    )


def _remask_skew_nodes(skew, node_alive: jax.Array):
    if skew is None:
        return None
    from p2pnetwork_tpu.ops import skew as SK

    return SK.remask_nodes(skew, node_alive)


def with_node_liveness(graph: Graph, node_alive: jax.Array) -> Graph:
    """Apply a liveness mask (bool[N_pad]; False = failed) to ``graph``.

    An edge is active iff it was active and both endpoints live; degrees
    are recomputed from the surviving edges; the neighbor table and the
    blocked/hybrid kernel layouts are re-masked in place (no host rebuild,
    no recompile — shapes are unchanged).
    """
    node_mask = graph.node_mask & node_alive
    edge_mask = (
        graph.edge_mask & node_mask[graph.senders] & node_mask[graph.receivers]
    )
    dyn_mask = graph.dyn_mask
    if dyn_mask is not None:
        # Dynamic links (sim/topology.py) die with either endpoint too.
        dyn_mask = (
            dyn_mask
            & node_mask[graph.dyn_senders]
            & node_mask[graph.dyn_receivers]
        )
    in_degree, out_degree = _degrees(graph, edge_mask, dyn_mask)
    neighbors = graph.neighbors
    neighbor_mask = graph.neighbor_mask
    if neighbor_mask is not None:
        neighbor_mask = (
            neighbor_mask & node_mask[:, None] & node_mask[neighbors]
        )
    return dataclasses.replace(
        graph,
        node_mask=node_mask,
        edge_mask=edge_mask,
        dyn_mask=dyn_mask,
        in_degree=in_degree,
        out_degree=out_degree,
        neighbor_mask=neighbor_mask,
        blocked=_remask_blocked(graph.blocked, node_mask),
        hybrid=_remask_hybrid(graph.hybrid, node_mask),
        skew=_remask_skew_nodes(graph.skew, node_mask),
    )


def fail_nodes(graph: Graph, node_ids) -> Graph:
    """Fail-stop the given node ids (crashed peers: they neither send nor
    receive; their edges die with them)."""
    _check_ids_in_range(node_ids, graph.n_nodes_padded, "node")
    _count_injected("node", node_ids)
    ids = jnp.asarray(node_ids, dtype=jnp.int32)
    alive = jnp.ones(graph.n_nodes_padded, dtype=bool).at[ids].set(False)
    return with_node_liveness(graph, alive)


def mark_unresponsive(graph: Graph, node_ids) -> Graph:
    """Flip ``node_mask`` for the given ids WITHOUT re-masking edges,
    degrees, or the neighbor table — the crashed-but-still-configured
    view a failure DETECTOR needs: survivors still hold the dead peer in
    their tables (the reference keeps the socket in ``nodes_inbound``
    until a timeout fires [ref: nodeconnection.py]) and must discover the
    silence by probing. For every other protocol use :func:`fail_nodes`,
    which models the loss consistently (a mark-only graph still counts
    the dead peer's table slots as live links)."""
    _check_ids_in_range(node_ids, graph.n_nodes_padded, "node")
    _count_injected("node_unresponsive", node_ids)
    ids = jnp.asarray(node_ids, dtype=jnp.int32)
    node_mask = graph.node_mask.at[ids].set(False)
    return dataclasses.replace(graph, node_mask=node_mask)


def with_edge_liveness(graph: Graph, edge_alive: jax.Array) -> Graph:
    """Apply a per-edge liveness mask (bool[E_pad]; False = cut link).

    Directed: cutting one direction of an undirected pair leaves the other
    alive. Degrees are recomputed; a complete neighbor table is re-masked
    exactly (slot ``s`` of row ``v`` is COO edge ``starts[v] + s``, so the
    edge mask scatters straight into the table); a width-capped table has
    lost its slot->edge mapping and is dropped. Graphs carrying the
    blocked/hybrid kernel layouts must use node failures or rebuild —
    their edge order differs and a silent partial update would be wrong.
    """
    if graph.blocked is not None or graph.hybrid is not None:
        raise ValueError(
            "edge-level failures on a graph with blocked/hybrid "
            "representations would desynchronize them; use fail_nodes / "
            "with_node_liveness, or rebuild from the surviving edge list"
        )
    edge_mask = graph.edge_mask & edge_alive
    in_degree, out_degree = _degrees(graph, edge_mask, graph.dyn_mask)
    neighbors = graph.neighbors
    neighbor_mask = graph.neighbor_mask
    if neighbor_mask is not None:
        if graph.neighbors_complete:
            starts = jnp.searchsorted(
                graph.receivers, jnp.arange(graph.n_nodes_padded)
            )
            width = neighbors.shape[1]
            take = starts[:, None] + jnp.arange(width)[None, :]
            take = jnp.minimum(take, graph.n_edges_padded - 1)
            neighbor_mask = neighbor_mask & edge_mask[take]
        else:
            # Capped rows are a random edge subset; the slot->edge map is
            # gone, so the table cannot be re-masked exactly.
            neighbors = None
            neighbor_mask = None
    skew = graph.skew
    if skew is not None:
        # The two-level table keeps its slot->edge map (SkewTable.start),
        # so edge cuts re-mask it exactly, device-side.
        from p2pnetwork_tpu.ops import skew as SK

        skew = SK.remask_edges(skew, edge_mask, graph.n_edges_padded)
    return dataclasses.replace(
        graph,
        edge_mask=edge_mask,
        in_degree=in_degree,
        out_degree=out_degree,
        neighbors=neighbors,
        neighbor_mask=neighbor_mask,
        skew=skew,
    )


def fail_edges(graph: Graph, edge_ids) -> Graph:
    """Cut specific links (indices into the edge arrays)."""
    _check_ids_in_range(edge_ids, graph.n_edges_padded, "edge")
    _count_injected("edge", edge_ids)
    ids = jnp.asarray(edge_ids, dtype=jnp.int32)
    alive = jnp.ones(graph.n_edges_padded, dtype=bool).at[ids].set(False)
    return with_edge_liveness(graph, alive)


def revive_nodes(graph: Graph, node_ids, original: Graph) -> Graph:
    """Un-fail the given node ids, restoring their ``original`` wiring.

    The inverse of :func:`kill_nodes` on the sockets chaos plane
    (chaos/plane.py). A failed graph has already zeroed the dead nodes'
    edges, so reviving needs the pre-failure ``original`` to know what to
    restore: the result is ``original`` re-masked to (previously live ∪
    revived) nodes. Edge-level cuts applied after ``original`` was taken
    are forgotten — revive node-level damage before link-level damage, or
    reapply the cuts."""
    _check_ids_in_range(node_ids, graph.n_nodes_padded, "node")
    _count_injected("node_revive", node_ids)
    ids = jnp.asarray(node_ids, dtype=jnp.int32)
    revived = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[ids].set(True)
    alive = graph.node_mask | (revived & original.node_mask)
    return with_node_liveness(original, alive)


def partition(graph: Graph, groups) -> Graph:
    """Cut every edge crossing between the node-id ``groups`` — static COO
    and dynamic-region links (sim/topology.py) both, so not a byte leaks
    across the split (nodes in no group are unconstrained) — the sim
    mirror of ``ChaosPlane.partition``. Keep the original graph around to
    heal. Uses edge-level liveness, so blocked/hybrid kernel graphs must
    use node failures or rebuild (see :func:`with_edge_liveness`)."""
    side = np.full(graph.n_nodes_padded, -1, dtype=np.int64)
    for gi, group in enumerate(groups):
        ids = np.asarray(group, dtype=np.int64)  # graftlint: ignore[host-sync-in-loop] -- groups are host-side id lists, never device arrays
        _check_ids_in_range(ids, graph.n_nodes_padded, "node")
        side[ids] = gi
    _count_injected("partition")

    def _crossing(senders, receivers):
        s, r = np.asarray(senders), np.asarray(receivers)
        return (side[s] >= 0) & (side[r] >= 0) & (side[s] != side[r])

    gp = with_edge_liveness(
        graph, jnp.asarray(~_crossing(graph.senders, graph.receivers)))
    if graph.dyn_mask is not None:
        # with_edge_liveness passes the dynamic region through untouched;
        # a runtime-added link spanning the split must die too.
        dyn_mask = gp.dyn_mask & jnp.asarray(
            ~_crossing(graph.dyn_senders, graph.dyn_receivers))
        in_degree, out_degree = _degrees(gp, gp.edge_mask, dyn_mask)
        gp = dataclasses.replace(gp, dyn_mask=dyn_mask,
                                 in_degree=in_degree, out_degree=out_degree)
    return gp


#: Name-for-name aliases shared with the sockets chaos plane
#: (chaos/plane.py): one failure-scenario vocabulary on both backends.
kill_nodes = fail_nodes
cut_links = fail_edges


def preempt(run, at_round: int):
    """Arm a deterministic preemption of a supervised run harness.

    The other fault kinds in this module damage the *simulated network*;
    ``preempt`` damages the *run itself* — the machine it executes on is
    reclaimed, exactly what this environment's wedged device tunnels and
    driver timeouts keep doing for real. ``run`` is a
    :class:`~p2pnetwork_tpu.supervise.runner.SupervisedRun` (anything with
    ``arm_preemption``); at the first chunk boundary at or past
    ``at_round`` it raises
    :class:`~p2pnetwork_tpu.supervise.runner.Preempted` *before* taking
    the checkpoint due there, so the durable trail ends where a real
    SIGKILL's would. Reviving is calling the same ``run_*`` entry again —
    it resumes from the last durable checkpoint, and the revived run's
    final state is bit-identical to an uninterrupted one (the supervised
    determinism contract). Counted as
    ``sim_injected_failures_total{kind="preempt"}`` like every other
    injected fault. Returns ``run`` for chaining."""
    _count_injected("preempt")
    run.arm_preemption(int(at_round))
    return run


def random_node_failures(graph: Graph, key: jax.Array, frac: float) -> Graph:
    """Fail each live node independently with probability ``frac`` —
    the churn model for coverage-under-failure experiments."""
    _count_injected("node_draw")
    alive = ~(
        jax.random.bernoulli(key, frac, (graph.n_nodes_padded,))
        & graph.node_mask
    )
    return with_node_liveness(graph, alive)


def random_edge_failures(graph: Graph, key: jax.Array, frac: float) -> Graph:
    """Cut each live directed edge independently with probability ``frac``."""
    _count_injected("edge_draw")
    cut = jax.random.bernoulli(key, frac, (graph.n_edges_padded,))
    return with_edge_liveness(graph, ~cut)
