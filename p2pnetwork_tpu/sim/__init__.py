"""Simulation backend: populations of peers as device arrays.

- ``graph``: static-shape peer graphs + generators, incremental
  ``GraphDelta``/``apply_delta`` builds
- ``engine``: compiled round execution (scan / while_loop)
- ``simnode``: JaxSimNode, the Node-API bridge
- ``checkpoint``: save/resume of simulation state
- ``failures``: fault injection (node/edge liveness masks)
- ``topology``: runtime joins/connects (capacity-padded dynamic edges)
- ``layout``: IO-aware build-time node reordering (degree / RCM)
- ``layoutcache``: content-addressed persistence of built layouts
"""

from p2pnetwork_tpu.utils.jax_env import apply_platform_env as _apply_platform_env

_apply_platform_env()

from p2pnetwork_tpu.sim import (  # noqa: E402
    checkpoint,
    engine,
    failures,
    graph,
    layout,
    layoutcache,
    topology,
)
from p2pnetwork_tpu.sim.graph import Graph, GraphDelta
from p2pnetwork_tpu.sim.simnode import JaxSimNode, SimPeer

__all__ = [
    "Graph", "GraphDelta", "JaxSimNode", "SimPeer", "checkpoint", "engine",
    "failures", "graph", "layout", "layoutcache", "topology",
]
