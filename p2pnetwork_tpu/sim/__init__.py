"""Simulation backend: populations of peers as device arrays.

- ``graph``: static-shape peer graphs + generators
- ``engine``: compiled round execution (scan / while_loop)
- ``simnode``: JaxSimNode, the Node-API bridge
- ``checkpoint``: save/resume of simulation state
- ``failures``: fault injection (node/edge liveness masks)
- ``topology``: runtime joins/connects (capacity-padded dynamic edges)
"""

from p2pnetwork_tpu.utils.jax_env import apply_platform_env as _apply_platform_env

_apply_platform_env()

from p2pnetwork_tpu.sim import (  # noqa: E402
    checkpoint,
    engine,
    failures,
    graph,
    topology,
)
from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.sim.simnode import JaxSimNode, SimPeer

__all__ = [
    "Graph", "JaxSimNode", "SimPeer", "checkpoint", "engine", "failures",
    "graph", "topology",
]
