"""Peer-graph representation for the simulation backend.

The reference keeps the peer topology as Python lists of live socket threads
(`nodes_inbound`/`nodes_outbound` [ref: p2pnetwork/node.py:46-49]) and
"broadcast" is a sequential Python loop over them [ref: node.py:110-112].
Here the whole population's topology is device-resident arrays with static
shapes, so one propagation round is one batched XLA computation (SURVEY.md
section 7 step 2):

- **COO edges sorted by receiver** (``senders``/``receivers``/``edge_mask``),
  the general representation, feeding segment reductions;
- an optional **padded neighbor table** (``neighbors``/``neighbor_mask``,
  shape ``[N, max_degree]``), the gather-friendly representation that maps
  well onto TPU vector loads for quasi-regular graphs (WS/ER).

Static shapes everywhere: node count and edge count are padded (capacity
padding + active masks), which is how dynamic topology (connect/disconnect,
SURVEY.md section 7 "hard parts" 4) fits XLA's compile-once model — adding or
dropping a peer flips mask bits, it does not recompile.

Generators (Erdős–Rényi, Barabási–Albert, Watts–Strogatz, ring, complete)
run host-side in numpy: graph construction is one-off setup, the hot path is
propagation.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu import native, telemetry


def _round_up(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


# ------------------------------------------------------ build-phase timing
#
# Host-side graph construction is the scale bottleneck (BENCH_r02:
# graph_build_s 40x the 1M headline), so where the build time goes —
# dedup, radix sort, neighbor tables, CSR, kernel layouts, reordering —
# is first-class telemetry: per-phase wall seconds accumulate into the
# registry (`sim_graph_build_seconds_total{phase}`) and the most recent
# build's breakdown is readable via :func:`last_build_phases` (bench.py
# publishes it as ``build_phases`` in BENCH_TELEMETRY.json).

_phases_tls = threading.local()


def _phases_dict() -> dict:
    d = getattr(_phases_tls, "d", None)
    if d is None:
        d = _phases_tls.d = {}
    return d


def _reset_phases() -> None:
    """Start a fresh per-build phase record, folding in any dedup time a
    generator accumulated just before calling :func:`from_edges` (the
    generators dedup BEFORE building, so the pending value belongs to the
    build that follows)."""
    d = _phases_dict()
    d.clear()
    pending = getattr(_phases_tls, "pending_dedup", 0.0)
    if pending:
        d["dedup_s"] = round(pending, 6)
        _phases_tls.pending_dedup = 0.0


def _note_dedup(seconds: float) -> None:
    """Accumulate generator-side dedup/sample time for the NEXT build."""
    _phases_tls.pending_dedup = getattr(
        _phases_tls, "pending_dedup", 0.0) + seconds
    telemetry.default_registry().counter(
        "sim_graph_build_seconds_total",
        "Host-side graph construction wall seconds by build phase.",
        ("phase",)).labels("dedup").inc(seconds)


def last_build_phases() -> dict:
    """Per-phase wall-second breakdown of the most recent graph build
    (``from_edges`` or ``apply_delta``) on this thread."""
    return dict(_phases_dict())


def _note_phase(name: str, seconds: float) -> None:
    d = _phases_dict()
    d[name + "_s"] = round(d.get(name + "_s", 0.0) + seconds, 6)
    telemetry.default_registry().counter(
        "sim_graph_build_seconds_total",
        "Host-side graph construction wall seconds by build phase.",
        ("phase",)).labels(name).inc(seconds)


class _phase:
    """Context manager: time one build phase into the thread-local record
    and the ``sim_graph_build_seconds_total{phase}`` counter."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _note_phase(self.name, time.perf_counter() - self._t0)
        return False


def _padded_row_fill(starts: np.ndarray, counts: np.ndarray, width: int):
    """Vectorized ragged-rows-to-padded-matrix fill.

    Row ``i`` owns ``counts[i]`` consecutive items beginning at ``starts[i]``
    in some flat pool array. Returns ``(take, valid)`` of shape
    ``[rows, width]``: flat pool indices (0 where padded) and the padding
    mask. Shared by the neighbor-table and blocked-edge builders — one fancy
    index instead of a per-row Python loop.
    """
    # int32 halves the temporaries for the (rows x width) tables, but only
    # when every index fits — beyond 2^31 edge slots int32 would wrap to
    # negative fancy indices and silently build a wrong table.
    big = starts.size and int(starts.max()) + width >= 2**31
    dtype = np.int64 if big else np.int32
    slot = np.arange(width, dtype=dtype)
    starts = starts.astype(dtype, copy=False)
    counts = counts.astype(dtype, copy=False)
    valid = slot[None, :] < counts[:, None]
    take = np.where(valid, starts[:, None] + slot[None, :], dtype(0))
    return take, valid


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Graph:
    """A static-shape peer graph on device.

    An edge ``(senders[e], receivers[e])`` means messages flow sender ->
    receiver (undirected topologies store both directions). Edges are sorted
    by receiver so segment reductions can assume sorted segment ids.
    """

    senders: jax.Array  # i32[E_pad]
    receivers: jax.Array  # i32[E_pad], non-decreasing
    edge_mask: jax.Array  # bool[E_pad]
    node_mask: jax.Array  # bool[N_pad]
    in_degree: jax.Array  # i32[N_pad]  (active incoming edges per node)
    out_degree: jax.Array  # i32[N_pad] (active outgoing edges per node)
    # Gather representation: incoming neighbor list per node, or None.
    neighbors: Optional[jax.Array]  # i32[N_pad, max_degree]
    neighbor_mask: Optional[jax.Array]  # bool[N_pad, max_degree]
    # Static metadata.
    n_nodes: int = dataclasses.field(metadata=dict(static=True))
    n_edges: int = dataclasses.field(metadata=dict(static=True))
    # Whether the neighbor table holds EVERY incoming edge. False when
    # from_edges(max_degree=...) capped the width — then each over-degree
    # node's row is a uniform random subset of its in-edges (fine for
    # Gossip's random partner draw, wrong for exact OR/sum aggregation,
    # which must not silently drop edges).
    neighbors_complete: bool = dataclasses.field(
        default=True, metadata=dict(static=True)
    )
    # The from_edges(max_degree=...) cap as given, or None. Distinct from
    # the table width: a cap WIDER than the build-time max in-degree
    # leaves the table complete at the narrower width, but must still
    # bound it when churn (apply_delta) or consolidation later grows a
    # hub past it. None on graphs from old checkpoints (pre-cap format).
    max_degree_cap: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )
    # The from_edges(edge_pad_multiple=) value, recorded so apply_delta
    # re-pads to the SAME multiple — a base built with a coarse multiple
    # to hold shapes stable across churn must not snap back to 128 (and
    # recompile every jitted consumer) on the first delta.
    edge_pad_multiple: int = dataclasses.field(
        default=128, metadata=dict(static=True)
    )
    # Widest contiguous run of one receiver id among the LIVE (unpadded)
    # COO entries — i.e. the max static in-degree at build. The padding
    # tail (receiver n_pad-1) can extend that id's physical run far wider;
    # consumers must mask with edge_mask, as the membership probe does.
    # Static so runtime probes (sim/topology.py connect) can scan a
    # [B, max_in_span] window instead of comparing against all E edges.
    max_in_span: int = dataclasses.field(default=0, metadata=dict(static=True))
    # Optional blocked-edge representation (ops/blocked.py) feeding the
    # matmul/Pallas aggregation paths; attach via with_blocked().
    blocked: Optional[object] = None
    # Optional diagonal+remainder representation (ops/diag.py) feeding the
    # gather-free "hybrid" aggregation path; attach via with_hybrid().
    hybrid: Optional[object] = None
    # Optional two-level (virtual-row) neighbor table (ops/skew.py) feeding
    # the hub-proof "skew" aggregation path for degree-skewed families;
    # attach via with_skew_table() or from_edges(skew_table=True).
    skew: Optional[object] = None
    # Dynamic edge region (sim/topology.py): unsorted COO slots for links
    # added at runtime; folded into every aggregation method.
    dyn_senders: Optional[jax.Array] = None  # i32[K]
    dyn_receivers: Optional[jax.Array] = None  # i32[K]
    dyn_mask: Optional[jax.Array] = None  # bool[K]
    # Source-CSR (out-edge) view for frontier-sparse traversal
    # (models/adaptive_flood.py): edge ids permuted sender-sorted —
    # ``src_eid[src_offsets[v] : src_offsets[v+1]]`` are node ``v``'s
    # out-edges as indices into senders/receivers/edge_mask. Row extents
    # are BUILD-time; runtime edge liveness is re-checked through
    # ``edge_mask[src_eid[...]]``, so failures need no rebuild. Attach via
    # ``from_edges(source_csr=True)`` or :meth:`with_source_csr`.
    src_eid: Optional[jax.Array] = None  # i32[E_pad]
    src_offsets: Optional[jax.Array] = None  # i32[N_pad + 1]
    #: Widest build-time out-edge row (static slot width for the sparse
    #: frontier gather), 0 when no CSR is attached.
    max_out_span: int = dataclasses.field(default=0, metadata=dict(static=True))
    # Optional per-edge weights (latency / link cost — f32[E_pad], aligned
    # with senders/receivers; padded slots masked like everything else).
    # None means the unweighted graph every propagate treats as cost-1.
    # Attach via ``from_edges(weights=...)`` or :meth:`with_weights`.
    edge_weight: Optional[jax.Array] = None  # f32[E_pad]
    # Gather-layout view of edge_weight ([N_pad, max_degree], aligned with
    # the neighbor table rows); built alongside the table when weights are
    # present so propagate_min_plus's gather lowering has aligned costs.
    neighbor_weight: Optional[jax.Array] = None  # f32[N_pad, max_degree]
    # IO-aware build-time node relabeling (sim/layout.py, from_edges
    # ``reorder=``): ``layout_perm[old] = new`` and ``layout_inv[new] =
    # old`` over the padded id space, or None when the graph keeps caller
    # order. Every runtime id (protocol sources, failures, deltas) speaks
    # the RELABELED space; map per-node results back with
    # ``layout.to_original_order``.
    layout_perm: Optional[jax.Array] = None  # i32[N_pad]
    layout_inv: Optional[jax.Array] = None  # i32[N_pad]

    @property
    def n_nodes_padded(self) -> int:
        return self.node_mask.shape[0]

    @property
    def n_edges_padded(self) -> int:
        return self.senders.shape[0]

    @property
    def max_degree(self) -> int:
        return 0 if self.neighbors is None else self.neighbors.shape[1]

    def with_blocked(self, block: int = 128) -> "Graph":
        """Return a copy carrying the blocked-edge representation used by the
        ``"blocked"`` (XLA einsum) and ``"pallas"`` aggregation methods."""
        from p2pnetwork_tpu.ops.blocked import build_blocked

        return dataclasses.replace(self, blocked=build_blocked(self, block))

    def with_source_csr(self) -> "Graph":
        """Return a copy carrying the source-CSR out-edge view used by the
        frontier-sparse rounds of models/adaptive_flood.py. Pulls the edge
        arrays to host; prefer ``from_edges(source_csr=True)`` at
        construction for large graphs."""
        senders = np.asarray(self.senders)
        emask = np.asarray(self.edge_mask)
        eid, offsets, span = _build_source_csr(
            senders, emask, self.n_nodes_padded, self.n_edges_padded
        )
        return dataclasses.replace(
            self, src_eid=jnp.asarray(eid), src_offsets=jnp.asarray(offsets),
            max_out_span=span,
        )

    def gather_row_slots(self, start, end, width: int):
        """``[K, width]`` out-edge slot gather through the source-CSR view:
        ``(eid, valid)`` for slots ``start[i] + j`` while ``< end[i]``.

        This is THE place the ``e_pad - 1`` padding sentinel of
        ``_build_source_csr`` is masked — that slot can name a LIVE edge
        (whenever the edge count is an exact pad multiple), so every
        consumer of the gathered ``eid`` must AND with the returned
        ``valid`` (and its own liveness masks) before trusting it. Used
        by the frontier-sparse wave rounds (models/adaptive_flood.py)
        and the walker cohort (models/walk.py)."""
        slot = start[:, None] + jnp.arange(width)[None, :]
        valid = slot < end[:, None]
        eid = self.src_eid[jnp.where(valid, slot, self.n_edges_padded - 1)]
        return eid, valid

    def with_weights(self, weights) -> "Graph":
        """Return a copy carrying per-edge costs.

        ``weights`` is either a callable ``(senders, receivers) -> f32``
        evaluated on the padded edge arrays (deterministic link-cost
        models, e.g. id-hash latency), or an array aligned with the
        receiver-sorted padded edge slots. When a complete neighbor table
        exists its aligned weight view is rebuilt host-side (the same
        one-off cost as ``with_hybrid``); a width-capped table cannot be
        re-aligned post hoc — pass ``weights=`` to ``from_edges`` instead.
        """
        if callable(weights):
            w = jnp.asarray(weights(self.senders, self.receivers),
                            dtype=jnp.float32)
        else:
            w = jnp.asarray(weights, dtype=jnp.float32)
        if w.shape != self.senders.shape:
            raise ValueError("weights must align with the padded edge slots")
        nw = None
        if self.neighbors is not None:
            if not self.neighbors_complete:
                raise ValueError(
                    "cannot re-align weights to a width-capped neighbor "
                    "table; rebuild via from_edges(weights=..., "
                    "max_degree=...)"
                )
            # Complete-table rows are the contiguous receiver runs of the
            # BUILD-time (unpadded) edge list, in order — recompute the
            # slot -> edge map the builder used. Build-time extents, not
            # in_degree: liveness re-masking since build changes degrees
            # but not slot layout (failures re-mask neighbor_mask, which
            # still guards every consumer of these values).
            rh = np.asarray(self.receivers)[: self.n_edges]
            ids = np.arange(self.n_nodes_padded)
            starts = np.searchsorted(rh, ids)
            counts = np.searchsorted(rh, ids, side="right") - starts
            width = self.neighbors.shape[1]
            take, valid = _padded_row_fill(
                starts, np.minimum(counts, width), width)
            wh = np.asarray(w)
            nw = jnp.asarray(np.where(
                valid, wh[np.minimum(take, max(self.n_edges - 1, 0))], 0.0
            ).astype(np.float32))
        sk = self.skew
        if sk is not None:
            # The virtual rows keep their COO slot map (SkewTable
            # .edge_slots), so the aligned weight view is one gather.
            sk = dataclasses.replace(
                sk,
                weight=jnp.where(
                    sk.mask, w[sk.edge_slots(self.n_edges_padded)], 0.0))
        return dataclasses.replace(self, edge_weight=w, neighbor_weight=nw,
                                   skew=sk)

    def with_skew_table(self, width: int = 0) -> "Graph":
        """Return a copy carrying the two-level (virtual-row) neighbor
        table used by the ``"skew"`` aggregation method — fixed-width row
        slices a hub cannot widen, combined by a per-row sorted segment
        reduction (ops/skew.py). ``width=0`` picks the width from the
        degree histogram. Pulls edge arrays to host; prefer
        ``from_edges(skew_table=True)`` at construction for large
        graphs."""
        from p2pnetwork_tpu.ops.skew import build_skew

        return dataclasses.replace(self, skew=build_skew(self, width))

    def apply_delta(self, delta: "GraphDelta", *,
                    edge_pad_multiple: Optional[int] = None,
                    donate: bool = False) -> "Graph":
        """Apply an add/remove edge batch incrementally — see
        :func:`apply_delta` (O(delta + touched rows) host work instead of
        a from-scratch rebuild, bit-identical results; ``donate=True``
        updates the neighbor table in place, invalidating this graph's
        copy)."""
        return apply_delta(self, delta, edge_pad_multiple=edge_pad_multiple,
                           donate=donate)

    def grow(self, n_new_nodes: int, *,
             node_capacity: Optional[int] = None) -> "Graph":
        """Grow the overlay by ``n_new_nodes`` fresh node ids — see
        :func:`grow` (amortized geometric capacity repad; bit-identical
        to a from-scratch :func:`from_edges` at the grown capacity)."""
        return grow(self, n_new_nodes, node_capacity=node_capacity)

    def with_hybrid(self, block: int = 512, max_diags: int = 64) -> "Graph":
        """Return a copy carrying the diagonal+remainder representation used
        by the ``"hybrid"`` aggregation method — circular-shift passes for
        the graph's dominant diagonals (gather-free), the Pallas kernel for
        the unstructured rest (ops/diag.py)."""
        from p2pnetwork_tpu.ops.diag import build_hybrid

        return dataclasses.replace(
            self, hybrid=build_hybrid(self, block, max_diags)
        )


def _build_source_csr(senders: np.ndarray, edge_mask: np.ndarray,
                      n_pad: int, e_pad: int):
    """Sender-sorted edge-id permutation + row offsets (host-side).

    Only edges active in ``edge_mask`` enter rows. Padding slots of
    ``src_eid`` hold ``e_pad - 1`` merely to stay in bounds — that edge CAN
    be live (whenever the edge count is an exact pad multiple), so
    consumers must mask out-of-row slots themselves before trusting the
    gathered edge (models/adaptive_flood.py's ``svalid``)."""
    from p2pnetwork_tpu import native

    active = np.flatnonzero(edge_mask).astype(np.int32)
    # Radix sort (native/graphcore.cpp, numpy fallback) — the same sorter
    # the receiver sort uses; np.argsort doubles the host cost at 100M
    # edges.
    _, sorted_eids = native.sort_pairs(senders[active], active)
    eid = np.full(e_pad, e_pad - 1, dtype=np.int32)
    eid[: active.size] = sorted_eids
    counts = np.bincount(senders[active], minlength=n_pad).astype(np.int32)
    offsets = np.zeros(n_pad + 1, dtype=np.int32)
    np.cumsum(counts, out=offsets[1:])
    span = int(counts.max()) if active.size else 0
    return eid, offsets, span


# ------------------------------------------------------- incremental builds


def _as_edge_array(x, dtype=np.int32) -> np.ndarray:
    return (np.zeros(0, dtype=dtype) if x is None
            else np.asarray(x, dtype=dtype).reshape(-1))


class EdgeEndpointError(ValueError):
    """A delta edge names a node id outside ``[0, n_nodes)``.

    Raised at :func:`apply_delta` / :func:`grow` entry, BEFORE any array
    is touched — an out-of-range id would otherwise surface as an index
    error or a silent scatter into capacity padding depending on which
    derived view met it first. ``pairs`` carries up to 16 offending
    ``(sender, receiver)`` tuples and ``n_nodes`` the valid id bound.
    Subclasses :class:`ValueError` (and keeps the historical
    "edge endpoint out of range" message prefix) so existing handlers
    keep working.
    """

    def __init__(self, pairs, n_nodes: int):
        self.pairs = [(int(s), int(r)) for s, r in pairs]
        self.n_nodes = int(n_nodes)
        shown = ", ".join(f"({s}, {r})" for s, r in self.pairs[:5])
        more = ("" if len(self.pairs) <= 5
                else f", +{len(self.pairs) - 5} more")
        super().__init__(
            f"edge endpoint out of range: edge(s) name a node id outside "
            f"[0, {self.n_nodes}) as (sender, receiver): {shown}{more}")


def _check_endpoints(senders: np.ndarray, receivers: np.ndarray,
                     n_nodes: int) -> None:
    """Raise :class:`EdgeEndpointError` for any edge naming an id outside
    ``[0, n_nodes)``."""
    if not senders.size:
        return
    bad = ((senders < 0) | (senders >= n_nodes)
           | (receivers < 0) | (receivers >= n_nodes))
    if bad.any():
        idx = np.flatnonzero(bad)[:16]
        raise EdgeEndpointError(
            list(zip(senders[idx], receivers[idx])), n_nodes)


@dataclasses.dataclass(frozen=True)
class GraphDelta:
    """A host-side add/remove edge batch for :func:`apply_delta`.

    Directed edges, like :func:`from_edges` — for the usual undirected
    overlay semantics build via :meth:`undirected`, which stores both
    directions of every pair. ``add_weights`` is required exactly when the
    target graph carries ``edge_weight``. Removals name (sender, receiver)
    pairs; every named pair must match at least one live edge (removing an
    absent edge is an error, not a no-op), and removal drops ALL live
    copies of the pair.
    """

    add_senders: Optional[np.ndarray] = None  # i32[A]
    add_receivers: Optional[np.ndarray] = None  # i32[A]
    add_weights: Optional[np.ndarray] = None  # f32[A]
    remove_senders: Optional[np.ndarray] = None  # i32[R]
    remove_receivers: Optional[np.ndarray] = None  # i32[R]

    def __post_init__(self):
        set_ = object.__setattr__  # frozen dataclass
        set_(self, "add_senders", _as_edge_array(self.add_senders))
        set_(self, "add_receivers",
             _as_edge_array(self.add_receivers))
        set_(self, "remove_senders",
             _as_edge_array(self.remove_senders))
        set_(self, "remove_receivers",
             _as_edge_array(self.remove_receivers))
        if self.add_weights is not None:
            set_(self, "add_weights",
                 np.asarray(self.add_weights, dtype=np.float32).reshape(-1))
            if self.add_weights.shape != self.add_senders.shape:
                raise ValueError("add_weights must align with add_senders")
        if self.add_senders.shape != self.add_receivers.shape:
            raise ValueError("add_senders/add_receivers shape mismatch")
        if self.remove_senders.shape != self.remove_receivers.shape:
            raise ValueError(
                "remove_senders/remove_receivers shape mismatch")

    @classmethod
    def undirected(cls, add_senders=None, add_receivers=None,
                   add_weights=None, remove_senders=None,
                   remove_receivers=None) -> "GraphDelta":
        """Both directions of every pair — the reference's TCP-connection
        semantic, matching what the generators store."""
        a_s = _as_edge_array(add_senders)
        a_r = _as_edge_array(add_receivers)
        r_s = _as_edge_array(remove_senders)
        r_r = _as_edge_array(remove_receivers)
        a_w = None
        if add_weights is not None:
            w = np.asarray(add_weights, dtype=np.float32).reshape(-1)
            a_w = np.concatenate([w, w])
        return cls(
            add_senders=np.concatenate([a_s, a_r]),
            add_receivers=np.concatenate([a_r, a_s]),
            add_weights=a_w,
            remove_senders=np.concatenate([r_s, r_r]),
            remove_receivers=np.concatenate([r_r, r_s]),
        )

    @property
    def n_adds(self) -> int:
        return int(self.add_senders.size)

    @property
    def n_removes(self) -> int:
        return int(self.remove_senders.size)


def _pow2_pad(n: int) -> int:
    """Next power of two — the shape bucket for the donated scatters, so a
    churn storm whose batch sizes vary only compiles log2(N) variants
    instead of one per distinct delta size (the retrace hazard
    analysis/retrace_guard exists to catch)."""
    return 1 << max(int(n) - 1, 0).bit_length()


def _pad_repeat_last(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad axis 0 to ``n`` by repeating the last entry — safe filler for
    scatters whose duplicate indices carry identical values."""
    if arr.shape[0] == n:
        return arr
    reps = np.repeat(arr[-1:], n - arr.shape[0], axis=0)
    return np.concatenate([arr, reps])


@functools.partial(jax.jit, donate_argnames=("arr",))
def _scatter_add_donating(arr, idx, deltas):
    """Donated scatter-add: the delta path's in-place degree update —
    O(delta) writes into the existing buffer instead of an O(N) copy.
    The donor graph's degree buffer is invalidated."""
    return arr.at[idx].add(deltas)


@functools.partial(jax.jit, donate_argnames=("table",))
def _scatter_rows_donating(table, rows, vals):
    """Donated row scatter: the delta path's in-place neighbor-table
    update. Donation lets XLA write the touched rows into the EXISTING
    buffer — O(touched) instead of an O(N x width) copy, which is the
    difference between a delta apply and a rebuild at 10M-node table
    sizes. The donor graph's table buffer is invalidated (engine-style
    donation contract)."""
    return table.at[rows].set(vals)


def _delta_neighbor_tables(graph: Graph, out_r, out_s, w_unpadded,
                           static_in, touched, width_old, pristine,
                           donate=False):
    """The neighbor-table piece of :func:`apply_delta`: copy the base
    table, recompute only the touched rows (plus every width-capped row —
    their shared subsample RNG stream is global) — bit-identical to the
    table :func:`from_edges` builds from the merged edge list. With
    ``donate=True`` (and an unchanged width on a pristine base) the
    touched rows scatter into the base table IN PLACE — no copy at
    all."""
    e_new = out_r.size
    n_pad = graph.n_nodes_padded
    true_width = int(static_in.max()) if e_new else 0
    cap = graph.max_degree_cap
    if cap is None and not graph.neighbors_complete:
        # Old-checkpoint graphs predate the recorded cap; an incomplete
        # table's width IS the cap (it bit at build).
        cap = width_old
    if cap is None:
        complete = True
        width = max(true_width, 1)
    else:
        complete = cap >= true_width
        width = max(min(true_width, cap), 1)
    weighted = graph.neighbor_weight is not None
    in_place = donate and pristine and width == width_old
    if not pristine:
        # Liveness-re-masked base (edges dropped by failures): copied rows
        # would keep their holes while a rebuild compacts them — recompute
        # everything.
        touched = np.arange(n_pad, dtype=np.int32)
    if not complete or (not graph.neighbors_complete):
        # Any over-width row's subsample keys come from ONE rng stream over
        # all capped rows, so every capped row recomputes whenever a cap is
        # in play — still O(capped edges), never O(E).
        capped_rows = np.flatnonzero(static_in > width)
        touched = np.union1d(touched, capped_rows)
    # int32 rows keep searchsorted from promoting (and re-copying) the
    # million-element receiver array to int64 per call.
    rows = np.asarray(touched, dtype=np.int32)

    vals = valid = wvals = None
    if rows.size:
        starts = np.searchsorted(out_r, rows)
        ends = np.searchsorted(out_r, rows, side="right")
        deg = ends - starts
        take, valid = _padded_row_fill(starts, np.minimum(deg, width), width)
        capped_local = np.nonzero(deg > width)[0]
        if capped_local.size:
            # The exact from_edges subsample, restricted to the capped rows
            # (which are all present in `rows`, ascending): same rng seed,
            # same draw order, same ranking — bit-identical keep sets.
            cap_rng = np.random.default_rng(0)
            degc = deg[capped_local]
            cap_edge = np.repeat(capped_local, degc)
            offs = np.arange(cap_edge.size) - np.repeat(
                np.cumsum(degc) - degc, degc)
            edge_idx = starts[cap_edge] + offs
            keys = cap_rng.random(edge_idx.size)
            order = np.lexsort((keys, cap_edge))
            rank = np.empty_like(offs)
            rank[order] = offs
            kept = rank < width
            resort = np.lexsort((edge_idx[kept], cap_edge[kept]))
            take[capped_local] = edge_idx[kept][resort].reshape(
                capped_local.size, width)
        pool = out_s if e_new else np.zeros(1, dtype=np.int32)
        take_safe = np.minimum(take, max(e_new - 1, 0))
        vals = np.where(valid, pool[take_safe], 0).astype(np.int32)
        if weighted:
            wpool = w_unpadded if e_new else np.zeros(1, dtype=np.float32)
            wvals = np.where(valid, wpool[take_safe], 0.0).astype(np.float32)

    if in_place:
        # Donated scatter: the touched rows land in the base buffers with
        # no table-sized copy — the donor graph's table is invalidated.
        if rows.size:
            # Bucket the scatter shape (pad by repeating the last row —
            # duplicate indices carry identical values, so the write is
            # idempotent) to bound recompiles across varying batch sizes.
            b = _pow2_pad(rows.size)
            rows_j = jnp.asarray(_pad_repeat_last(rows, b))
            nb = _scatter_rows_donating(graph.neighbors, rows_j,
                                        _pad_repeat_last(vals, b))
            nbm = _scatter_rows_donating(graph.neighbor_mask, rows_j,
                                         _pad_repeat_last(valid, b))
            nw = (_scatter_rows_donating(graph.neighbor_weight, rows_j,
                                         _pad_repeat_last(wvals, b))
                  if weighted else None)
        else:  # nothing touched: the table is exactly the base table
            nb, nbm = graph.neighbors, graph.neighbor_mask
            nw = graph.neighbor_weight
        return nb, nbm, nw, complete

    nw = None
    if width == width_old and pristine:
        nb = np.array(graph.neighbors)  # writable copies
        nbm = np.array(graph.neighbor_mask)
        if weighted:
            nw = np.array(graph.neighbor_weight)
    elif pristine:
        c = min(width, width_old)
        nb = np.zeros((n_pad, width), dtype=np.int32)
        nbm = np.zeros((n_pad, width), dtype=bool)
        nb[:, :c] = np.asarray(graph.neighbors)[:, :c]
        nbm[:, :c] = np.asarray(graph.neighbor_mask)[:, :c]
        if weighted:
            nw = np.zeros((n_pad, width), dtype=np.float32)
            nw[:, :c] = np.asarray(graph.neighbor_weight)[:, :c]
    else:
        nb = np.zeros((n_pad, width), dtype=np.int32)
        nbm = np.zeros((n_pad, width), dtype=bool)
        if weighted:
            nw = np.zeros((n_pad, width), dtype=np.float32)
    if rows.size:
        nb[rows] = vals
        nbm[rows] = valid
        if weighted:
            nw[rows] = wvals
    return nb, nbm, nw, complete


def apply_delta(graph: Graph, delta: GraphDelta, *,
                edge_pad_multiple: Optional[int] = None,
                donate: bool = False) -> Graph:
    """Apply a :class:`GraphDelta` incrementally — bit-identical to a
    from-scratch :func:`from_edges` on the merged edge list, in
    O(delta + touched) host work instead of a full rebuild.

    ``donate=True`` is the churn-storm fast path: when the base is
    pristine (no failure-masked edges) and the table width is unchanged,
    the touched neighbor-table rows scatter into the base graph's
    buffers IN PLACE — O(touched rows) instead of an O(N x width) table
    copy, the difference between a delta and a rebuild at 10M-node
    table sizes. Like the engine's donating run loops, the donor
    graph's table buffers are INVALIDATED (reading them afterwards
    raises); use it in ``g = apply_delta(g, d, donate=True)`` rolling
    form and keep ``donate=False`` (the default) when the pre-delta
    graph must stay usable.

    The base COO is already receiver-sorted, so only the DELTA is
    radix-sorted; linear native merge/anti-merge passes
    (native/graphcore.cpp, numpy fallback under ``force_fallback()``)
    splice it into the base order, degrees and spans update from the
    batch, and only the touched neighbor-table rows recompute. The
    source-CSR view merges the surviving old order with the delta's
    sender-sorted ids — no E-element re-sort anywhere.

    Equivalence contract: the result equals
    ``from_edges(kept + adds, n_nodes, ...)`` with the base's layout
    settings, where ``kept`` is the base's LIVE edges (in sorted order)
    minus the removed pairs. Consequences:

    - edges masked out by failures are dropped for good (the
      ``consolidate`` semantic); node liveness (``node_mask``) is
      preserved as-is;
    - attached blocked/hybrid/skew layouts are REBUILT from the merged
      arrays (full host cost — they bake edge order); the incremental
      win covers COO, degrees, spans, neighbor tables, and the
      source CSR;
    - the dynamic edge region and any layout permutation ride along
      unchanged; delta ids speak the graph's (possibly relabeled) id
      space.
    """
    _reset_phases()
    n_pad = graph.n_nodes_padded
    # Default to the base's recorded multiple: shapes stay stable across
    # churn, so jitted consumers keep their compiled programs.
    pad_mult = edge_pad_multiple or graph.edge_pad_multiple
    add_s, add_r = delta.add_senders, delta.add_receivers
    rem_s, rem_r = delta.remove_senders, delta.remove_receivers
    _check_endpoints(add_s, add_r, graph.n_nodes)
    _check_endpoints(rem_s, rem_r, graph.n_nodes)
    weighted = graph.edge_weight is not None
    if weighted and add_s.size and delta.add_weights is None:
        raise ValueError(
            "graph carries edge weights; GraphDelta adds need add_weights")
    if not weighted and delta.add_weights is not None:
        raise ValueError(
            "add_weights on an unweighted graph — build with "
            "from_edges(weights=...) first")

    with _phase("delta_sort"):
        # Radix-sort only the delta (native sort_pairs): adds stably by
        # receiver — the order a stable from-scratch sort would give the
        # appended batch — removals by (receiver, sender) for the linear
        # anti-merge walk.
        add_w = delta.add_weights
        if add_s.size:
            _, perm = native.sort_pairs(
                add_r, np.arange(add_s.size, dtype=np.int32))
            add_r, add_s = add_r[perm], add_s[perm]
            if weighted:
                add_w = add_w[perm]
        if rem_s.size:
            order = np.lexsort((rem_s, rem_r))
            rem_r, rem_s = rem_r[order], rem_s[order]

    base_s = np.asarray(graph.senders)
    base_r = np.asarray(graph.receivers)
    emask = np.asarray(graph.edge_mask)
    live_count = int(np.count_nonzero(emask))
    # Pristine = every build edge still live: the precondition for
    # copy-then-patch on the neighbor table and CSR (a failure-masked base
    # compacts differently; those fall back to full recomputes of just
    # those two derived views).
    pristine = live_count == graph.n_edges

    with _phase("delta_merge"):
        keep, matched = native.delta_antimerge(
            base_r, base_s, emask, rem_r, rem_s)
        if not bool(matched.all()):
            missing = np.flatnonzero(~matched)[:5]
            pairs = [(int(rem_s[i]), int(rem_r[i])) for i in missing]
            raise ValueError(
                f"{int((~matched).sum())} removal pair(s) match no live "
                f"edge (first few as (sender, receiver): {pairs})")
        e_new = int(np.count_nonzero(keep)) + int(add_s.size)
        e_pad = _round_up(max(e_new, 1), pad_mult)
        # The merge writes straight into the padded target buffers — no
        # second copy pass; only the padding tails are filled after.
        s_arr = np.empty(e_pad, dtype=np.int32)
        r_arr = np.empty(e_pad, dtype=np.int32)
        out_r, out_s, posa, posb = native.delta_merge(
            base_r, base_s, keep, add_r, add_s, out_r=r_arr, out_s=s_arr)
        r_arr[e_new:] = n_pad - 1
        s_arr[e_new:] = 0
        emask_new = np.empty(e_pad, dtype=bool)
        emask_new[:e_new] = True
        emask_new[e_new:] = False
        w_arr = w_unpadded = None
        if weighted:
            w_host = np.asarray(graph.edge_weight)
            w_arr = np.zeros(e_pad, dtype=np.float32)
            kept_slots = posa >= 0
            w_arr[posa[kept_slots]] = w_host[kept_slots]
            if add_s.size:
                w_arr[posb] = add_w
            w_unpadded = w_arr[:e_new]

    with _phase("delta_degrees"):
        # Degrees update from the batch alone (in place, O(delta));
        # dynamic-region contributions (sim/topology.py connect) ride
        # inside in_degree/out_degree already and stay put — only the
        # STATIC views (span, table width, CSR counts) subtract them.
        rm_pos = (np.flatnonzero(emask ^ keep) if rem_s.size  # keep ⊆ emask
                  else np.zeros(0, dtype=np.int64))
        if donate:
            # Donated scatter-add: no O(N) degree-array copies; the donor
            # graph's degree buffers are invalidated.
            if rm_pos.size or add_s.size:
                # Zero-padded to a power-of-two bucket (adding 0 at index
                # 0 is the identity) so varying batch sizes reuse a
                # handful of compiled scatters.
                b = _pow2_pad(rm_pos.size + add_s.size)
                deltas = np.zeros(b, dtype=np.int32)
                deltas[:rm_pos.size] = -1
                deltas[rm_pos.size:rm_pos.size + add_s.size] = 1
                idx_r = np.zeros(b, dtype=np.int32)
                idx_r[:rm_pos.size + add_s.size] = np.concatenate(
                    [base_r[rm_pos], add_r])
                idx_s = np.zeros(b, dtype=np.int32)
                idx_s[:rm_pos.size + add_s.size] = np.concatenate(
                    [base_s[rm_pos], add_s])
                in_deg_new = _scatter_add_donating(
                    graph.in_degree, idx_r, deltas)
                out_deg_new = _scatter_add_donating(
                    graph.out_degree, idx_s, deltas)
            else:
                in_deg_new, out_deg_new = graph.in_degree, graph.out_degree
            in_host = np.asarray(in_deg_new)
            out_host = np.asarray(out_deg_new)
        else:
            in_host = np.asarray(graph.in_degree).copy()
            out_host = np.asarray(graph.out_degree).copy()
            if rm_pos.size:
                np.subtract.at(in_host, base_r[rm_pos], 1)
                np.subtract.at(out_host, base_s[rm_pos], 1)
            if add_s.size:
                np.add.at(in_host, add_r, 1)
                np.add.at(out_host, add_s, 1)
            in_deg_new, out_deg_new = in_host, out_host
        if graph.dyn_mask is not None:
            dm = np.asarray(graph.dyn_mask)
            static_in = in_host - np.bincount(
                np.asarray(graph.dyn_receivers)[dm],
                minlength=n_pad).astype(np.int32)
            static_out = out_host - np.bincount(
                np.asarray(graph.dyn_senders)[dm],
                minlength=n_pad).astype(np.int32)
        else:
            static_in, static_out = in_host, out_host
        max_in_span = max(int(static_in.max()) if e_new else 0, 1)

    nb = nbm = nw = None
    complete = graph.neighbors_complete
    if graph.neighbors is not None:
        with _phase("neighbor_table"):
            touched = np.unique(np.concatenate([rem_r, add_r]))
            nb, nbm, nw, complete = _delta_neighbor_tables(
                graph, out_r, out_s, w_unpadded, static_in, touched,
                graph.max_degree, pristine, donate=donate)

    src_eid = src_offsets = None
    max_out_span = graph.max_out_span
    if graph.src_eid is not None:
        with _phase("source_csr"):
            counts = static_out[:n_pad]
            src_offsets = np.zeros(n_pad + 1, dtype=np.int32)
            np.cumsum(counts, out=src_offsets[1:])
            max_out_span = int(counts.max()) if e_new else 0
            eid_arr = np.empty(e_pad, dtype=np.int32)
            eid_arr[e_new:] = e_pad - 1
            if pristine:
                kept_eids = native.map_filter(
                    np.asarray(graph.src_eid)[:graph.n_edges], posa)
                if add_s.size:
                    # posb ascends along the (receiver-sorted) adds, so a
                    # stable sender sort leaves per-sender ids ascending —
                    # the (sender, eid) order the merge needs.
                    _, add_eids = native.sort_pairs(add_s, posb)
                    native.merge_eids_by_sender(
                        out_s, kept_eids, add_eids, out=eid_arr[:e_new])
                else:
                    eid_arr[:e_new] = kept_eids
            else:
                eid_arr, src_offsets, max_out_span = _build_source_csr(
                    s_arr, emask_new, n_pad, e_pad)
            src_eid = eid_arr

    blocked_rep, hybrid_rep, skew_rep = graph.blocked, graph.hybrid, graph.skew
    if blocked_rep is not None or hybrid_rep is not None \
            or skew_rep is not None:
        with _phase("layouts"):
            # Rebuilds keep the base's RECORDED tuning (blocked/hybrid
            # block size, skew row width); the hybrid diagonal budget
            # (max_diags/min_count) is not recorded on the representation
            # and re-derives at its defaults.
            if blocked_rep is not None:
                from p2pnetwork_tpu.ops.blocked import \
                    build_blocked_from_arrays

                blocked_rep = build_blocked_from_arrays(
                    out_s, out_r, n_pad, blocked_rep.block)
            if hybrid_rep is not None:
                from p2pnetwork_tpu.ops.diag import build_hybrid_from_arrays

                kw = {}
                if hybrid_rep.remainder is not None:
                    kw["block"] = hybrid_rep.remainder.block
                hybrid_rep = build_hybrid_from_arrays(
                    out_s, out_r, graph.n_nodes, n_pad, **kw)
            if skew_rep is not None:
                from p2pnetwork_tpu.ops.skew import build_skew_from_arrays

                skew_rep = build_skew_from_arrays(
                    out_s, out_r, n_pad, e_pad, width=skew_rep.width,
                    weights=w_unpadded)

    arrays = {
        "senders": s_arr,
        "receivers": r_arr,
        "edge_mask": emask_new,
        "in_degree": in_deg_new,
        "out_degree": out_deg_new,
    }
    if nb is not None:
        arrays["neighbors"] = nb
        arrays["neighbor_mask"] = nbm
    if nw is not None:
        arrays["neighbor_weight"] = nw
    if src_eid is not None:
        arrays["src_eid"] = src_eid
        arrays["src_offsets"] = src_offsets
    if w_arr is not None:
        arrays["edge_weight"] = w_arr
    # One batched host->device put for every updated array (a per-array
    # jnp.asarray pays a fixed dispatch cost ~10x over).
    arrays = jax.device_put(arrays)
    return dataclasses.replace(
        graph,
        n_edges=e_new,
        neighbors_complete=complete,
        edge_pad_multiple=pad_mult,
        max_in_span=max_in_span,
        blocked=blocked_rep,
        hybrid=hybrid_rep,
        skew=skew_rep,
        max_out_span=max_out_span,
        **arrays,
    )


# ----------------------------------------------------------- live growth


def growth_capacity(demand: int, current: int) -> int:
    """Geometric node-capacity schedule: the smallest doubling of
    ``current`` that covers ``demand``.

    Doubling (not rounding up to the next pad multiple) is what makes
    :func:`grow` amortized: a sequence of K single-node growth steps
    crosses only O(log K) capacity boundaries, so the capacity-dependent
    rebuilds — and the recompiles every jitted consumer pays at a new
    ``N_pad`` — are paid O(log K) times, not K times. Doubling a pad
    multiple stays a pad multiple, so XLA tiling assumptions hold at
    every step.
    """
    cap = max(int(current), 1)
    demand = int(demand)
    while cap < demand:
        cap *= 2
    return cap


def grow(graph: Graph, n_new_nodes: int, *,
         node_capacity: Optional[int] = None) -> Graph:
    """Grow the overlay by ``n_new_nodes`` fresh live node ids
    (``n_nodes .. n_nodes + n_new_nodes - 1``), repadding node capacity
    on the geometric schedule of :func:`growth_capacity` when demand
    exceeds the current ``N_pad``.

    The node-capacity counterpart of :func:`apply_delta`'s O(delta) edge
    churn: existing node ids, edges, liveness masks, and the dynamic
    edge region are preserved bit-for-bit; only the capacity-dependent
    leaves are rebuilt (node mask/degrees zero-extended, neighbor-table
    rows zero-extended, the COO padding tail re-aimed at the new
    ``N_pad - 1`` sentinel so the receiver sort order survives, CSR
    offsets extended, layout permutations identity-extended, and the
    blocked/hybrid/skew layouts rebuilt at the new capacity with their
    recorded tuning). The result is bit-identical to a from-scratch
    :func:`from_edges` of the same edge list at
    ``node_pad_multiple=new capacity`` — wire the new nodes' edges with
    the existing :func:`apply_delta` machinery afterwards (its
    ``donate=True`` fast path stays valid: every grown leaf is a fresh
    device buffer).

    ``node_capacity`` pins an explicit target capacity (>= both the
    current capacity and the grown node count) instead of the doubling
    schedule — the repad-resume path uses it to match a checkpoint's
    recorded capacity exactly. When neither the node count nor the
    capacity changes this is a no-op returning ``graph`` itself.
    """
    if n_new_nodes < 0:
        raise ValueError("n_new_nodes must be >= 0")
    n_pad = graph.n_nodes_padded
    new_n = graph.n_nodes + int(n_new_nodes)
    new_pad = growth_capacity(new_n, n_pad)
    if node_capacity is not None:
        if int(node_capacity) < max(new_n, n_pad):
            raise ValueError(
                f"node_capacity {node_capacity} below the grown node "
                f"count {new_n} / current capacity {n_pad}")
        new_pad = int(node_capacity)
    if n_new_nodes == 0 and new_pad == n_pad:
        return graph
    _reset_phases()
    with _phase("grow"):
        g = _grow(graph, new_n, new_pad)
    telemetry.default_registry().counter(
        "sim_graph_grow_total",
        "Live overlay growth steps, split by whether node capacity "
        "repadded.", ("repad",)).labels(
            "true" if new_pad != n_pad else "false").inc()
    return g


def _grow(graph: Graph, new_n: int, new_pad: int) -> Graph:
    n_nodes, n_pad = graph.n_nodes, graph.n_nodes_padded
    e, e_pad = graph.n_edges, graph.n_edges_padded
    hybrid_rep = graph.hybrid
    s_live = r_live = None
    if graph.blocked is not None or hybrid_rep is not None \
            or graph.skew is not None:
        s_live = np.asarray(graph.senders)[:e]
        r_live = np.asarray(graph.receivers)[:e]

    if new_pad == n_pad:
        # Capacity holds: flip the new ids live and bump the static node
        # count. The hybrid layout is the one capacity-independent view
        # that bakes n_nodes (its diagonal census runs over the live
        # block), so it alone rebuilds.
        nm = np.asarray(graph.node_mask).copy()
        nm[n_nodes:new_n] = True
        if hybrid_rep is not None:
            from p2pnetwork_tpu.ops.diag import build_hybrid_from_arrays

            kw = {}
            if hybrid_rep.remainder is not None:
                kw["block"] = hybrid_rep.remainder.block
            hybrid_rep = build_hybrid_from_arrays(
                s_live, r_live, new_n, n_pad, **kw)
        return dataclasses.replace(
            graph, n_nodes=new_n, hybrid=hybrid_rep,
            node_mask=jax.device_put(nm))

    # Repad: rebuild exactly the capacity-dependent leaves. Everything
    # edge-shaped except the receiver padding tail is N-independent and
    # carries over untouched (senders pad with 0, src_eid with e_pad-1).
    nm = np.zeros(new_pad, dtype=bool)
    nm[:n_pad] = np.asarray(graph.node_mask)
    nm[n_nodes:new_n] = True
    in_deg = np.zeros(new_pad, dtype=np.int32)
    in_deg[:n_pad] = np.asarray(graph.in_degree)
    out_deg = np.zeros(new_pad, dtype=np.int32)
    out_deg[:n_pad] = np.asarray(graph.out_degree)
    # Padding receivers re-aim at the NEW last padded id — still >= every
    # live id, so the non-decreasing promise behind
    # indices_are_sorted=True survives the repad.
    r_arr = np.asarray(graph.receivers).copy()
    r_arr[e:] = new_pad - 1
    arrays = {"node_mask": nm, "in_degree": in_deg, "out_degree": out_deg,
              "receivers": r_arr}

    if graph.neighbors is not None:
        # Row-extend with empty rows — exactly what from_edges builds for
        # ids with no incoming edges, so capped-row subsampling (whose
        # shared RNG stream depends only on the capped degrees, which
        # growth never changes) stays bit-identical.
        width = graph.neighbors.shape[1]
        nb = np.zeros((new_pad, width), dtype=np.int32)
        nb[:n_pad] = np.asarray(graph.neighbors)
        nbm = np.zeros((new_pad, width), dtype=bool)
        nbm[:n_pad] = np.asarray(graph.neighbor_mask)
        arrays["neighbors"] = nb
        arrays["neighbor_mask"] = nbm
        if graph.neighbor_weight is not None:
            nw = np.zeros((new_pad, width), dtype=np.float32)
            nw[:n_pad] = np.asarray(graph.neighbor_weight)
            arrays["neighbor_weight"] = nw

    if graph.src_offsets is not None:
        # New rows own zero out-edges: the exclusive-prefix-sum tail just
        # repeats the total. src_eid's e_pad-1 padding fill is
        # N-independent and rides along.
        so = np.asarray(graph.src_offsets)
        arrays["src_offsets"] = np.concatenate(
            [so, np.full(new_pad - n_pad, so[-1], dtype=np.int32)])

    if graph.layout_perm is not None:
        # The relabeling extends with the identity over the new capacity
        # range, like from_edges pads it over the padding ids.
        ext = np.arange(n_pad, new_pad, dtype=np.int32)
        arrays["layout_perm"] = np.concatenate(
            [np.asarray(graph.layout_perm), ext])
        arrays["layout_inv"] = np.concatenate(
            [np.asarray(graph.layout_inv), ext])

    blocked_rep, skew_rep = graph.blocked, graph.skew
    if blocked_rep is not None:
        from p2pnetwork_tpu.ops.blocked import build_blocked_from_arrays

        blocked_rep = build_blocked_from_arrays(
            s_live, r_live, new_pad, blocked_rep.block)
    if hybrid_rep is not None:
        from p2pnetwork_tpu.ops.diag import build_hybrid_from_arrays

        kw = {}
        if hybrid_rep.remainder is not None:
            kw["block"] = hybrid_rep.remainder.block
        hybrid_rep = build_hybrid_from_arrays(
            s_live, r_live, new_n, new_pad, **kw)
    if skew_rep is not None:
        from p2pnetwork_tpu.ops.skew import build_skew_from_arrays

        w_unpadded = None
        if graph.edge_weight is not None:
            w_unpadded = np.asarray(graph.edge_weight)[:e]
        skew_rep = build_skew_from_arrays(
            s_live, r_live, new_pad, e_pad, width=skew_rep.width,
            weights=w_unpadded)

    arrays = jax.device_put(arrays)
    return dataclasses.replace(
        graph, n_nodes=new_n, blocked=blocked_rep, hybrid=hybrid_rep,
        skew=skew_rep, **arrays)


def from_edges(
    senders,
    receivers,
    n_nodes: int,
    *,
    node_pad_multiple: int = 128,
    edge_pad_multiple: int = 128,
    build_neighbor_table: bool = True,
    max_degree: Optional[int] = None,
    blocked: bool = False,
    hybrid: bool = False,
    skew_table: bool = False,
    skew_width: int = 0,
    source_csr: bool = False,
    weights=None,
    reorder: Optional[str] = None,
) -> Graph:
    """Build a :class:`Graph` from host-side edge arrays.

    Edges are sorted by receiver and padded to ``edge_pad_multiple``; nodes
    are padded to ``node_pad_multiple`` (lane-friendly sizes keep XLA tiling
    happy). Padded edges point at the LAST padded node index (keeping the
    receiver array non-decreasing — the ``indices_are_sorted=True`` promise
    the segment reductions rely on) and are masked out of every aggregation.
    ``max_degree`` caps the neighbor table width (default: the true maximum
    in-degree).

    ``blocked=True`` / ``hybrid=True`` attach those aggregation
    representations *during* construction — same results as the
    ``with_blocked()`` / ``with_hybrid()`` methods, but built from the
    host-side arrays already in hand instead of pulling device arrays back
    over the wire (a multi-second round trip at BASELINE scale).

    ``reorder`` (opt-in; ``"degree"`` or ``"rcm"``, sim/layout.py) relabels
    node ids through an IO-aware permutation before building, so gathers
    over neighbor rows hit contiguous memory; the mapping is recorded on
    the graph (``layout_perm``/``layout_inv``) and every runtime id then
    speaks the relabeled space — map results back with
    ``layout.to_original_order``.
    """
    senders = np.asarray(senders, dtype=np.int32)
    receivers = np.asarray(receivers, dtype=np.int32)
    if senders.shape != receivers.shape:
        raise ValueError("senders and receivers must have the same shape")
    if senders.size and (senders.max() >= n_nodes or receivers.max() >= n_nodes):
        raise ValueError("edge endpoint out of range")

    _reset_phases()
    layout_perm = layout_inv = None
    if reorder is not None:
        with _phase("reorder"):
            from p2pnetwork_tpu.sim import layout

            perm = layout.node_permutation(senders, receivers, n_nodes,
                                           strategy=reorder)
            senders = perm[senders]
            receivers = perm[receivers]
            layout_perm = perm

    with _phase("sort"):
        if weights is not None:
            # Per-edge costs (latency-weighted overlays): permute through
            # the same receiver sort as the endpoints so everything stays
            # aligned.
            weights = np.asarray(weights, dtype=np.float32)
            if weights.shape != senders.shape:
                raise ValueError("weights must align with senders/receivers")
            receivers, perm = native.sort_pairs(
                receivers, np.arange(senders.size, dtype=np.int32))
            senders = senders[perm]
            weights = weights[perm]
        else:
            receivers, senders = native.sort_pairs(receivers, senders)

    n_pad = _round_up(max(n_nodes, 1), node_pad_multiple)
    e = senders.size
    e_pad = _round_up(max(e, 1), edge_pad_multiple)

    s = np.zeros(e_pad, dtype=np.int32)
    # Padding receivers with n_pad-1 (>= every active id) keeps the array
    # sorted; padded contributions are zeroed by edge_mask either way.
    r = np.full(e_pad, n_pad - 1, dtype=np.int32)
    s[:e], r[:e] = senders, receivers
    emask = np.zeros(e_pad, dtype=bool)
    emask[:e] = True
    w = None
    if weights is not None:
        w = np.zeros(e_pad, dtype=np.float32)
        w[:e] = weights
    nmask = np.zeros(n_pad, dtype=bool)
    nmask[:n_nodes] = True

    in_deg = np.bincount(receivers, minlength=n_pad).astype(np.int32)
    out_deg = np.bincount(senders, minlength=n_pad).astype(np.int32)
    # The padding tail (receiver n_pad-1, edge_mask False) extends that id's
    # run but can never match a probe — edge_mask excludes it — so the
    # window only needs to span the widest LIVE run.
    max_in_span = max(int(in_deg.max()) if e else 0, 1)

    if layout_perm is not None:
        # Pad the relabeling with the identity over the padding ids so the
        # recorded mapping covers the full padded id space.
        layout_perm = np.concatenate([
            layout_perm.astype(np.int32),
            np.arange(n_nodes, n_pad, dtype=np.int32)])
        layout_inv = np.empty_like(layout_perm)
        layout_inv[layout_perm] = np.arange(n_pad, dtype=np.int32)

    neighbors = neighbor_mask = neighbor_weight = None
    neighbors_complete = True
    _t_table = time.perf_counter()
    if build_neighbor_table:
        width = int(in_deg.max()) if e else 0
        if max_degree is not None:
            neighbors_complete = max_degree >= width
            width = min(width, max_degree)
        width = max(width, 1)
        # receivers are sorted, so each node's incoming edges are contiguous.
        starts = np.searchsorted(receivers, np.arange(n_pad))
        ends = np.searchsorted(receivers, np.arange(n_pad), side="right")
        take, valid = _padded_row_fill(starts, np.minimum(ends - starts, width), width)
        # Over-degree rows get a uniform random subset of their in-edges
        # (deterministic seed: graph construction stays reproducible). A
        # plain prefix would bias Gossip's partner draw toward whichever
        # senders happen to sort first. Vectorized: rank random keys per
        # edge within its row; an edge is kept iff its rank < width — a
        # uniform width-subset for every capped row in one pass.
        capped = np.nonzero(ends - starts > width)[0]
        if capped.size:
            cap_rng = np.random.default_rng(0)
            deg = ends - starts
            cap_edge = np.repeat(capped, deg[capped])
            offs = np.arange(cap_edge.size) - np.repeat(
                np.cumsum(deg[capped]) - deg[capped], deg[capped]
            )
            edge_idx = starts[cap_edge] + offs
            keys = cap_rng.random(edge_idx.size)
            # rank within row = position after sorting by (row, key)
            order = np.lexsort((keys, cap_edge))
            rank = np.empty_like(offs)
            rank[order] = offs
            kept = rank < width  # exactly `width` uniform survivors per row
            resort = np.lexsort((edge_idx[kept], cap_edge[kept]))
            take[capped] = edge_idx[kept][resort].reshape(capped.size, width)
        # A dummy pool entry keeps the (eagerly evaluated) gather in-bounds
        # for zero-edge graphs; `valid` masks it out.
        pool = senders if e else np.zeros(1, dtype=np.int32)
        take_safe = np.minimum(take, max(e - 1, 0))
        neighbors = np.where(valid, pool[take_safe], 0).astype(np.int32)
        neighbor_mask = valid
        if weights is not None:
            wpool = weights if e else np.zeros(1, dtype=np.float32)
            neighbor_weight = np.where(valid, wpool[take_safe], 0.0).astype(
                np.float32)

    if build_neighbor_table:
        _note_phase("neighbor_table", time.perf_counter() - _t_table)

    blocked_rep = hybrid_rep = skew_rep = None
    _t_layouts = time.perf_counter()
    if blocked:
        from p2pnetwork_tpu.ops.blocked import build_blocked_from_arrays

        blocked_rep = build_blocked_from_arrays(senders, receivers, n_pad)
    if hybrid:
        from p2pnetwork_tpu.ops.diag import build_hybrid_from_arrays

        hybrid_rep = build_hybrid_from_arrays(senders, receivers, n_nodes, n_pad)
    if skew_table:
        from p2pnetwork_tpu.ops.skew import build_skew_from_arrays

        skew_rep = build_skew_from_arrays(
            senders, receivers, n_pad, e_pad, width=skew_width,
            weights=weights,
        )
    if blocked or hybrid or skew_table:
        _note_phase("layouts", time.perf_counter() - _t_layouts)

    src_eid = src_offsets = None
    max_out_span = 0
    if source_csr:
        with _phase("source_csr"):
            src_eid, src_offsets, max_out_span = _build_source_csr(
                s, emask, n_pad, e_pad
            )
            src_eid = jnp.asarray(src_eid)
            src_offsets = jnp.asarray(src_offsets)

    return Graph(
        senders=jnp.asarray(s),
        receivers=jnp.asarray(r),
        edge_mask=jnp.asarray(emask),
        node_mask=jnp.asarray(nmask),
        in_degree=jnp.asarray(in_deg),
        out_degree=jnp.asarray(out_deg),
        neighbors=None if neighbors is None else jnp.asarray(neighbors),
        neighbor_mask=None if neighbor_mask is None else jnp.asarray(neighbor_mask),
        n_nodes=n_nodes,
        n_edges=e,
        neighbors_complete=neighbors_complete,
        max_degree_cap=max_degree,
        edge_pad_multiple=edge_pad_multiple,
        max_in_span=max_in_span,
        blocked=blocked_rep,
        hybrid=hybrid_rep,
        skew=skew_rep,
        src_eid=src_eid,
        src_offsets=src_offsets,
        max_out_span=max_out_span,
        edge_weight=None if w is None else jnp.asarray(w),
        neighbor_weight=(None if neighbor_weight is None
                         else jnp.asarray(neighbor_weight)),
        layout_perm=(None if layout_perm is None
                     else jnp.asarray(layout_perm)),
        layout_inv=None if layout_inv is None else jnp.asarray(layout_inv),
    )


def _undirect(src: np.ndarray, dst: np.ndarray):
    """Duplicate each undirected edge into both directions."""
    return np.concatenate([src, dst]), np.concatenate([dst, src])


def _pair_bits(n: int) -> int:
    """Bits needed to hold an id in ``[0, n)`` — the shift of the packed
    undirected-pair key. Using the minimal width (not a fixed 32) keeps the
    radix sort at the fewest 16-bit passes the key range allows."""
    return max(int(n - 1).bit_length(), 1)


def _dedup_undirected(src: np.ndarray, dst: np.ndarray, n: int):
    """Unique undirected pairs as (lo, hi) int32 arrays.

    Encodes each pair as ``min << b | max`` (``b`` = bits of ``n-1``; int64,
    safe for any int32 id range) and dedups with one native radix sort pass
    — shared by every random generator so each undirected edge enters the
    graph exactly once (duplicates would double-count infection pressure in
    SIR). Shifts/masks, not ``*n`` / ``// n``: the int64 divisions of the
    arithmetic encoding were a measured hotspot of graph build at 10M nodes.
    """
    t0 = time.perf_counter()
    b = _pair_bits(n)
    lo = np.minimum(src, dst).astype(np.int64)
    hi = np.maximum(src, dst)
    keys = native.sort_unique((lo << b) | hi)
    out = (keys >> b).astype(np.int32), (keys & ((1 << b) - 1)).astype(np.int32)
    _note_dedup(time.perf_counter() - t0)
    return out


def erdos_renyi(n: int, p: float, seed: int = 0, **kw) -> Graph:
    """G(n, p) random graph (undirected).

    For scale, the number of undirected edges is drawn from the matching
    binomial and pairs are sampled uniformly (with collision dedup) instead
    of materialising the O(n^2) adjacency — equivalent in distribution up to
    the dedup, and the only tractable construction at millions of nodes.
    """
    rng = np.random.default_rng(seed)
    n_pairs = n * (n - 1) // 2
    m = rng.binomial(n_pairs, p) if n_pairs < 2**63 else int(p * n_pairs)
    if m == 0:
        return from_edges(np.zeros(0), np.zeros(0), n, **kw)
    # Accumulate unique pairs until we have at least m, then subsample to
    # exactly m uniformly — truncating the (sorted) unique keys instead would
    # bias edges toward low-index nodes.
    t0 = time.perf_counter()
    b = _pair_bits(n)
    keys = np.zeros(0, dtype=np.int64)
    draw = int(m * 1.2) + 16
    while keys.size < m:
        src = rng.integers(0, n, size=draw, dtype=np.int64)
        dst = rng.integers(0, n, size=draw, dtype=np.int64)
        keep = src != dst
        lo, hi = np.minimum(src[keep], dst[keep]), np.maximum(src[keep], dst[keep])
        keys = native.sort_unique(np.concatenate([keys, (lo << b) | hi]))
        draw *= 2
    keys = rng.permutation(keys)[:m]
    _note_dedup(time.perf_counter() - t0)
    lo = (keys >> b).astype(np.int32)
    hi = (keys & ((1 << b) - 1)).astype(np.int32)
    return from_edges(*_undirect(lo, hi), n, **kw)


def barabasi_albert(n: int, m: int, seed: int = 0, **kw) -> Graph:
    """Barabási–Albert preferential attachment via the Bollobás linearized
    chord diagram (LCD) construction — the rigorous formulation of the BA
    process, chosen because it vectorizes exactly.

    Sequential BA ("attach proportionally to current degree") looks
    inherently serial: each attachment changes the degrees the next one
    samples from. In the LCD form, mini-vertex ``i``'s target is a uniform
    draw ``u_i`` over ``2i+1`` endpoint slots whose *layout* is fixed in
    advance — slot ``2j`` holds mini-vertex ``j``, slot ``2j+1`` holds
    ``j``'s (yet unresolved) target, slot ``2i`` means a self-loop — so a
    node's appearance count equals its degree and the draw is exactly
    degree-proportional. All draws happen up front; odd slots form pointer
    chains to earlier draws, resolved in O(log chain) pointer-doubling
    passes. ``m > 1`` contracts groups of ``m`` consecutive mini-vertices;
    self-loops and duplicate pairs are dropped (so a node can end with
    fewer than ``m`` attachments, as in the standard construction).
    """
    if m < 1 or m >= n:
        raise ValueError("barabasi_albert requires 1 <= m < n")
    rng = np.random.default_rng(seed)
    N = n * m  # mini-vertices of the m=1 process
    i = np.arange(N, dtype=np.int64)
    u = (rng.random(N) * (2 * i + 1)).astype(np.int64)  # uniform on [0, 2i]
    # Even slot -> resolved node id (slot 2i is the self-loop, = i). Odd
    # slot -> the target of an earlier draw: follow the chain.
    targets = np.where(u % 2 == 0, u // 2, np.int64(-1))
    parent = np.where(u % 2 == 1, (u - 1) // 2, i)
    unresolved = targets < 0
    while unresolved.any():
        targets = np.where(unresolved, targets[parent], targets)
        parent = parent[parent]  # pointer doubling
        unresolved = targets < 0
    src = i // m
    dst = targets // m
    keep = src != dst  # drop self-loops (LCD produces them by design)
    lo, hi = _dedup_undirected(src[keep], dst[keep], n)
    return from_edges(*_undirect(lo, hi), n, **kw)


def watts_strogatz(n: int, k: int, p: float, seed: int = 0, **kw) -> Graph:
    """Watts–Strogatz small world: ring lattice with ``k`` neighbors per node
    (k/2 each side), each edge rewired with probability ``p``. Vectorized —
    this is the generator used for the million-node benchmark configs."""
    if k % 2 != 0:
        raise ValueError("watts_strogatz requires even k")
    if k >= n:
        # The ring lattice needs k distinct neighbors per node; the wrap
        # arithmetic below folds base+off past n at most once, which only
        # covers offsets < n.
        raise ValueError("watts_strogatz requires k < n")
    rng = np.random.default_rng(seed)
    base = np.arange(n, dtype=np.int32)
    srcs, dsts = [], []
    for off in range(1, k // 2 + 1):
        src = base
        # base + off wraps at most once past n, so a conditional subtract
        # replaces the (per-element integer division) modulo.
        ring_dst = base + np.int32(off)
        ring_dst = np.where(ring_dst >= n, ring_dst - np.int32(n), ring_dst)
        rewire = rng.random(n) < p
        new_dst = rng.integers(0, n, size=n, dtype=np.int32)
        dst = np.where(rewire, new_dst, ring_dst)
        dst = np.where(dst == src, ring_dst, dst)
        srcs.append(src)
        dsts.append(dst)
    src = np.concatenate(srcs)
    dst = np.concatenate(dsts)
    # A rewired target can collide with another (lattice or rewired) edge of
    # the same node; dedup so each undirected pair appears once.
    lo, hi = _dedup_undirected(src, dst, n)
    return from_edges(*_undirect(lo, hi), n, **kw)


def ring(n: int, **kw) -> Graph:
    """Simple bidirectional ring."""
    base = np.arange(n, dtype=np.int32)
    return from_edges(*_undirect(base, (base + 1) % n), n, **kw)


def chord(n: int, **kw) -> Graph:
    """Chord-style structured overlay: the identifier ring plus a finger
    to ``(v + 2^i) mod n`` for every ``i`` with ``2^i < n`` — the DHT
    topology (successor lists + finger tables) that P2P deployments build
    on top of unstructured libraries like the reference. O(log n) degree,
    O(log n) diameter: greedy/BFS routing here is the batched form of a
    Chord lookup. Edges are undirected (the reference's TCP-connection
    semantic: traffic flows both ways)."""
    if n < 2:
        raise ValueError("chord requires n >= 2 (no fingers exist below that)")
    base = np.arange(n, dtype=np.int64)
    srcs, dsts = [], []
    i = 0
    while (1 << i) < n:
        srcs.append(base)
        dsts.append((base + (1 << i)) % n)
        i += 1
    lo, hi = _dedup_undirected(np.concatenate(srcs), np.concatenate(dsts), n)
    return from_edges(*_undirect(lo, hi), n, **kw)


def kademlia(n: int, k: int = 1, **kw) -> Graph:
    """Kademlia-style structured overlay: for every node ``v`` and every
    XOR-distance bucket ``[2^i, 2^(i+1))`` with ``2^i < n``, edges to the
    ``k`` CLOSEST ids in that bucket — ``v ^ d`` for ``d = 2^i ..
    2^i + k - 1`` (the k smallest XOR distances the band contains), kept
    when the partner id exists. The other classic DHT geometry beside
    :func:`chord`: XOR-metric buckets instead of modular fingers, so at
    ``k = 1`` on a fully-populated (power-of-two) id space this is
    exactly the binary hypercube Kademlia lookups walk, with O(log n)
    degree and O(log n) diameter; larger ``k`` is the bucket width
    (Kademlia's replication parameter) adding redundancy per band. Ids
    above ``n - 1`` don't exist (a partially-populated id space): when
    the rank-``j`` closest partner ``v ^ (2^i + j)`` is such a ghost,
    the edge falls back to the ``j``-th LOWEST id of the bucket's live
    range — farther by XOR but a legitimate bucket contact, so every
    populated bucket gets at least its ``j = 0`` edge (a bucket holding
    fewer than ``j + 1`` live ids simply has no rank-``j`` contact, like
    a real routing table's short bucket). Deterministic; edges
    undirected (the reference's TCP-connection semantic)."""
    if n < 2:
        raise ValueError("kademlia requires n >= 2 (no buckets below that)")
    if k < 1:
        raise ValueError("k must be >= 1")
    v = np.arange(n, dtype=np.int64)
    srcs, dsts = [], []
    i = 0
    while (1 << i) < n:
        width = 1 << i
        # The bucket's id range: v's prefix above bit i with bit i flipped.
        bucket_base = ((v >> i) ^ 1) << i
        v_low = v & (width - 1)
        for j in range(min(k, width)):
            ideal = bucket_base + (v_low ^ j)  # XOR distance 2^i + j
            fallback = bucket_base + j  # always exists when the bucket does
            cand = np.where(ideal < n, ideal, fallback)
            keep = cand < n
            srcs.append(v[keep])
            dsts.append(cand[keep])
        i += 1
    lo, hi = _dedup_undirected(np.concatenate(srcs), np.concatenate(dsts), n)
    return from_edges(*_undirect(lo, hi), n, **kw)


def complete(n: int, **kw) -> Graph:
    """Complete graph (every pair connected) — small n only."""
    src, dst = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    keep = src != dst
    return from_edges(src[keep].astype(np.int32), dst[keep].astype(np.int32), n, **kw)


def build(topology) -> Graph:
    """Build a graph from a :class:`p2pnetwork_tpu.config.TopologyConfig`."""
    kind = topology.kind
    if kind == "erdos_renyi":
        return erdos_renyi(topology.n_nodes, topology.p, topology.seed)
    if kind == "barabasi_albert":
        return barabasi_albert(topology.n_nodes, topology.k, topology.seed)
    if kind == "watts_strogatz":
        return watts_strogatz(topology.n_nodes, topology.k, topology.p, topology.seed)
    if kind == "ring":
        return ring(topology.n_nodes)
    if kind == "chord":
        return chord(topology.n_nodes)
    if kind == "kademlia":
        return kademlia(topology.n_nodes, topology.k)
    if kind == "complete":
        return complete(topology.n_nodes)
    raise ValueError(f"unknown topology kind: {kind!r}")
