"""Content-addressed persistence of built graph layouts.

Graph construction is the host-side scale bottleneck (BENCH_r02: the 1M
run finishes in 0.133 s while ``graph_build_s`` is 5.31), so a built
layout — COO + neighbor tables + kernel layouts + CSR, everything
``sim/checkpoint.py`` ``save_graph`` serializes — should be paid for once
per (builder code, topology params, layout flags) and reloaded
thereafter. bench.py grew exactly this machinery privately
(``_layout_fingerprint`` / ``_cached_graph``); this module is the
library-level generalization bench, the supervise plane, and tests all
share.

The cache is content-addressed: an entry's filename carries a
:func:`fingerprint` of (a) every source file whose code determines the
built arrays — the graph builder, the reorder pass, the topology
generators, the kernel-layout builders, the native radix/merge kernels
and their bindings, the serializer — and (b) the caller-supplied
``params`` (topology arguments and layout flags, the reorder strategy
included). Editing any of those sources, or changing a param, changes
the name, so a stale layout can never be loaded as fresh — it is simply
never found (delete old files at leisure; ``clear()`` does it for you).

Fingerprints are pure stdlib (file bytes + canonical JSON); jax enters
only inside :func:`cached_graph`, where graphs are actually
(de)serialized through ``sim/checkpoint.py`` — bench's stdlib-only
parent process never calls either (its stage children do).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Iterable, Optional, Tuple

from p2pnetwork_tpu import telemetry

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Package-relative sources whose code determines a built graph's arrays
#: and kernel layouts. This set is the fix for the bench stale-cache bug:
#: the old bench-private fingerprint omitted the native radix sort
#: (graphcore.cpp + its bindings) and the topology generators, so edits
#: there silently reused stale cached graphs.
DEFAULT_SOURCES = (
    "sim/graph.py",
    "sim/layout.py",
    "sim/topology.py",
    "sim/checkpoint.py",
    "ops/blocked.py",
    "ops/diag.py",
    "ops/skew.py",
    "ops/bitset.py",
    "ops/frontier.py",
    "native/graphcore.cpp",
    "native/__init__.py",
)


def fingerprint(*, params: Optional[dict] = None,
                extra_sources: Iterable[str] = (),
                digest_size: int = 6) -> str:
    """Hex digest naming one layout configuration.

    Folds the bytes of every :data:`DEFAULT_SOURCES` file (package-
    relative) plus any ``extra_sources`` (absolute paths — e.g. the
    caller script whose build invocation holds the kwargs), then the
    canonical JSON of ``params``. Pass every topology argument and
    layout flag that shapes the build — the reorder strategy included —
    as ``params``; two configurations differing only there must not
    share an entry.
    """
    h = hashlib.blake2b(digest_size=digest_size)
    for rel in DEFAULT_SOURCES:
        try:
            with open(os.path.join(_PKG_DIR, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            # Not every install ships every source (a .py-only wheel has
            # no graphcore.cpp — the same case the native loader falls
            # back on). Absence is itself fingerprinted, so a source
            # (dis)appearing still invalidates; the cache must degrade,
            # never crash the caller's build.
            h.update(f"<absent:{rel}>".encode())
    for path in extra_sources:
        # Caller-supplied sources stay strict: a typo'd path here would
        # silently fingerprint nothing and UNDER-invalidate.
        with open(path, "rb") as f:
            h.update(f.read())
    if params:
        h.update(json.dumps(params, sort_keys=True, default=str).encode())
    return h.hexdigest()


def default_cache_dir() -> str:
    """``$P2P_LAYOUT_CACHE_DIR``, else a per-user cache directory."""
    env = os.environ.get("P2P_LAYOUT_CACHE_DIR")
    if env:
        return env
    cache = os.environ.get("XDG_CACHE_HOME",
                           os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(cache, "p2pnetwork_tpu", "layouts")


def entry_path(name: str, *, cache_dir: Optional[str] = None,
               params: Optional[dict] = None,
               extra_sources: Iterable[str] = ()) -> str:
    """The content-addressed file a configuration persists to."""
    fp = fingerprint(params=params, extra_sources=extra_sources)
    return os.path.join(cache_dir or default_cache_dir(),
                        f"{name}_{fp}.npz")


def _miss_counter():
    return telemetry.default_registry().counter(
        "layout_cache_miss_total",
        "Layout-cache misses by cause; every miss costs a full graph "
        "build.", ("reason",))


def cached_graph(name: str, build: Callable, *,
                 cache_dir: Optional[str] = None,
                 params: Optional[dict] = None,
                 extra_sources: Iterable[str] = (),
                 enabled: Optional[bool] = None,
                 on_miss: Optional[Callable] = None,
                 log: Optional[Callable[[str], None]] = None) -> Tuple:
    """Load the persisted layout for ``(name, fingerprint)`` or build and
    persist it. Returns ``(graph, seconds, from_cache)``.

    Any cache failure (missing file, version skew, truncated write) falls
    back to a fresh ``build()`` — the cache can only make callers faster,
    never wrong: the fingerprint pins the builder code and params, and
    builds are seed-deterministic, so cached and rebuilt graphs are
    identical arrays. Every fallback is REPORTED, never swallowed: the
    ``layout_cache_miss_total{reason=missing|corrupt|disabled}`` counter
    plus the optional ``on_miss(reason, path, error)`` callback (bench
    mirrors it into its structured warning events). ``enabled`` defaults
    to ``$P2P_LAYOUT_CACHE != "0"``; ``log`` (if given) receives one
    info line per load/store.
    """
    from p2pnetwork_tpu.sim import checkpoint as ckpt

    if enabled is None:
        enabled = os.environ.get("P2P_LAYOUT_CACHE", "1") != "0"
    cache_dir = cache_dir or default_cache_dir()
    path = None
    if enabled:  # a disabled cache computes no fingerprint at all
        path = entry_path(name, cache_dir=cache_dir, params=params,
                          extra_sources=extra_sources)

    def _miss(reason: str, error: Optional[str] = None) -> None:
        _miss_counter().labels(reason=reason).inc()
        if on_miss is not None:
            on_miss(reason, path, error)

    if enabled and os.path.exists(path):
        try:
            t0 = time.perf_counter()
            g = ckpt.load_graph(path)
            dt = time.perf_counter() - t0
            if log is not None:
                log(f"{name}: loaded cached graph in {dt:.1f}s ({path})")
            return g, dt, True
        except Exception as e:
            _miss("corrupt", f"{type(e).__name__}: {e}")
    elif enabled:
        _miss("missing")
    else:
        _miss("disabled")
    t0 = time.perf_counter()
    g = build()
    dt = time.perf_counter() - t0
    if enabled:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            ckpt.save_graph(path, g)
            if log is not None:
                log(f"{name}: built in {dt:.1f}s, cached to {path}")
        except Exception as e:  # a full disk must not sink the caller
            if log is not None:
                log(f"{name}: cache save failed ({type(e).__name__}: {e})")
    return g, dt, False


def clear(cache_dir: Optional[str] = None) -> int:
    """Delete every ``.npz`` entry under the cache dir (current AND stale
    fingerprints — the invalidation workflow after intentional layout
    changes). Returns the number of files removed."""
    cache_dir = cache_dir or default_cache_dir()
    removed = 0
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return 0
    for fname in names:
        if fname.endswith(".npz"):
            try:
                os.unlink(os.path.join(cache_dir, fname))
                removed += 1
            except OSError:
                pass
    return removed
