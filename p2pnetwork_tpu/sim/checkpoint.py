"""Checkpoint / resume for simulation runs.

The reference has no persistence of any kind — node ids are regenerated per
run [ref: p2pnetwork/node.py:85-90] (SURVEY.md section 5 "Checkpoint").
For multi-million-node simulations, resumability is table stakes: a
checkpoint is the protocol state pytree plus the PRNG key and round counter
— everything needed to make a resumed run bit-identical to an uninterrupted
one (tests/test_checkpoint.py proves that).

Format: a single ``.npz`` (atomic rename on save). The state's tree
structure is recorded so loads verify against the template; arrays come
back as numpy and are device-put lazily by the first jitted use.

For sharded / multi-host runs use :func:`save_orbax` / :func:`load_orbax`:
the npz path funnels every shard through one host, while orbax writes each
process's shards in parallel and restores arrays WITH their shardings (the
template's shardings are applied on load, so a resumed multi-chip run does
not round-trip through host memory).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Optional, Tuple

import jax
import numpy as np


def save(path: str, state: Any, key: jax.Array, round_index: int,
         message_count: int = 0) -> None:
    """Atomically write (state pytree, PRNG key, round counter, message
    counter) to ``path``."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    payload = {f"leaf_{i}": np.asarray(jax.device_get(x)) for i, x in enumerate(leaves)}
    payload["__key__"] = np.asarray(jax.random.key_data(key))
    payload["__round__"] = np.asarray(round_index, dtype=np.int64)
    payload["__messages__"] = np.asarray(message_count, dtype=np.int64)
    payload["__treedef__"] = np.frombuffer(str(treedef).encode(), dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str, template: Any) -> Tuple[Any, jax.Array, int, int]:
    """Load a checkpoint written by :func:`save`.

    ``template`` is a state pytree with the same structure (e.g. a freshly
    built ``protocol.init(...)``); its treedef validates the file.
    Returns ``(state, key, round_index, message_count)``.
    """
    with np.load(path) as data:
        _, treedef = jax.tree_util.tree_flatten(template)
        stored = bytes(data["__treedef__"]).decode()
        if stored != str(treedef):
            raise ValueError(
                f"checkpoint structure mismatch:\n  file: {stored}\n  template: {treedef}"
            )
        n = len([k for k in data.files if k.startswith("leaf_")])
        leaves = [data[f"leaf_{i}"] for i in range(n)]
        state = jax.tree_util.tree_unflatten(treedef, leaves)
        key = jax.random.wrap_key_data(data["__key__"])
        messages = int(data["__messages__"]) if "__messages__" in data.files else 0
        return state, key, int(data["__round__"]), messages


def save_orbax(path: str, state: Any, key: jax.Array, round_index: int,
               message_count: int = 0) -> None:
    """Checkpoint via orbax (sharding-preserving, multi-host-parallel).

    ``path`` is a directory (created/overwritten). All hosts of a
    multi-process job must call this collectively.
    """
    import orbax.checkpoint as ocp

    payload = {
        "state": state,
        "key_data": jax.random.key_data(key),
        "round_index": np.int64(round_index),
        "message_count": np.int64(message_count),
    }
    # Context-manage: each StandardCheckpointer owns async worker threads;
    # a checkpoint-every-N-rounds loop must not leak one pool per save.
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), payload, force=True)
        ckptr.wait_until_finished()


def load_orbax(path: str, template: Any) -> Tuple[Any, jax.Array, int, int]:
    """Restore a :func:`save_orbax` checkpoint.

    ``template`` supplies structure, dtypes, AND shardings: pass a state
    built the way the resumed run will use it (e.g. on the same mesh) and
    the restored arrays land sharded the same way, no host round-trip.
    Returns ``(state, key, round_index, message_count)``.
    """
    import orbax.checkpoint as ocp

    # Every leaf gets an explicit sharding: omitting one makes orbax fall
    # back to the sharding recorded at save time, which it documents as
    # unsafe across device topologies — exactly the resume-on-a-different-
    # slice case this API exists for. Leaves without a sharding (and the
    # bookkeeping scalars) are replicated over the template's mesh when it
    # has one, else placed on the default device.
    meshes = [
        leaf.sharding.mesh
        for leaf in jax.tree.leaves(template)
        if isinstance(getattr(leaf, "sharding", None), jax.sharding.NamedSharding)
    ]
    if meshes:
        default_sharding = jax.sharding.NamedSharding(
            meshes[0], jax.sharding.PartitionSpec()
        )
    else:
        default_sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    def abstract(x):
        x = jax.numpy.asarray(x)
        sharding = getattr(x, "sharding", None) or default_sharding
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    key_data = jax.random.key_data(jax.random.key(0))
    target = {
        "state": jax.tree.map(abstract, template),
        "key_data": jax.ShapeDtypeStruct(
            key_data.shape, key_data.dtype, sharding=default_sharding
        ),
        "round_index": jax.ShapeDtypeStruct((), np.int64, sharding=default_sharding),
        "message_count": jax.ShapeDtypeStruct((), np.int64, sharding=default_sharding),
    }
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.abspath(path), target)
    key = jax.random.wrap_key_data(restored["key_data"])
    return (
        restored["state"],
        key,
        int(restored["round_index"]),
        int(restored["message_count"]),
    )
