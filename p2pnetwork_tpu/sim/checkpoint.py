"""Checkpoint / resume for simulation runs.

The reference has no persistence of any kind — node ids are regenerated per
run [ref: p2pnetwork/node.py:85-90] (SURVEY.md section 5 "Checkpoint").
For multi-million-node simulations, resumability is table stakes: a
checkpoint is the protocol state pytree plus the PRNG key and round counter
— everything needed to make a resumed run bit-identical to an uninterrupted
one (tests/test_checkpoint.py proves that). Topology mutations (failures,
runtime links) are state too — the reference's peer lists live on the node
object [ref: p2pnetwork/node.py:46-52] — captured/re-applied via
:func:`topology_state` / :func:`apply_topology_state`.

Format: a single ``.npz`` (atomic rename on save). The state's tree
structure is recorded so loads verify against the template; arrays come
back as numpy and are device-put lazily by the first jitted use.

For sharded / multi-host runs use :func:`save_orbax` / :func:`load_orbax`:
the npz path funnels every shard through one host, while orbax writes each
process's shards in parallel and restores arrays WITH their shardings (the
template's shardings are applied on load, so a resumed multi-chip run does
not round-trip through host memory).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import tempfile
import zipfile
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file failed integrity verification.

    Raised instead of the bare ``zipfile.BadZipFile`` / ``zlib.error`` /
    ``ValueError`` soup a truncated or bit-flipped ``.npz`` produces — a
    crash-tolerant resume loop (supervise/store.py) needs to tell "this
    entry is damaged, skip to the previous one" apart from "the caller
    passed the wrong template" (which stays a ``ValueError``). Carries the
    file path plus, for hash mismatches, the expected and actual digests.
    """

    def __init__(self, path: str, detail: str = "",
                 expected: Optional[str] = None, actual: Optional[str] = None):
        self.path = path
        self.expected = expected
        self.actual = actual
        msg = f"corrupt checkpoint {path!r}"
        if expected is not None:
            msg += f": content hash mismatch (expected {expected}, got {actual})"
        elif detail:
            msg += f": {detail}"
        super().__init__(msg)


#: npz entry carrying the content digest; excluded from its own hash.
_DIGEST_KEY = "__sha256__"


def _payload_digest(payload: Dict[str, np.ndarray]) -> str:
    """sha256 over every payload entry (name, dtype, shape, raw bytes), in
    sorted-name order — the integrity hash ``save`` embeds and ``load``
    verifies. Deterministic across processes: no pickled objects, no dict
    order dependence."""
    h = hashlib.sha256()
    for name in sorted(payload):
        if name == _DIGEST_KEY:
            continue
        arr = np.ascontiguousarray(payload[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def topology_state(graph) -> Dict[str, Any]:
    """The graph's runtime-mutable leaves, as a checkpointable pytree.

    The reference's topology IS its state — the peer lists live on the node
    object [ref: p2pnetwork/node.py:46-52] — so a faithful checkpoint must
    capture what failures (sim/failures.py) and dynamic links
    (sim/topology.py) did to the graph: liveness masks, degrees, the
    dynamic edge region, and the derived masks of every attached
    aggregation representation. The static arrays (edge lists, neighbor
    ids, kernel layouts) are NOT stored — they are reconstructed by
    attaching the same pristine graph and re-applying this state via
    :func:`apply_topology_state`.
    """
    ts: Dict[str, Any] = {
        "node_mask": graph.node_mask,
        "edge_mask": graph.edge_mask,
        "in_degree": graph.in_degree,
        "out_degree": graph.out_degree,
    }
    if graph.neighbor_mask is not None:
        ts["neighbor_mask"] = graph.neighbor_mask
    if graph.dyn_senders is not None:
        ts["dyn_senders"] = graph.dyn_senders
        ts["dyn_receivers"] = graph.dyn_receivers
        ts["dyn_mask"] = graph.dyn_mask
    if graph.blocked is not None:
        ts["blocked_mask"] = graph.blocked.mask
    if graph.hybrid is not None:
        ts["hybrid_masks"] = graph.hybrid.masks
        if graph.hybrid.remainder is not None:
            ts["hybrid_remainder_mask"] = graph.hybrid.remainder.mask
    return ts


def apply_topology_state(graph, ts: Dict[str, Any]):
    """Re-apply a :func:`topology_state` onto a structurally-equal graph.

    ``graph`` must carry the same representations (dynamic capacity,
    neighbor table, blocked/hybrid layouts) and shapes as the graph the
    state was saved from — typically the same pristine construction the
    original run attached. Returns a new Graph whose mutation state
    (failed nodes, cut edges, runtime links, degrees) is exactly the
    saved one.
    """
    def _shape(name, current):
        saved_shape = tuple(np.shape(ts[name]))
        if current is None or saved_shape != tuple(current.shape):
            raise ValueError(
                f"topology state mismatch for {name!r}: saved shape "
                f"{saved_shape}, graph has "
                f"{None if current is None else tuple(current.shape)} — "
                f"attach the same graph construction the checkpoint came from"
            )

    ts = {k: jax.numpy.asarray(v) for k, v in ts.items()}  # npz gives numpy;
    # raw numpy leaves would break .at[] updates (connect after restore) and
    # re-pay host->device transfer on every subsequent jit call.

    expected = set(topology_state(graph).keys())
    got = set(ts.keys())
    drop_neighbor_table = False
    if expected - got == {"neighbor_mask"} and not graph.neighbors_complete:
        # The checkpointed run dropped its width-capped neighbor table
        # (fail_edges on an incomplete table loses the slot->edge map);
        # mirror that on the attached graph instead of rejecting a valid
        # checkpoint the docs say to restore onto the pristine build.
        drop_neighbor_table = True
        expected.discard("neighbor_mask")
    if expected != got:
        raise ValueError(
            f"topology state keys mismatch: checkpoint has {sorted(got)}, "
            f"attached graph expects {sorted(expected)} — attach a graph "
            f"with the same representations (capacity, neighbor table, "
            f"blocked/hybrid) as the one checkpointed"
        )

    for name, cur in (
        ("node_mask", graph.node_mask),
        ("edge_mask", graph.edge_mask),
        ("in_degree", graph.in_degree),
        ("out_degree", graph.out_degree),
    ):
        _shape(name, cur)
    kw: Dict[str, Any] = {
        "node_mask": ts["node_mask"],
        "edge_mask": ts["edge_mask"],
        "in_degree": ts["in_degree"],
        "out_degree": ts["out_degree"],
    }
    if "neighbor_mask" in ts:
        _shape("neighbor_mask", graph.neighbor_mask)
        kw["neighbor_mask"] = ts["neighbor_mask"]
    elif drop_neighbor_table:
        kw["neighbors"] = None
        kw["neighbor_mask"] = None
    if "dyn_senders" in ts:
        _shape("dyn_senders", graph.dyn_senders)
        kw["dyn_senders"] = ts["dyn_senders"]
        kw["dyn_receivers"] = ts["dyn_receivers"]
        kw["dyn_mask"] = ts["dyn_mask"]
    if "blocked_mask" in ts:
        _shape("blocked_mask", graph.blocked.mask)
        kw["blocked"] = dataclasses.replace(graph.blocked, mask=ts["blocked_mask"])
    if "hybrid_masks" in ts:
        _shape("hybrid_masks", graph.hybrid.masks)
        remainder = graph.hybrid.remainder
        if "hybrid_remainder_mask" in ts:
            _shape("hybrid_remainder_mask", remainder.mask)
            remainder = dataclasses.replace(
                remainder, mask=ts["hybrid_remainder_mask"]
            )
        kw["hybrid"] = dataclasses.replace(
            graph.hybrid, masks=ts["hybrid_masks"], remainder=remainder
        )
    return dataclasses.replace(graph, **kw)


def grow_state(state: Any, template: Any) -> Any:
    """Zero-extend every leaf of ``state`` into ``template``'s shapes — the
    repad-compatibility half of ``Graph.grow``.

    A checkpoint written at node capacity ``N_pad`` holds per-node leaves of
    width ``N_pad``; after a geometric repad the resumed run's template is
    wider. Growth padding is all-dead (``node_mask`` False), and zero IS the
    canonical state value for dead padding in every shipped protocol (init
    masks by liveness), so zero-extension makes resume-across-repad
    bit-identical to an uninterrupted grown run — tests/test_graftchurn.py
    pins that.

    Each leaf must match its template leaf's dtype and rank, and be no
    larger along any axis (growth only — shrinking would drop state);
    otherwise ``ValueError``, which :meth:`CheckpointStore.load_latest`
    counts as a template mismatch and skips past. Leaves whose shapes
    already match pass through untouched (the no-repad case is identity).
    """
    s_leaves, s_def = jax.tree_util.tree_flatten(state)
    t_leaves, t_def = jax.tree_util.tree_flatten(template)
    if str(s_def) != str(t_def):
        raise ValueError(
            f"state structure mismatch:\n  state: {s_def}\n  template: {t_def}")
    out = []
    for i, (s, t) in enumerate(zip(s_leaves, t_leaves)):
        t_shape = tuple(np.shape(t))
        t_dtype = np.dtype(getattr(t, "dtype", None) or np.asarray(t).dtype)  # graftlint: ignore[host-sync-in-loop] -- dtype probe of a dtype-less leaf (a Python scalar); no device transfer
        s_shape = tuple(np.shape(s))
        s_dtype = np.dtype(getattr(s, "dtype", None) or np.asarray(s).dtype)  # graftlint: ignore[host-sync-in-loop] -- dtype probe of a dtype-less leaf (a Python scalar); no device transfer
        if s_shape == t_shape and s_dtype == t_dtype:
            out.append(s)
            continue
        if (s_dtype != t_dtype or len(s_shape) != len(t_shape)
                or any(a > b for a, b in zip(s_shape, t_shape))):
            raise ValueError(
                f"state leaf {i} is not repad-growable: saved "
                f"{s_dtype}{s_shape}, template {t_dtype}{t_shape} — a "
                f"repad-compatible leaf matches dtype and rank and only "
                f"grows along axes")
        grown = np.zeros(t_shape, s_dtype)
        grown[tuple(slice(0, d) for d in s_shape)] = np.asarray(  # graftlint: ignore[host-sync-in-loop] -- zero-extension IS a host splice of every grown leaf; once per resume, not per round
            jax.device_get(s))  # graftlint: ignore[host-sync-in-loop] -- one audited pull per grown leaf, once per resume
        out.append(grown)
    return jax.tree_util.tree_unflatten(s_def, out)


def load_node_payload(path: str, graph, protocol_state_template) -> Tuple[
        Dict[str, Any], jax.Array, int, int]:
    """Load a JaxSimNode checkpoint (payload dict with ``protocol``,
    ``topology``, ``churn_count`` keys) written by
    ``JaxSimNode.save_checkpoint``.

    Owns the format-level tolerances:

    - A run that hit ``fail_edges`` on a width-capped neighbor table
      dropped the table, so its checkpoint legitimately lacks
      ``neighbor_mask`` — when the straight load rejects the structure and
      the attached graph's table is droppable (incomplete), retry with a
      table-less template and let :func:`apply_topology_state` mirror the
      drop.
    - Legacy checkpoints (pre-topology format: the protocol state was the
      root pytree) still load — they carry no topology, so the graph
      resumes exactly as attached.
    """
    ts_template = topology_state(graph)

    def _template(ts):
        return {
            "protocol": protocol_state_template,
            "topology": ts,
            "churn_count": np.int64(0),
        }

    try:
        return load(path, _template(ts_template))
    except ValueError as err:
        if "neighbor_mask" in ts_template and not graph.neighbors_complete:
            ts2 = dict(ts_template)
            ts2.pop("neighbor_mask")
            try:
                return load(path, _template(ts2))
            except ValueError:
                pass
        try:
            state, key, rnd, msgs = load(path, protocol_state_template)
        except ValueError:
            raise err  # genuinely mismatched, not just old-format
        payload = {
            "protocol": state,
            "topology": topology_state(graph),  # as-attached (no-op apply)
            "churn_count": np.int64(0),
        }
        return payload, key, rnd, msgs


def save(path: str, state: Any, key: jax.Array, round_index: int,
         message_count: int = 0) -> None:
    """Atomically write (state pytree, PRNG key, round counter, message
    counter) to ``path``, with an embedded content hash ``load`` verifies
    (a bit-flipped or truncated file raises :class:`CheckpointCorrupt`
    instead of resuming from garbage)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    payload = {f"leaf_{i}": np.asarray(jax.device_get(x)) for i, x in enumerate(leaves)}
    payload["__key__"] = np.asarray(jax.random.key_data(key))
    payload["__round__"] = np.asarray(round_index, dtype=np.int64)
    payload["__messages__"] = np.asarray(message_count, dtype=np.int64)
    payload["__treedef__"] = np.frombuffer(str(treedef).encode(), dtype=np.uint8)
    payload[_DIGEST_KEY] = np.frombuffer(
        _payload_digest(payload).encode(), dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str, template: Any, *,
         grow: bool = False) -> Tuple[Any, jax.Array, int, int]:
    """Load a checkpoint written by :func:`save`.

    ``template`` is a state pytree with the same structure (e.g. a freshly
    built ``protocol.init(...)``); its treedef validates the file.
    Returns ``(state, key, round_index, message_count)``.

    ``grow=True`` makes the template repad-compatible: a file whose leaves
    are *smaller* than the template's (written before a ``Graph.grow``
    capacity repad) is zero-extended into the grown shapes via
    :func:`grow_state`; leaves that cannot grow into the template (dtype or
    rank change, shrink) stay a ``ValueError``.

    Integrity: a file carrying the embedded content hash (every checkpoint
    written since the hash landed in the format) is verified against it; a
    truncated, bit-flipped, or otherwise unreadable file raises
    :class:`CheckpointCorrupt` (file + expected/actual hash), never a bare
    ``zipfile``/``zlib`` error. Old hashless files load unverified for
    back-compat. A structure mismatch against ``template`` stays a
    ``ValueError`` — that is a caller error, not file damage.
    """
    try:
        # Read every member eagerly inside the guard: npz members load
        # lazily, so a file truncated mid-member only fails at access time.
        with np.load(path) as data:
            payload = {k: np.asarray(data[k]) for k in data.files}
    except (zipfile.BadZipFile, OSError, EOFError, KeyError, ValueError) as e:
        raise CheckpointCorrupt(
            path, detail=f"{type(e).__name__}: {e}") from e
    if _DIGEST_KEY in payload:
        stored_digest = bytes(payload[_DIGEST_KEY]).decode()
        actual = _payload_digest(payload)
        if stored_digest != actual:
            raise CheckpointCorrupt(path, expected=stored_digest,
                                    actual=actual)
    if "__treedef__" not in payload or "__round__" not in payload \
            or "__key__" not in payload:
        raise CheckpointCorrupt(
            path, detail="missing checkpoint bookkeeping entries "
            "(not a sim/checkpoint.py file, or truncated before the "
            "hash format)")
    _, treedef = jax.tree_util.tree_flatten(template)
    stored = bytes(payload["__treedef__"]).decode()
    if stored != str(treedef):
        raise ValueError(
            f"checkpoint structure mismatch:\n  file: {stored}\n  template: {treedef}"
        )
    n = len([k for k in payload if k.startswith("leaf_")])
    leaves = [payload[f"leaf_{i}"] for i in range(n)]
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if grow:
        state = grow_state(state, template)
    key = jax.random.wrap_key_data(payload["__key__"])
    messages = int(payload["__messages__"]) if "__messages__" in payload else 0
    return state, key, int(payload["__round__"]), messages


def save_orbax(path: str, state: Any, key: jax.Array, round_index: int,
               message_count: int = 0) -> None:
    """Checkpoint via orbax (sharding-preserving, multi-host-parallel).

    ``path`` is a directory (created/overwritten). All hosts of a
    multi-process job must call this collectively.
    """
    import orbax.checkpoint as ocp

    payload = {
        "state": state,
        # Host numpy, not a device array: a single-device jax.Array is
        # "host-local" to orbax in a multi-process job and refuses to
        # serialize; the key is tiny and identical on every process.
        "key_data": np.asarray(jax.random.key_data(key)),
        # 0-d ndarrays, not np.int64 scalars: this image's orbax rejects
        # numpy GENERIC scalars as unsupported leaf types.
        "round_index": np.asarray(round_index, dtype=np.int64),
        "message_count": np.asarray(message_count, dtype=np.int64),
    }
    # Context-manage: each StandardCheckpointer owns async worker threads;
    # a checkpoint-every-N-rounds loop must not leak one pool per save.
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), payload, force=True)
        ckptr.wait_until_finished()


def load_orbax(path: str, template: Any) -> Tuple[Any, jax.Array, int, int]:
    """Restore a :func:`save_orbax` checkpoint.

    ``template`` supplies structure, dtypes, AND shardings: pass a state
    built the way the resumed run will use it (e.g. on the same mesh) and
    the restored arrays land sharded the same way, no host round-trip.
    Returns ``(state, key, round_index, message_count)``.
    """
    import orbax.checkpoint as ocp

    # Every leaf gets an explicit sharding: omitting one makes orbax fall
    # back to the sharding recorded at save time, which it documents as
    # unsafe across device topologies — exactly the resume-on-a-different-
    # slice case this API exists for. Leaves without a sharding (and the
    # bookkeeping scalars) are replicated over the template's mesh when it
    # has one, else placed on the default device.
    meshes = [
        leaf.sharding.mesh
        for leaf in jax.tree.leaves(template)
        if isinstance(getattr(leaf, "sharding", None), jax.sharding.NamedSharding)
    ]
    if meshes:
        default_sharding = jax.sharding.NamedSharding(
            meshes[0], jax.sharding.PartitionSpec()
        )
    else:
        default_sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    def abstract(x):
        x = jax.numpy.asarray(x)
        sharding = getattr(x, "sharding", None) or default_sharding
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    key_data = jax.random.key_data(jax.random.key(0))
    target = {
        "state": jax.tree.map(abstract, template),
        "key_data": jax.ShapeDtypeStruct(
            key_data.shape, key_data.dtype, sharding=default_sharding
        ),
        "round_index": jax.ShapeDtypeStruct((), np.int64, sharding=default_sharding),
        "message_count": jax.ShapeDtypeStruct((), np.int64, sharding=default_sharding),
    }
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.abspath(path), target)
    key = jax.random.wrap_key_data(restored["key_data"])
    return (
        restored["state"],
        key,
        int(restored["round_index"]),
        int(restored["message_count"]),
    )


# ------------------------------------------------------ graph persistence

#: Array-valued Graph fields serialized by save_graph (optionals skipped
#: when None); static ints/bools/tuples travel in the JSON meta record.
_GRAPH_ARRAYS = (
    "senders", "receivers", "edge_mask", "node_mask", "in_degree",
    "out_degree", "neighbors", "neighbor_mask", "dyn_senders",
    "dyn_receivers", "dyn_mask", "src_eid", "src_offsets", "edge_weight",
    "neighbor_weight", "layout_perm", "layout_inv",
)


def save_graph(path: str, graph) -> None:
    """Atomically persist a built :class:`~p2pnetwork_tpu.sim.graph.Graph`
    — including kernel layouts (blocked/hybrid/source-CSR), weights, the
    dynamic region, and any liveness re-masking — as one ``.npz``.

    The complement of the state checkpoints above: graph CONSTRUCTION is
    the host-side cost at scale (tens of seconds for the 100M-edge build,
    BENCH.md), so a pipeline that reuses a topology should pay it once.
    No pickle: arrays plus a JSON record of the static fields.
    """
    import json

    payload: Dict[str, Any] = {}
    meta: Dict[str, Any] = {
        "version": 1,
        "n_nodes": graph.n_nodes,
        "n_edges": graph.n_edges,
        "neighbors_complete": graph.neighbors_complete,
        "max_degree_cap": graph.max_degree_cap,
        "edge_pad_multiple": graph.edge_pad_multiple,
        "max_in_span": graph.max_in_span,
        "max_out_span": graph.max_out_span,
    }
    # One pytree transfer for every present array rather than a
    # device_get per field: device_get batches the whole dict into a
    # single device->host round trip (graftlint host-sync-in-loop).
    present = {name: getattr(graph, name) for name in _GRAPH_ARRAYS
               if getattr(graph, name) is not None}
    payload.update({name: np.asarray(v)
                    for name, v in jax.device_get(present).items()})
    if graph.blocked is not None:
        meta["blocked_block"] = graph.blocked.block
        payload["blocked_src"] = np.asarray(jax.device_get(graph.blocked.src))
        payload["blocked_local_dst"] = np.asarray(
            jax.device_get(graph.blocked.local_dst))
        payload["blocked_mask"] = np.asarray(
            jax.device_get(graph.blocked.mask))
    if graph.skew is not None:
        payload["skew_src"] = np.asarray(jax.device_get(graph.skew.src))
        payload["skew_mask"] = np.asarray(jax.device_get(graph.skew.mask))
        payload["skew_owner"] = np.asarray(jax.device_get(graph.skew.owner))
        payload["skew_start"] = np.asarray(jax.device_get(graph.skew.start))
        if graph.skew.weight is not None:
            payload["skew_weight"] = np.asarray(
                jax.device_get(graph.skew.weight))
    if graph.hybrid is not None:
        meta["hybrid_offsets"] = list(graph.hybrid.offsets)
        meta["hybrid_n"] = graph.hybrid.n
        payload["hybrid_masks"] = np.asarray(
            jax.device_get(graph.hybrid.masks))
        rem = graph.hybrid.remainder
        if rem is not None:
            meta["hybrid_rem_block"] = rem.block
            payload["hybrid_rem_src"] = np.asarray(jax.device_get(rem.src))
            payload["hybrid_rem_local_dst"] = np.asarray(
                jax.device_get(rem.local_dst))
            payload["hybrid_rem_mask"] = np.asarray(jax.device_get(rem.mask))
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)

    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_graph(path: str):
    """Load a graph written by :func:`save_graph` (arrays land on the
    default device lazily, via the first jitted use)."""
    import json

    import jax.numpy as jnp

    from p2pnetwork_tpu.ops.blocked import BlockedEdges
    from p2pnetwork_tpu.ops.diag import HybridEdges
    from p2pnetwork_tpu.sim.graph import Graph

    with np.load(path) as data:
        meta = json.loads(bytes(data["__meta__"]).decode())
        if meta.get("version") != 1:
            raise ValueError(f"unknown graph file version: {meta.get('version')}")
        # Absent arrays were None at save time and must load back as None
        # explicitly: neighbors/neighbor_mask have no dataclass default, so
        # omitting them raises for any graph saved with
        # build_neighbor_table=False (the 10M bench config).
        fields: Dict[str, Any] = {
            name: jnp.asarray(data[name]) if name in data.files else None
            for name in _GRAPH_ARRAYS
        }
        blocked = None
        if "blocked_src" in data.files:
            blocked = BlockedEdges(
                src=jnp.asarray(data["blocked_src"]),
                local_dst=jnp.asarray(data["blocked_local_dst"]),
                mask=jnp.asarray(data["blocked_mask"]),
                block=int(meta["blocked_block"]),
            )
        skew = None
        if "skew_src" in data.files:
            from p2pnetwork_tpu.ops.skew import SkewTable

            skew = SkewTable(
                src=jnp.asarray(data["skew_src"]),
                mask=jnp.asarray(data["skew_mask"]),
                owner=jnp.asarray(data["skew_owner"]),
                start=jnp.asarray(data["skew_start"]),
                weight=(jnp.asarray(data["skew_weight"])
                        if "skew_weight" in data.files else None),
            )
        hybrid = None
        if "hybrid_masks" in data.files:
            rem = None
            if "hybrid_rem_src" in data.files:
                rem = BlockedEdges(
                    src=jnp.asarray(data["hybrid_rem_src"]),
                    local_dst=jnp.asarray(data["hybrid_rem_local_dst"]),
                    mask=jnp.asarray(data["hybrid_rem_mask"]),
                    block=int(meta["hybrid_rem_block"]),
                )
            hybrid = HybridEdges(
                masks=jnp.asarray(data["hybrid_masks"]),
                remainder=rem,
                offsets=tuple(meta["hybrid_offsets"]),
                n=int(meta["hybrid_n"]),
            )
        cap = meta.get("max_degree_cap")  # absent in pre-cap files
        return Graph(
            n_nodes=int(meta["n_nodes"]),
            n_edges=int(meta["n_edges"]),
            neighbors_complete=bool(meta["neighbors_complete"]),
            max_degree_cap=None if cap is None else int(cap),
            edge_pad_multiple=int(meta.get("edge_pad_multiple", 128)),
            max_in_span=int(meta["max_in_span"]),
            max_out_span=int(meta["max_out_span"]),
            blocked=blocked,
            hybrid=hybrid,
            skew=skew,
            **fields,
        )
