"""Checkpoint / resume for simulation runs.

The reference has no persistence of any kind — node ids are regenerated per
run [ref: p2pnetwork/node.py:85-90] (SURVEY.md section 5 "Checkpoint").
For multi-million-node simulations, resumability is table stakes: a
checkpoint is the protocol state pytree plus the PRNG key and round counter
— everything needed to make a resumed run bit-identical to an uninterrupted
one (tests/test_checkpoint.py proves that).

Format: a single ``.npz`` (atomic rename on save). The state's tree
structure is recorded so loads verify against the template; arrays come
back as numpy and are device-put lazily by the first jitted use.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Optional, Tuple

import jax
import numpy as np


def save(path: str, state: Any, key: jax.Array, round_index: int,
         message_count: int = 0) -> None:
    """Atomically write (state pytree, PRNG key, round counter, message
    counter) to ``path``."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    payload = {f"leaf_{i}": np.asarray(jax.device_get(x)) for i, x in enumerate(leaves)}
    payload["__key__"] = np.asarray(jax.random.key_data(key))
    payload["__round__"] = np.asarray(round_index, dtype=np.int64)
    payload["__messages__"] = np.asarray(message_count, dtype=np.int64)
    payload["__treedef__"] = np.frombuffer(str(treedef).encode(), dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str, template: Any) -> Tuple[Any, jax.Array, int, int]:
    """Load a checkpoint written by :func:`save`.

    ``template`` is a state pytree with the same structure (e.g. a freshly
    built ``protocol.init(...)``); its treedef validates the file.
    Returns ``(state, key, round_index, message_count)``.
    """
    with np.load(path) as data:
        _, treedef = jax.tree_util.tree_flatten(template)
        stored = bytes(data["__treedef__"]).decode()
        if stored != str(treedef):
            raise ValueError(
                f"checkpoint structure mismatch:\n  file: {stored}\n  template: {treedef}"
            )
        n = len([k for k in data.files if k.startswith("leaf_")])
        leaves = [data[f"leaf_{i}"] for i in range(n)]
        state = jax.tree_util.tree_unflatten(treedef, leaves)
        key = jax.random.wrap_key_data(data["__key__"])
        messages = int(data["__messages__"]) if "__messages__" in data.files else 0
        return state, key, int(data["__round__"]), messages
