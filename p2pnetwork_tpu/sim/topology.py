"""Dynamic topology: peers joining and links forming at runtime.

The reference mutates topology freely — ``connect_with_node`` adds a live
peer [ref: node.py:122], the accept loop admits inbound ones
[ref: node.py:227-280]. XLA programs have static shapes, so the sim
backend's version is capacity planning (SURVEY.md section 7 hard part 4):

- **Node capacity** already exists: ``node_mask`` padding rows are
  allocated-but-dead peers, and :func:`join_node` activates one.
- **Edge capacity** is a *dynamic edge region*: ``with_capacity`` reserves
  ``extra_edges`` slots in separate (unsorted) COO arrays; :func:`connect`
  fills the next free slots device-side. Every aggregation method folds
  the dynamic region in through one extra (unsorted) segment pass
  (ops/segment.py), so flood/SIR/gossip aggregation see new links
  immediately with no recompile and no rebuild.

Static-layout representations that bake in edge order (neighbor table for
partner *sampling*, blocked/hybrid kernel layouts for the *static* edges)
keep serving the static edges; the dynamic region rides alongside them.
Leaves are sim/failures.py. When the dynamic region fills up or churn
accumulates, :func:`consolidate` rebuilds via ``from_edges`` with the
merged live edge list (one-off host cost, amortized over many rounds).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.sim.graph import Graph, _round_up


def with_capacity(graph: Graph, extra_edges: int = 0,
                  extra_nodes: int = 0) -> Graph:
    """Reserve headroom for runtime topology growth (host-side, one-off).

    ``extra_nodes`` grows the node padding (new dead rows to activate
    later); ``extra_edges`` allocates the dynamic edge region. Node growth
    changes array shapes, so do it at build time, before compiling.
    """
    g = graph
    if extra_nodes:
        n_pad_new = _round_up(g.n_nodes_padded + extra_nodes, 128)
        grow = n_pad_new - g.n_nodes_padded
        pad1 = lambda x, fill=0: jnp.pad(x, (0, grow), constant_values=fill)  # noqa: E731
        neighbors = g.neighbors
        neighbor_mask = g.neighbor_mask
        if neighbors is not None:
            neighbors = jnp.pad(neighbors, ((0, grow), (0, 0)))
            neighbor_mask = jnp.pad(neighbor_mask, ((0, grow), (0, 0)))
        if g.blocked is not None or g.hybrid is not None:
            raise ValueError(
                "with_capacity(extra_nodes=...) on a graph carrying "
                "blocked/hybrid layouts: build those after growing, or "
                "pass capacity to the generator instead"
            )
        src_offsets = g.src_offsets
        if src_offsets is not None:
            # Grown nodes have empty build-time out-rows: extend the offset
            # array with its end value, preserving the i32[N_pad + 1]
            # invariant (models/adaptive_flood.py reads offsets[v+1]).
            src_offsets = jnp.pad(src_offsets, (0, grow), mode="edge")
        g = dataclasses.replace(
            g,
            node_mask=pad1(g.node_mask, False),
            in_degree=pad1(g.in_degree),
            out_degree=pad1(g.out_degree),
            neighbors=neighbors,
            neighbor_mask=neighbor_mask,
            src_offsets=src_offsets,
        )
    if extra_edges:
        k = _round_up(extra_edges, 128)
        if g.dyn_senders is not None:
            # Grow the existing region — replacing it would silently drop
            # every runtime link made so far.
            g = dataclasses.replace(
                g,
                dyn_senders=jnp.pad(g.dyn_senders, (0, k)),
                dyn_receivers=jnp.pad(g.dyn_receivers, (0, k)),
                dyn_mask=jnp.pad(g.dyn_mask, (0, k)),
            )
        else:
            g = dataclasses.replace(
                g,
                dyn_senders=jnp.zeros(k, jnp.int32),
                dyn_receivers=jnp.zeros(k, jnp.int32),
                dyn_mask=jnp.zeros(k, bool),
            )
    return g


def _require_dynamic(graph: Graph) -> None:
    if graph.dyn_senders is None:
        raise ValueError(
            "no dynamic edge capacity: build with "
            "topology.with_capacity(graph, extra_edges=...) first"
        )


def static_edge_exists(graph: Graph, s: jax.Array, r: jax.Array) -> jax.Array:
    """bool[B]: is each directed (s, r) pair a live STATIC edge?

    The COO is receiver-sorted, so each receiver's in-edges are one
    contiguous run no wider than ``graph.max_in_span`` (static metadata
    from the build). One ``searchsorted`` per query plus a
    ``[B, max_in_span]`` window scan — O(B log E + B * max_deg), sublinear
    in E, vs the O(B * E) broadcast compare this replaces. Graphs predating
    ``max_in_span`` (== 0) fall back to the broadcast compare. Shared by
    runtime connect's duplicate guard and the wedge-closure sampler
    (models/triangles.py) — one probe, one set of edge cases.
    """
    if graph.max_in_span > 0:
        lo = jnp.searchsorted(graph.receivers, r, side="left")
        idx = lo[:, None] + jnp.arange(graph.max_in_span, dtype=jnp.int32)[None, :]
        idx = jnp.minimum(idx, graph.n_edges_padded - 1)
        return jnp.any(
            (graph.receivers[idx] == r[:, None])
            & (graph.senders[idx] == s[:, None])
            & graph.edge_mask[idx],
            axis=1,
        )
    return jnp.any(
        (graph.senders[None, :] == s[:, None])
        & (graph.receivers[None, :] == r[:, None])
        & graph.edge_mask[None, :],
        axis=1,
    )


def _edge_exists(graph: Graph, s: jax.Array, r: jax.Array) -> jax.Array:
    """bool[B]: is each directed (s, r) pair already a live edge (static or
    dynamic)? The dynamic region is unsorted by design, but its capacity K
    is small — the brute compare there is the cheap part."""
    static = static_edge_exists(graph, s, r)
    dyn = jnp.any(
        (graph.dyn_senders[None, :] == s[:, None])
        & (graph.dyn_receivers[None, :] == r[:, None])
        & graph.dyn_mask[None, :],
        axis=1,
    )
    return static | dyn


def connect(graph: Graph, senders, receivers, *,
            undirected: bool = True, check_capacity: bool = True) -> Graph:
    """Add links at runtime (device-side; no recompile).

    Fills the next free dynamic slots. ``undirected=True`` (the
    reference's TCP-connection semantic: traffic flows both ways
    [ref: nodeconnection.py]) stores both directions. Connecting an
    already-connected pair is a no-op, like the reference's duplicate
    ``connect_with_node`` [ref: node.py:136-139] — a silent parallel edge
    would double-count infection pressure and inflate degrees. A link with
    a DEAD endpoint is likewise dropped (the reference's connect to a
    crashed peer fails [ref: node.py:173-176]); it also keeps
    fail-then-connect and connect-then-fail equivalent, since the liveness
    re-mask only sees links that exist when it runs.

    ``check_capacity=True`` verifies slot headroom and id bounds host-side,
    which forces a device sync per call when the ids live on device. For
    sustained churn, pass ``check_capacity=False`` (and guarantee capacity
    and bounds): every step is then pure device work — async-dispatchable,
    jittable, no host round-trip — and an overflow still drops the excess
    entries whole instead of corrupting slots (see the degree bookkeeping
    below).
    """
    _require_dynamic(graph)
    from p2pnetwork_tpu.sim.failures import _check_ids_in_range

    if check_capacity:
        _check_ids_in_range(senders, graph.n_nodes_padded, "node")
        _check_ids_in_range(receivers, graph.n_nodes_padded, "node")
    s = jnp.asarray(senders, jnp.int32).reshape(-1)
    r = jnp.asarray(receivers, jnp.int32).reshape(-1)
    if undirected:
        s, r = jnp.concatenate([s, r]), jnp.concatenate([r, s])
    # Drop pairs that already exist, and duplicates within the batch (keep
    # each pair's first occurrence). Componentwise compare — an encoded
    # s * n_pad + r key would silently wrap in int32 (x64 is off) for
    # million-node graphs and alias distinct pairs.
    dup_prior = (
        (s[:, None] == s[None, :])
        & (r[:, None] == r[None, :])
        & jnp.tril(jnp.ones((s.size, s.size), bool), k=-1)
    )
    # Dead endpoints reject the link, like the reference's connect to a
    # crashed peer failing [ref: node.py:173-176] — otherwise
    # fail-then-connect and connect-then-fail would leave different live
    # link sets for the same topology (the liveness re-mask only sees
    # links that exist when it runs).
    valid = (~_edge_exists(graph, s, r) & ~dup_prior.any(axis=1)
             & graph.node_mask[s] & graph.node_mask[r])
    free = ~graph.dyn_mask
    if check_capacity:
        try:
            if int(jnp.sum(valid)) > int(jnp.sum(free)):
                raise ValueError(
                    f"dynamic edge region full "
                    f"({graph.dyn_senders.shape[0]} slots); consolidate with "
                    f"from_edges or reserve more via with_capacity"
                )
        except jax.errors.ConcretizationTypeError:
            pass  # traced: caller guarantees capacity
    # First-free-slot allocation: disconnect() leaves holes, and writing at
    # used-count would overwrite live edges past them. Valid entries are
    # compacted onto the free slots (pos = rank among valid entries), so a
    # batch mixing duplicates with new links never consumes slots for the
    # duplicates. Entries past the real free count — and invalid entries —
    # get the out-of-bounds sentinel K, which scatter drops instead of
    # silently destroying whatever edge lives in slot 0.
    K = graph.dyn_mask.shape[0]
    free_slots = jnp.nonzero(free, size=K, fill_value=K)[0]
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    applied = valid & (pos < jnp.sum(free.astype(jnp.int32)))
    slots = jnp.where(applied, free_slots[jnp.clip(pos, 0, K - 1)], K)
    dyn_s = graph.dyn_senders.at[slots].set(s, mode="drop")
    dyn_r = graph.dyn_receivers.at[slots].set(r, mode="drop")
    dyn_m = graph.dyn_mask.at[slots].set(True, mode="drop")
    # Degrees track only the entries actually written, so even a traced
    # overflow (where the host-side check above cannot run) leaves degrees
    # and edges consistent — the overflow entries are dropped whole.
    add = applied.astype(jnp.int32)
    in_degree = graph.in_degree.at[r].add(add)
    out_degree = graph.out_degree.at[s].add(add)
    return dataclasses.replace(
        graph,
        dyn_senders=dyn_s,
        dyn_receivers=dyn_r,
        dyn_mask=dyn_m,
        in_degree=in_degree,
        out_degree=out_degree,
    )


def disconnect(graph: Graph, senders, receivers, *,
               undirected: bool = True) -> Graph:
    """Remove dynamic links (matched by endpoint pair; static edges are
    removed with sim/failures.py)."""
    _require_dynamic(graph)
    s = jnp.asarray(senders, jnp.int32).reshape(-1)
    r = jnp.asarray(receivers, jnp.int32).reshape(-1)
    if undirected:
        s, r = jnp.concatenate([s, r]), jnp.concatenate([r, s])
    hit = (
        (graph.dyn_senders[:, None] == s[None, :])
        & (graph.dyn_receivers[:, None] == r[None, :])
    ).any(axis=1) & graph.dyn_mask
    in_degree = graph.in_degree - jax.ops.segment_sum(
        hit.astype(jnp.int32), graph.dyn_receivers,
        num_segments=graph.n_nodes_padded,
    )
    out_degree = graph.out_degree - jnp.zeros(
        graph.n_nodes_padded, jnp.int32
    ).at[graph.dyn_senders].add(hit.astype(jnp.int32))
    return dataclasses.replace(
        graph,
        dyn_mask=graph.dyn_mask & ~hit,
        in_degree=in_degree,
        out_degree=out_degree,
    )


def join_node(graph: Graph, node_id: int, peers) -> Graph:
    """Activate a spare (padding) node and connect it to ``peers`` — the
    sim analog of a new peer starting up and dialing its bootstrap set
    [ref: node.py:122]."""
    _require_dynamic(graph)
    from p2pnetwork_tpu.sim.failures import _check_ids_in_range

    _check_ids_in_range([node_id], graph.n_nodes_padded, "node")
    node_mask = graph.node_mask.at[node_id].set(True)
    g = dataclasses.replace(graph, node_mask=node_mask)
    peers = jnp.asarray(peers, jnp.int32).reshape(-1)
    return connect(g, jnp.full(peers.shape, node_id, jnp.int32), peers)


def consolidate(graph: Graph, *, extra_edges: int = 0, extra_nodes: int = 0,
                **from_edges_kwargs) -> Graph:
    """Fold accumulated churn into a fresh static representation — the
    documented consolidation path, as one call (one-off host cost,
    amortized over many rounds).

    The merged LIVE edge list (static + dynamic region) is rebuilt through
    :func:`p2pnetwork_tpu.sim.graph.from_edges` — runtime links become
    static edges (entering the neighbor table, so Gossip samples them),
    dead edges are dropped for good, and liveness is preserved: failed
    nodes stay failed, joined spare nodes stay alive (the rebuilt id space
    covers every live or referenced id). Kernel layouts
    (blocked/hybrid/source-CSR) carry over from the input graph by
    default — a population running ``method='hybrid'`` keeps running it —
    and can be toggled via ``from_edges_kwargs``.
    ``extra_edges`` / ``extra_nodes`` re-reserve growth capacity on the
    result. Propagation results are unchanged by construction
    (tests/test_topology.py asserts flood parity before/after)."""
    from p2pnetwork_tpu.sim.failures import with_node_liveness

    emask = np.asarray(graph.edge_mask)
    senders = np.asarray(graph.senders)[emask]
    receivers = np.asarray(graph.receivers)[emask]
    weights = None
    if graph.edge_weight is not None:
        weights = np.asarray(graph.edge_weight)[emask]
    if graph.dyn_mask is not None:
        dm = np.asarray(graph.dyn_mask)
        senders = np.concatenate(
            [senders, np.asarray(graph.dyn_senders)[dm]]
        )
        receivers = np.concatenate(
            [receivers, np.asarray(graph.dyn_receivers)[dm]]
        )
        if weights is not None:
            # Runtime links propagated at unit cost; consolidation bakes
            # that in as their static weight (ops/segment.py
            # DYNAMIC_LINK_COST).
            from p2pnetwork_tpu.ops.segment import DYNAMIC_LINK_COST

            weights = np.concatenate([
                weights,
                np.full(int(dm.sum()), DYNAMIC_LINK_COST, dtype=np.float32),
            ])
    alive = np.asarray(graph.node_mask)
    # The rebuilt id space must cover joined spare nodes (ids >=
    # n_nodes) and every edge endpoint.
    referenced = [graph.n_nodes]
    if alive.any():
        referenced.append(int(np.flatnonzero(alive).max()) + 1)
    if senders.size:
        referenced.append(int(max(senders.max(), receivers.max())) + 1)
    n_eff = max(referenced)

    from p2pnetwork_tpu.sim.graph import from_edges

    # Kernel layouts: default to what the input graph carried (a graph
    # running method='hybrid' must still run it after consolidation), let
    # kwargs override. With node growth they attach AFTER with_capacity
    # (which refuses to grow under a baked layout); otherwise they build
    # inside from_edges from the host arrays already in hand — no device
    # round trip.
    layout_kw = {
        "blocked": from_edges_kwargs.pop("blocked", graph.blocked is not None),
        "hybrid": from_edges_kwargs.pop("hybrid", graph.hybrid is not None),
        "source_csr": from_edges_kwargs.pop("source_csr",
                                            graph.src_eid is not None),
    }
    # Neighbor-table settings carry over like the kernel layouts do: a
    # graph built without one (the documented 10M-node path) must not get
    # an O(N·max_in_degree) table silently rebuilt host-side, and an
    # explicit width cap survives — the recorded from_edges(max_degree=)
    # value when the graph carries one (it bounds the rebuilt table even
    # if it never bit at build), else an incomplete table's width (old
    # checkpoints predating the recorded cap).
    from_edges_kwargs.setdefault("build_neighbor_table",
                                 graph.neighbors is not None)
    from_edges_kwargs.setdefault("edge_pad_multiple",
                                 graph.edge_pad_multiple)
    if graph.max_degree_cap is not None:
        from_edges_kwargs.setdefault("max_degree", graph.max_degree_cap)
    elif graph.neighbors is not None and not graph.neighbors_complete:
        from_edges_kwargs.setdefault("max_degree", graph.max_degree)
    defer_layouts = bool(extra_nodes)
    if not defer_layouts:
        from_edges_kwargs.update(layout_kw)
    if weights is not None:
        from_edges_kwargs.setdefault("weights", weights)
    g2 = from_edges(senders, receivers, n_eff, **from_edges_kwargs)
    # from_edges marks [0, n_eff) all-alive; re-apply the real liveness
    # (failed nodes stay failed; ids beyond the old padding stay dead).
    alive2 = np.zeros(g2.n_nodes_padded, dtype=bool)
    span = min(alive.shape[0], g2.n_nodes_padded)
    alive2[:span] = alive[:span]
    g2 = with_node_liveness(g2, jnp.asarray(alive2))
    if extra_edges or extra_nodes:
        g2 = with_capacity(g2, extra_edges=extra_edges,
                           extra_nodes=extra_nodes)
    if defer_layouts:
        if layout_kw["blocked"]:
            g2 = g2.with_blocked()
        if layout_kw["hybrid"]:
            g2 = g2.with_hybrid()
        if layout_kw["source_csr"]:
            g2 = g2.with_source_csr()
    return g2
