"""Round engine: compiled protocol execution.

The reference's runtime is its thread-and-poll loops (SURVEY.md section 1
"concurrency model"); the sim backend's runtime is this module — ``lax.scan``
over protocol rounds, compiled once, with per-round stats as device-side
reductions, plus a ``lax.while_loop`` variant for run-to-coverage with no
host round-trips (the north-star benchmark loop).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.utils import accum


@functools.partial(jax.jit, static_argnames=("protocol", "rounds"))
def run(graph: Graph, protocol, key: jax.Array, rounds: int):
    """Run ``rounds`` synchronous rounds from the protocol's initial state;
    returns (final_state, stacked stats).

    Stats come back as arrays of shape [rounds] per entry — the full
    per-round history of the device-side counters in one transfer.
    """
    return run_from(graph, protocol, protocol.init(graph, key), key, rounds)


@functools.partial(jax.jit, static_argnames=("protocol", "rounds"))
def run_from(graph: Graph, protocol, state, key: jax.Array, rounds: int):
    """Run ``rounds`` rounds continuing from an existing ``state`` (resume
    path — e.g. after loading a checkpoint, or incremental stepping from
    JaxSimNode)."""

    def body(carry, round_key):
        st, = carry
        st, stats = protocol.step(graph, st, round_key)
        return (st,), stats

    keys = jax.random.split(jax.random.fold_in(key, 1), rounds)
    (state,), stats = jax.lax.scan(body, (state,), keys)
    return state, stats


def run_until_coverage(
    graph: Graph,
    protocol,
    key: jax.Array,
    *,
    coverage_target: float = 0.99,
    max_rounds: int = 1024,
):
    """Run until ``stats['coverage'] >= coverage_target`` (or max_rounds).

    Device-side early exit via ``lax.while_loop`` — the whole
    run-to-99%-coverage measurement executes as one XLA program (init
    included) with zero host synchronization per round. Returns
    (final_state, dict with ``rounds``, ``coverage``, ``messages`` totals;
    ``messages`` is an exact Python int — see
    :func:`run_until_coverage_from`).

    Requires the protocol's stats to include ``coverage`` and ``messages``
    (e.g. models.flood.Flood).
    """
    state, packed = _coverage_with_init(
        graph, protocol, key,
        coverage_target=coverage_target, max_rounds=max_rounds,
    )
    return state, _unpack_summary(packed)


def run_until_coverage_from(
    graph: Graph,
    protocol,
    state0,
    key: jax.Array,
    *,
    coverage_target: float = 0.99,
    max_rounds: int = 1024,
):
    """Run-to-coverage continuing from an existing ``state0`` (resume path).

    If the protocol exposes ``coverage(graph, state)`` (Flood, SIR do), the
    loop starts from the true coverage of ``state0`` — resuming an
    already-finished run executes zero rounds instead of one spurious one.

    ``messages`` in the returned dict is an exact Python int: the loop
    accumulates device-side in a two-limb (hi, lo) counter (utils/accum.py)
    so totals past 2^31 — routine at 10M-node scale — do not wrap int32.
    The whole summary (rounds, coverage, both limbs) comes back in ONE
    packed transfer — on tunneled backends every extra round trip is
    milliseconds.
    """
    state, packed = _coverage_loop(
        graph, protocol, state0, key,
        coverage_target=coverage_target, max_rounds=max_rounds,
    )
    return state, _unpack_summary(packed)


# One-transfer run summaries, shared with the sharded coverage loops.
_pack_summary = accum.pack_summary
_unpack_summary = accum.unpack_summary


def _coverage_body(graph, protocol, state0, key, coverage_target, max_rounds):
    def cond(carry):
        _, _, rounds, coverage, _, _ = carry
        return (coverage < coverage_target) & (rounds < max_rounds)

    def body(carry):
        state, k, rounds, _, hi, lo = carry
        k, sub = jax.random.split(k)
        state, stats = protocol.step(graph, state, sub)
        hi, lo = accum.add((hi, lo), stats["messages"])
        return (state, k, rounds + 1, stats["coverage"], hi, lo)

    cov0 = (
        jnp.float32(protocol.coverage(graph, state0))
        if hasattr(protocol, "coverage")
        else jnp.float32(0.0)
    )
    init = (state0, key, jnp.int32(0), cov0, *accum.zero())
    state, _, rounds, coverage, hi, lo = jax.lax.while_loop(cond, body, init)
    return state, _pack_summary(rounds, coverage, (hi, lo))


@functools.partial(jax.jit, static_argnames=("protocol", "max_rounds"))
def _coverage_with_init(graph, protocol, key, *, coverage_target, max_rounds):
    """init + loop in one XLA program (the fresh-run entry pays zero eager
    dispatches — protocol.init's scatter and the seed coverage all trace)."""
    return _coverage_body(graph, protocol, protocol.init(graph, key), key,
                          coverage_target, max_rounds)


@functools.partial(jax.jit, static_argnames=("protocol", "max_rounds"))
def _coverage_loop(graph, protocol, state0, key, *, coverage_target,
                   max_rounds):
    return _coverage_body(graph, protocol, state0, key, coverage_target,
                          max_rounds)
