"""Round engine: compiled protocol execution.

The reference's runtime is its thread-and-poll loops (SURVEY.md section 1
"concurrency model"); the sim backend's runtime is this module — ``lax.scan``
over protocol rounds, compiled once, with per-round stats as device-side
reductions, plus a ``lax.while_loop`` variant for run-to-coverage with no
host round-trips (the north-star benchmark loop).

The resume entry points (``run_from`` / ``run_until_coverage_from`` /
``run_until_converged``) DONATE the state carry by default — the caller's
buffers alias the loop's instead of double-buffering in HBM, and are
invalidated (``donate=False`` opts out; see ``run_from``). Protocols that
expose a ``frontier_occupancy`` stat (the flood family) get its per-run
mean packed into the summary and recorded into the
``sim_frontier_occupancy`` histogram.

The BATCHED message plane rides the same loop discipline at B messages
per program: :func:`run_batch_until_coverage` advances a lane-packed
:class:`~p2pnetwork_tpu.models.messagebatch.MessageBatch` (32 concurrent
broadcast states per uint32 word — models/messagebatch.py) with one
donated-carry ``lax.while_loop``, per-message completion detection via
lane-masked popcounts against per-message coverage targets, completed
lanes frozen out of the batch frontier, and the whole per-lane summary
back in ONE packed transfer. Staggered admission happens BETWEEN calls
through ``BatchFlood.admit`` — the serving front-end's seam. Per-batch
occupancy and completion land in the ``sim_batch_active_lanes`` gauge
and ``sim_batch_completion_rounds`` histogram.

The QUERY plane generalizes the batch loop past boolean floods:
:func:`run_queries_until_done` advances a
:class:`~p2pnetwork_tpu.models.querybatch.QueryBatch` of K non-boolean
query lanes (min-plus route lookups, DHT successor chases, push-sum
aggregations — f32/i32 carriers budgeted BY BYTES via
``ops/lanes.lane_budget``) with the same donated-carry discipline,
per-lane freeze, and a packed summary that additionally carries every
lane's ANSWER back in the one transfer.

graftscope rides the resume/batch/query loops: ``recorder=`` on
:func:`run_from`, :func:`run_until_coverage_from`,
:func:`run_batch_until_coverage` and :func:`run_queries_until_done` (a
:class:`~p2pnetwork_tpu.sim.flightrec.FlightRecorder`) accumulates a
bounded per-round record ring INSIDE the compiled carry — donated like
the state, bit-identical results, one extra fetch per run — and, when a
trace plane is installed (telemetry/spans.py), batched runs emit
``batch_run`` spans with per-lane lifecycle events. Run summaries also
sample the default history ring (telemetry/history.py) so ``/history``
serves per-run gauge series with zero extra wiring.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.chaos import device as chaos_device
from p2pnetwork_tpu.ops import bitset
from p2pnetwork_tpu.sim import flightrec
from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.telemetry import history, jaxhooks, spans
from p2pnetwork_tpu.utils import accum

# Compile/recompile accounting rides jax.monitoring's lowering-duration
# events into the default registry (jax_compiles_total /
# jax_compile_seconds_total{stage}) — a run-to-* loop whose shapes churn
# shows up as a climbing compile count, not just mysterious wall time.
jaxhooks.install()


#: Occupancy is a fraction of live nodes in [0, 1]; geometric buckets from
#: ~0.1% up resolve the sparse tail where the frontier fast path pays off.
_OCCUPANCY_BUCKETS = telemetry.exponential_buckets(1 / 1024, 2.0, 11)
#: Cardinality bound for sim_frontier_occupancy's (loop, protocol) children
#: — a sweep over many protocol configs must not grow the family without
#: limit (the per-peer-gauge pruning rule, telemetry/registry.py).
_OCCUPANCY_MAX_CHILDREN = 16
#: Recency order of observed (loop, protocol) pairs — pruning evicts the
#: LEAST-RECENTLY-observed child, not the oldest-registered: a histogram
#: is cumulative, and a long-lived protocol's history must not be zeroed
#: because 16 one-shot sweep configs registered after it. Guarded by its
#: own lock: run summaries bridge from whatever thread finished the run
#: (several JaxSimNodes in one process), and the registry's internal
#: locking does not cover this side-table.
_occupancy_recency: dict = {}
_occupancy_lock = concurrency.lock()


def _observe_occupancy(loop: str, protocol_name: str, value: float) -> None:
    """Record one run's mean per-round frontier occupancy, pruning the
    least-recently-observed labeled children past the cardinality bound."""
    hist = telemetry.default_registry().histogram(
        "sim_frontier_occupancy",
        "Mean per-round frontier occupancy (active fraction of live nodes) "
        "per run-to-* invocation.",
        ("loop", "protocol"), buckets=_OCCUPANCY_BUCKETS)
    key = (loop, protocol_name)
    with _occupancy_lock:
        # observe INSIDE the lock: outside it, a concurrent prune at the
        # bound could evict this child between observe and re-insert,
        # dropping the sample just recorded.
        hist.labels(*key).observe(value)  # graftlint: ignore[lock-open-call] -- must be atomic with the recency re-insert (comment above); metric locks never take this one
        _occupancy_recency.pop(key, None)
        _occupancy_recency[key] = None  # re-insert = move to most-recent
        # Drop recency entries for children gone from the (possibly
        # swapped) registry, then evict the coldest down to the bound.
        live = {c.labels for c in hist.children()}  # graftlint: ignore[lock-open-call] -- same atomicity; children() is a leaf lock
        for stale in [k for k in _occupancy_recency if k not in live]:
            del _occupancy_recency[stale]
        while len(_occupancy_recency) > _OCCUPANCY_MAX_CHILDREN:
            coldest = next(iter(_occupancy_recency))
            del _occupancy_recency[coldest]
            hist.remove(*coldest)


def _record_run_summary(loop: str, wall_s: float, transfer_s: float,
                        transfer_bytes: int, out: dict,
                        protocol_name: str = "") -> None:
    """Bridge one host-side run summary into the registry post-transfer.

    The compiled loops are pure device programs — the only host hooks are
    their entry and the packed-summary transfer, so that is where the
    telemetry plane observes the sim backend."""
    reg = telemetry.default_registry()
    reg.counter("sim_runs_total", "Completed run-to-* loop invocations.",
                ("loop",)).labels(loop).inc()
    reg.counter("sim_rounds_total", "Protocol rounds executed on device.",
                ("loop",)).labels(loop).inc(float(out["rounds"]))
    reg.counter("sim_messages_total",
                "Messages moved by protocol rounds (exact two-limb totals).",
                ("loop",)).labels(loop).inc(float(out["messages"]))
    reg.histogram("sim_run_seconds",
                  "Wall seconds per run-to-* invocation (dispatch through "
                  "summary transfer).", ("loop",)).labels(loop).observe(wall_s)
    reg.counter("sim_transfer_seconds_total",
                "Seconds blocked on device->host summary transfers (includes "
                "waiting out the device program on async backends)."
                ).inc(transfer_s)
    reg.counter("sim_transfer_bytes_total",
                "Bytes moved by device->host summary transfers."
                ).inc(transfer_bytes)
    if loop.startswith("coverage") and "coverage" in out:
        # (the converged loop reuses the packed f32 slot for its stat, so
        # its summary also carries a "coverage" key — not a coverage)
        reg.gauge("sim_last_coverage", "Coverage reached by the most recent "
                  "run-to-coverage loop.", ("loop",)).labels(loop).set(
                      float(out["coverage"]))
    if "frontier_occupancy_mean" in out:
        _observe_occupancy(loop, protocol_name,
                           float(out["frontier_occupancy_mean"]))


def _timed_summary(loop: str, t0: float, state, packed,
                   protocol_name: str = "", has_occupancy: bool = False,
                   ring=None):
    """Unpack the packed one-transfer summary, timing the transfer, and
    record the whole invocation into the registry. ``has_occupancy`` says
    whether the protocol's stats carried ``frontier_occupancy`` — only
    then does the packed fifth slot mean anything (it is zero-filled for
    protocols without the stat, which must not pollute the histogram).
    ``ring`` is the flight-recorder carry when the run recorded one —
    fetched in the SAME blocking ``device_get`` as the summary (still
    one sync point per run) and attached as ``out["flight_record"]``."""
    t1 = time.perf_counter()
    nbytes = sum(int(getattr(leaf, "nbytes", 0))
                 for leaf in jax.tree_util.tree_leaves((packed, ring)))
    if ring is not None:
        packed, ring = jax.device_get((packed, ring))
    out = _unpack_summary(packed)
    extra = out.pop("extra", None)
    if has_occupancy and extra is not None:
        out["frontier_occupancy_mean"] = extra
    if ring is not None:
        out["flight_record"] = flightrec.trim(ring, out["rounds"])
    t2 = time.perf_counter()
    _record_run_summary(loop, t2 - t0, t2 - t1, nbytes, out, protocol_name)
    history.default_history().sample()
    return state, out


def _scan_rounds(graph: Graph, protocol, state, key: jax.Array, rounds: int,
                 ring=None):
    """The shared scan body of :func:`run` / :func:`run_from`. One body
    for the recording and non-recording forms (trace-time ``ring``
    branch, the ``_stat_while`` pattern) so the RNG chain and state math
    CANNOT diverge between them: ``ring`` (sim/flightrec.py) adds a
    per-round row write to the carry and a third return value."""

    def body(carry, round_key):
        st = carry[0]
        st, stats = protocol.step(graph, st, round_key)
        if ring is None:
            return (st,), stats
        _, rg, r, tot = carry
        msgs = jnp.float32(stats.get("messages", 0.0))
        tot = tot + msgs
        rg = flightrec.write_row(
            rg, r, occupancy=stats.get("frontier_occupancy", 0.0),
            new=msgs, total=tot, coverage=stats.get("coverage", 0.0),
            active_lanes=1, ici_bytes=0.0)
        return (st, rg, r + 1, tot), stats

    keys = jax.random.split(jax.random.fold_in(key, 1), rounds)
    init = (state,) if ring is None \
        else (state, ring, jnp.int32(0), jnp.float32(0.0))
    carry, stats = jax.lax.scan(body, init, keys)
    if ring is None:
        return carry[0], stats
    return carry[0], stats, carry[1]


@functools.partial(jax.jit, static_argnames=("protocol", "rounds"))
def run(graph: Graph, protocol, key: jax.Array, rounds: int):
    """Run ``rounds`` synchronous rounds from the protocol's initial state;
    returns (final_state, stacked stats).

    Stats come back as arrays of shape [rounds] per entry — the full
    per-round history of the device-side counters in one transfer.
    """
    return _scan_rounds(graph, protocol, protocol.init(graph, key), key,
                        rounds)


_run_from_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "rounds"),
    donate_argnames=("state",))(_scan_rounds)
_run_from_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- the deliberate donate=False escape hatch (aliased-leaf states, double-resume); the donating twin is the default
    jax.jit, static_argnames=("protocol", "rounds"))(_scan_rounds)


def _scan_rounds_rec(graph: Graph, protocol, state, key: jax.Array,
                     rounds: int, ring: jax.Array):
    """The recording form of :func:`_scan_rounds` (same body — this
    wrapper only exists so the jit variants can name ``ring`` in
    ``donate_argnames``): the ring is a donated carry leaf of the
    donating variant, like the state."""
    return _scan_rounds(graph, protocol, state, key, rounds, ring)


_run_from_rec_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "rounds"),
    donate_argnames=("state", "ring"))(_scan_rounds_rec)
_run_from_rec_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- same donate=False escape hatch as the non-recording twin
    jax.jit, static_argnames=("protocol", "rounds"))(_scan_rounds_rec)


def _donatable(state, *others) -> bool:
    """False when two leaves of ``state`` are the SAME array — XLA rejects
    donating one buffer twice, and protocol inits routinely alias (Flood's
    seed IS both ``seen`` and ``frontier``) — or when a state leaf is also
    a leaf of a NON-donated argument (LeaderElection's state carries
    ``graph.node_mask`` itself: `f(a, donate(a))` is equally rejected).
    Such states ride the non-donating path transparently; after one real
    step the leaves are distinct buffers and donation kicks in."""
    leaves = jax.tree_util.tree_leaves(state)
    ids = {id(leaf) for leaf in leaves}
    if len(ids) < len(leaves):
        return False
    other_ids = {id(leaf) for o in others
                 for leaf in jax.tree_util.tree_leaves(o)}
    return not (ids & other_ids)


def _check_not_donated(state) -> None:
    """Resuming from a state whose buffers a previous donating run already
    consumed surfaces, without this check, as an opaque XLA "Buffer has
    been deleted or donated" error from deep inside the dispatch. Detect
    deleted leaves up front and name the actual fix."""
    for leaf in jax.tree_util.tree_leaves(state):
        if (isinstance(leaf, jax.Array)
                and not isinstance(leaf, jax.core.Tracer)
                and leaf.is_deleted()):
            raise ValueError(
                "resume state has deleted device buffers — they were "
                "donated to a previous run_from / run_until_coverage_from "
                "/ run_until_converged call (donate=True is the default). "
                "To resume the same state more than once pass "
                "donate=False to the earlier call, or reload the state "
                "from a checkpoint."
            )


def _pick_loop(donating, keeping, donate, state, graph, key):
    """The one donation gate all three resume entry points share: the
    donating jit variant only when asked AND the state's buffers are
    cleanly donatable against the non-donated args."""
    _check_not_donated(state)
    return donating if donate and _donatable(state, graph, key) else keeping


def run_from(graph: Graph, protocol, state, key: jax.Array, rounds: int, *,
             donate: bool = True, recorder=None):
    """Run ``rounds`` rounds continuing from an existing ``state`` (resume
    path — e.g. after loading a checkpoint, or incremental stepping from
    JaxSimNode).

    ``donate=True`` (the default) donates the ``state`` buffers to the
    compiled loop: the caller's copy stops double-buffering in HBM
    alongside the loop carry — at 10M nodes that is tens of MB per
    predicate — and is INVALIDATED (reading the passed-in state
    afterwards raises). Pass ``donate=False`` to keep it (e.g. to resume
    the same state twice), and checkpoint a pre-run state BEFORE the
    donating call — ``sim/checkpoint.py`` copies to host at save time,
    so save-then-run is safe, run-then-save-the-old-state is not. A
    state whose leaves alias one buffer (fresh protocol inits do) skips
    donation automatically rather than trip XLA's double-donate check.

    ``recorder`` (a :class:`~p2pnetwork_tpu.sim.flightrec.FlightRecorder`,
    default off) accumulates the per-round flight ring inside the scan
    carry — results stay bit-identical — and changes the return to
    ``(state, stats, FlightRecord)`` (the record fetch is the one extra
    sync the recorder adds, at the END of the run).
    """
    # graftquake chunk-dispatch gate (see run_until_coverage_from).
    chaos_device.dispatch_gate("engine-rounds")
    if recorder is None:
        fn = _pick_loop(_run_from_donating, _run_from_keeping, donate,
                        state, graph, key)
        return fn(graph, protocol, state, key, rounds)
    fn = _pick_loop(_run_from_rec_donating, _run_from_rec_keeping, donate,
                    state, graph, key)
    state, stats, ring = fn(graph, protocol, state, key, rounds,
                            recorder.init())
    return state, stats, flightrec.trim(np.asarray(ring), rounds)


def run_until_coverage(
    graph: Graph,
    protocol,
    key: jax.Array,
    *,
    coverage_target: float = 0.99,
    max_rounds: int = 1024,
    steps_per_round: int = 1,
):
    """Run until ``stats['coverage'] >= coverage_target`` (or max_rounds).

    Device-side early exit via ``lax.while_loop`` — the whole
    run-to-99%-coverage measurement executes as one XLA program (init
    included) with zero host synchronization per round. Returns
    (final_state, dict with ``rounds``, ``coverage``, ``messages`` totals;
    ``messages`` is an exact Python int — see
    :func:`run_until_coverage_from`).

    Requires the protocol's stats to include ``coverage`` and ``messages``
    (e.g. models.flood.Flood). Protocols that also expose
    ``frontier_occupancy`` (the flood family) get its per-run mean back as
    ``frontier_occupancy_mean`` and recorded into the
    ``sim_frontier_occupancy`` histogram.
    """
    keys = _require_stats(graph, protocol, None, key, ("coverage", "messages"))
    t0 = time.perf_counter()
    state, packed = _coverage_with_init(
        graph, protocol, key,
        coverage_target=coverage_target, max_rounds=max_rounds,
        steps_per_round=steps_per_round,
    )
    return _timed_summary("coverage", t0, state, packed,
                          type(protocol).__name__,
                          "frontier_occupancy" in keys)


def run_until_coverage_from(
    graph: Graph,
    protocol,
    state0,
    key: jax.Array,
    *,
    coverage_target: float = 0.99,
    max_rounds: int = 1024,
    steps_per_round: int = 1,
    donate: bool = True,
    recorder=None,
):
    """Run-to-coverage continuing from an existing ``state0`` (resume path).

    If the protocol exposes ``coverage(graph, state)`` (Flood, SIR do), the
    loop starts from the true coverage of ``state0`` — resuming an
    already-finished run executes zero rounds instead of one spurious one.

    ``messages`` in the returned dict is an exact Python int: the loop
    accumulates device-side in a two-limb (hi, lo) counter (utils/accum.py)
    so totals past 2^31 — routine at 10M-node scale — do not wrap int32.
    The whole summary (rounds, coverage, both limbs) comes back in ONE
    packed transfer — on tunneled backends every extra round trip is
    milliseconds.

    ``donate=True`` (default) hands ``state0``'s buffers to the loop and
    invalidates the caller's copy (see :func:`run_from` for the full
    donation contract); pass ``donate=False`` to resume the same state
    more than once.

    ``recorder`` (a :class:`~p2pnetwork_tpu.sim.flightrec.FlightRecorder`,
    default off) rides the per-round flight ring in the while carry
    (donated alongside the state) and attaches the host-side
    :class:`~p2pnetwork_tpu.sim.flightrec.FlightRecord` as
    ``out["flight_record"]`` — run results stay bit-identical to
    recorder-off runs, still with zero per-round host sync.
    """
    # graftquake chunk-dispatch gate: an armed DispatchChaos fault
    # (chip preemption / wedged dispatch) fires HERE, before any buffer
    # is touched — one attribute read + None check when nothing is
    # installed (chaos/device.py).
    chaos_device.dispatch_gate("engine-coverage")
    keys = _require_stats(graph, protocol, state0, key,
                          ("coverage", "messages"))
    t0 = time.perf_counter()
    if recorder is None:
        loop_fn = _pick_loop(_coverage_loop_donating, _coverage_loop_keeping,
                             donate, state0, graph, key)
        state, packed = loop_fn(
            graph, protocol, state0, key,
            coverage_target=coverage_target, max_rounds=max_rounds,
            steps_per_round=steps_per_round,
        )
        ring = None
    else:
        loop_fn = _pick_loop(_coverage_loop_rec_donating,
                             _coverage_loop_rec_keeping, donate, state0,
                             graph, key)
        state, packed, ring = loop_fn(
            graph, protocol, state0, key, recorder.init(),
            coverage_target=coverage_target, max_rounds=max_rounds,
            steps_per_round=steps_per_round,
        )
    return _timed_summary("coverage_from", t0, state, packed,
                          type(protocol).__name__,
                          "frontier_occupancy" in keys, ring=ring)


# One-transfer run summaries, shared with the sharded coverage loops.
_pack_summary = accum.pack_summary
_unpack_summary = accum.unpack_summary


def run_until_converged(
    graph: Graph,
    protocol,
    key: jax.Array,
    *,
    stat: str,
    threshold: float,
    max_rounds: int = 1024,
    state0=None,
    steps_per_round: int = 1,
    donate: bool = True,
):
    """Run until the scalar ``stats[stat]`` drops BELOW ``threshold`` — the
    run-to-coverage loop's sibling for convergence-style protocols
    (PageRank to a residual, PushSum/Gossip to a variance), as one
    device-side ``lax.while_loop`` with the packed single-transfer summary.

    Returns ``(state, dict(rounds, value, messages))`` where ``value`` is
    the stat after the final round (inf if zero rounds ran) and
    ``messages`` an exact Python int. Pass ``state0`` to resume.

    Thresholds have an f32 floor: an L1 residual summed over N ranks
    bottoms out around N * eps * scale (measured ~1.4e-8 at 50K nodes), so
    an unreachable threshold runs to ``max_rounds`` — size it to the
    population, or watch ``value`` in the summary.

    ``donate=True`` (default) hands a non-None ``state0``'s buffers to the
    loop and invalidates the caller's copy (see :func:`run_from`)."""
    keys = _require_stats(graph, protocol, state0, key, (stat, "messages"))
    t0 = time.perf_counter()
    loop_fn = _pick_loop(_converged_loop_donating,
                         _converged_loop_keeping, donate, state0, graph,
                         key)
    state, packed = loop_fn(
        graph, protocol, state0, key, stat=stat, threshold=threshold,
        max_rounds=max_rounds, steps_per_round=steps_per_round,
    )
    state, out = _timed_summary("converged", t0, state, packed,
                                type(protocol).__name__,
                                "frontier_occupancy" in keys)
    out["value"] = out.pop("coverage")  # pack_summary's f32 slot, reused
    return state, out


def _converged_loop(graph, protocol, state0, key, *, stat, threshold,
                    max_rounds, steps_per_round=1):
    if state0 is None:
        state0 = protocol.init(graph, key)
    return _stat_while(
        graph, protocol, state0, key, stat=stat,
        keep_going=lambda v, r: (v >= threshold) & (r < max_rounds),
        value0=jnp.float32(jnp.inf), steps_per_round=steps_per_round,
    )


_converged_loop_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "stat", "max_rounds",
                              "steps_per_round"),
    donate_argnames=("state0",))(_converged_loop)
_converged_loop_keeping = functools.partial(
    jax.jit, static_argnames=("protocol", "stat", "max_rounds",
                              "steps_per_round"))(_converged_loop)


# ------------------------------------------------------------- batch plane

#: Completion-rounds buckets: floods finish in O(diameter) rounds, so
#: geometric 1..2048 resolves both small-world (~10) and chain-like tails.
_COMPLETION_BUCKETS = telemetry.exponential_buckets(1.0, 2.0, 12)


def _add_words(acc, words: jax.Array):
    """Fold per-word uint32 subtotals into the two-limb accumulator —
    each subtotal is < 2^32 by the ``messages_words`` contract
    (models/messagebatch.py), so ``accum.add``'s single-carry invariant
    holds per fold. W is tens at most; a fori_loop keeps it carry-exact
    without widening anything."""
    return jax.lax.fori_loop(
        0, words.shape[0], lambda i, a: accum.add(a, words[i]), acc)


def _batch_body(graph, protocol, batch0, key, *, max_rounds, ring=None):
    """The batched run-to-coverage loop: advance every running lane per
    iteration until ALL admitted lanes complete (or ``max_rounds`` more
    global rounds pass). Per-lane completion/round accounting lives in
    the protocol's step (lane-masked popcounts vs per-lane targets);
    this loop only asks "is anything still running" — one i32 reduction
    per round, no host sync. Callers must hand in a REFRESHED batch
    (protocol.refresh — run_batch_until_coverage does): refreshing
    inside this jit would dead-code the stale seen_count input and
    silently drop its donation.

    One body for the recording and non-recording forms (trace-time
    ``ring`` branch, the ``_stat_while`` pattern) so the RNG chain and
    accumulation math CANNOT diverge between them. A ring row per
    global round: union-frontier occupancy, this round's aggregate
    sends, the running total, the masked seen-count sum over lanes (the
    batch plane's coverage numerator), and the active-lane count."""

    def cond(carry):
        batch, r = carry[0], carry[2]
        return jnp.any(batch.admitted & ~batch.done) & (r < max_rounds)

    def body(carry):
        batch, k, r, hi, lo, occ = carry[:6]
        k, sub = jax.random.split(k)
        batch, stats = protocol.step(graph, batch, sub)
        hi, lo = _add_words((hi, lo), stats["messages_words"])
        out = (batch, k, r + 1, hi, lo,
               occ + jnp.float32(stats["batch_occupancy"]))
        if ring is None:
            return out
        return out + (flightrec.write_row(
            carry[6], r,
            occupancy=stats["batch_occupancy"],
            new=jnp.sum(stats["messages_words"].astype(jnp.float32)),
            total=flightrec.total_f32(hi, lo),
            coverage=jnp.sum(batch.seen_count.astype(jnp.float32)),
            active_lanes=stats["active_lanes"],
            ici_bytes=0.0),)

    init = (batch0, key, jnp.int32(0), *accum.zero(), jnp.float32(0.0))
    if ring is not None:
        init = init + (ring,)
    final = jax.lax.while_loop(cond, body, init)
    batch, _, rounds, hi, lo, occ = final[:6]
    packed = accum.pack_batch_summary(
        rounds,
        jnp.sum((batch.admitted & ~batch.done).astype(jnp.int32)),
        jnp.sum(batch.done.astype(jnp.int32)),
        (hi, lo),
        occ / jnp.maximum(rounds, 1),
        bitset.pack_bits(batch.done),
        batch.rounds,
    )
    if ring is None:
        return batch, packed
    return batch, packed, final[6]


def _batch_loop(graph, protocol, batch0, key, *, max_rounds):
    return _batch_body(graph, protocol, batch0, key, max_rounds=max_rounds)


_batch_loop_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds"),
    donate_argnames=("batch0",))(_batch_loop)
_batch_loop_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- the deliberate donate=False escape hatch, same as the single-message twins
    jax.jit, static_argnames=("protocol", "max_rounds"))(_batch_loop)


def _batch_loop_rec(graph, protocol, batch0, key, ring, *, max_rounds):
    """The recording form of :func:`_batch_body` (this wrapper only
    exists so the jit variants can name ``ring`` in
    ``donate_argnames``) — same RNG chain and state math by
    construction, so per-lane results stay bit-identical."""
    return _batch_body(graph, protocol, batch0, key, max_rounds=max_rounds,
                       ring=ring)


_batch_loop_rec_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds"),
    donate_argnames=("batch0", "ring"))(_batch_loop_rec)
_batch_loop_rec_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- same donate=False escape hatch as the non-recording twin
    jax.jit, static_argnames=("protocol", "max_rounds"))(_batch_loop_rec)


def _record_batch_summary(wall_s: float, transfer_s: float,
                          transfer_bytes: int, out: dict,
                          newly_done_rounds, protocol_name: str) -> None:
    """Bridge one batched run summary into the registry: the shared
    sim_* run counters under ``loop="batch"`` plus the batch plane's own
    gauges — ``sim_batch_active_lanes`` (lanes still running when the
    loop returned: >0 means max_rounds cut stragglers off) and one
    ``sim_batch_completion_rounds`` observation per lane that COMPLETED
    in this call (lanes finished in an earlier call must not re-observe
    on resume)."""
    # The shared sim_* run counters register through the one site that
    # owns their names/help/labels (loop="batch" has no "coverage" key,
    # so the coverage gauge and occupancy branches there stay idle).
    _record_run_summary("batch", wall_s, transfer_s, transfer_bytes, out,
                        protocol_name)
    reg = telemetry.default_registry()
    reg.gauge("sim_batch_active_lanes",
              "Lanes still running (admitted, not at target) when the last "
              "batched loop returned — nonzero means max_rounds froze "
              "stragglers.").set(float(out["active_lanes"]))
    hist = reg.histogram(
        "sim_batch_completion_rounds",
        "Rounds each batched message took to reach its coverage target "
        "(one observation per lane completed in a "
        "run_batch_until_coverage call).", buckets=_COMPLETION_BUCKETS)
    for r in newly_done_rounds.tolist():  # host ints (numpy, post-unpack)
        hist.observe(r)
    _observe_occupancy("batch", protocol_name,
                       float(out["occupancy_mean"]))
    # One history-ring sample per batched run, taken AFTER the batch
    # gauges are set so /history's sim_batch_active_lanes series tracks
    # run boundaries (telemetry/history.py).
    history.default_history().sample()


def _emit_batch_entry_events(admitted0, done0, rounds0) -> None:
    """Per-lane lifecycle events at batch-run entry (trace plane,
    telemetry/spans.py): ``lane_admit`` for lanes this run advances for
    the first time, ``lane_resume`` for lanes resuming from an earlier
    call. No-ops unless a tracer is installed (the callers gate)."""
    running = admitted0 & ~done0
    for lane in np.flatnonzero(running & (rounds0 == 0)).tolist():
        spans.emit("lane_admit", lane=lane)
    for lane in np.flatnonzero(running & (rounds0 > 0)).tolist():
        spans.emit("lane_resume", lane=lane)


def _emit_batch_exit_events(admitted0, done0, out) -> None:
    """Per-lane lifecycle events at batch-run exit: ``lane_complete``
    for lanes that reached target in this call (with their cumulative
    round count), ``lane_freeze`` for running lanes the loop returned
    still unfinished (max_rounds cut the stragglers off)."""
    lane_done = out["lane_done"]
    newly = np.flatnonzero(lane_done & ~done0)
    rounds = out["lane_rounds"][newly]
    for lane, r in zip(newly.tolist(), rounds.tolist()):
        spans.emit("lane_complete", lane=lane, rounds=r)
    frozen = np.flatnonzero(admitted0 & ~done0 & ~lane_done)
    for lane in frozen.tolist():
        spans.emit("lane_freeze", lane=lane)


def run_batch_until_coverage(graph: Graph, protocol, batch, key: jax.Array,
                             *, max_rounds: int = 1024,
                             donate: bool = True, recorder=None):
    """Advance ALL in-flight messages of a lane-packed batch until every
    admitted lane reaches its coverage target (or ``max_rounds`` global
    rounds pass) — the B-message sibling of
    :func:`run_until_coverage_from`, one compiled program per call.

    ``protocol`` is a batched protocol (models/messagebatch.BatchFlood):
    ``step(graph, batch, key) -> (batch, stats)`` with per-lane
    completion folded into the state and ``stats`` carrying
    ``messages_words`` / ``batch_occupancy`` / ``active_lanes``.
    Completed lanes freeze (masked out of the batch frontier), so
    stragglers do not pay for finished messages; admission of NEW
    messages into open lanes happens between calls via
    ``protocol.admit`` — the serving front-end's seam.

    Returns ``(batch, out)`` where ``out`` carries the aggregates
    (``rounds`` global rounds this call, exact ``messages``,
    ``active_lanes``, ``completed``, ``occupancy_mean``) plus per-lane
    vectors (``lane_done`` bool[B], ``lane_rounds`` i32[B] — TOTAL steps
    applied per lane, resume-cumulative) and, when any lane completed in
    this call, ``completion_rounds_p50`` / ``completion_rounds_p99`` over
    those lanes — the serving-SLO numbers the bench publishes. The whole
    summary is ONE packed device->host transfer however large B is.

    ``donate=True`` (default) hands the batch's buffers to the loop and
    invalidates the caller's copy (see :func:`run_from`); pass
    ``donate=False`` to keep reading the pre-run batch (e.g. to resume
    it twice).

    ``recorder`` (a :class:`~p2pnetwork_tpu.sim.flightrec.FlightRecorder`,
    default off) rides the per-round flight ring in the donated carry
    and attaches ``out["flight_record"]`` — per-lane results stay
    bit-identical to recorder-off runs. When a trace plane is installed
    (telemetry/spans.py), the whole call runs under a ``batch_run`` span
    carrying per-lane ``lane_admit`` / ``lane_resume`` /
    ``lane_complete`` / ``lane_freeze`` events."""
    # graftquake chunk-dispatch gate (see run_until_coverage_from): an
    # armed fault raises before the batch is read, so a healing retry
    # re-dispatches an intact carry.
    chaos_device.dispatch_gate("engine-batch")
    t0 = time.perf_counter()
    _check_not_donated(batch)  # friendly error before refresh reads it
    # Pre-run done flags, snapshotted BEFORE the refresh: a lane the
    # refresh itself completes (failures between calls moved its target)
    # completed in THIS call and must observe into the completion
    # histogram/percentiles like any other (and the copy must precede
    # the loop consuming the donated buffers anyway).
    done0 = np.asarray(batch.done)
    tracer = spans.current_tracer()
    # Lane lifecycle snapshot for the trace plane, read pre-refresh
    # (refresh-completed lanes still count as completing in this run).
    admitted0 = np.asarray(batch.admitted) if tracer is not None else None
    rounds0 = np.asarray(batch.rounds) if tracer is not None else None
    with spans.span("batch_run", loop="engine", max_rounds=max_rounds):
        if tracer is not None:
            _emit_batch_entry_events(admitted0, done0, rounds0)
        # Entry-time mask refresh — the batched cov0 seeding: node
        # failures applied between calls change the masked
        # numerator/denominator, so lanes re-decide "already done"
        # against the CURRENT graph before any step runs. Eager on
        # purpose (see BatchFlood.refresh).
        batch = protocol.refresh(graph, batch)
        n_words = int(batch.seen.shape[0])
        if recorder is None:
            loop_fn = _pick_loop(_batch_loop_donating, _batch_loop_keeping,
                                 donate, batch, graph, key)
            state, packed = loop_fn(graph, protocol, batch, key,
                                    max_rounds=max_rounds)
            ring = None
        else:
            loop_fn = _pick_loop(_batch_loop_rec_donating,
                                 _batch_loop_rec_keeping, donate, batch,
                                 graph, key)
            state, packed, ring = loop_fn(graph, protocol, batch, key,
                                          recorder.init(),
                                          max_rounds=max_rounds)
        t1 = time.perf_counter()
        nbytes = sum(int(getattr(leaf, "nbytes", 0))
                     for leaf in jax.tree_util.tree_leaves((packed, ring)))
        if ring is not None:
            packed, ring = jax.device_get((packed, ring))
        out = accum.unpack_batch_summary(packed, n_words)
        if ring is not None:
            out["flight_record"] = flightrec.trim(ring, out["rounds"])
        t2 = time.perf_counter()
        newly = out["lane_done"] & ~done0
        # Which lanes completed in THIS call (pre-run done excluded) —
        # the serving front-end's harvest set: map these back to tickets
        # without re-deriving done-flag deltas caller-side.
        out["newly_completed_lanes"] = np.flatnonzero(newly).astype(np.int32)
        newly_rounds = out["lane_rounds"][newly]
        if newly_rounds.size:
            out["completion_rounds_p50"] = float(
                np.percentile(newly_rounds, 50))
            out["completion_rounds_p99"] = float(
                np.percentile(newly_rounds, 99))
        if tracer is not None:
            _emit_batch_exit_events(admitted0, done0, out)
            # graftsight: one summary point per chunk inside the
            # batch_run span — the engine-side join key for the serve
            # driver's per-ticket ticket_chunk replay (serve/service.py
            # correlates by tick; this carries the chunk's aggregates).
            spans.emit("batch_summary",
                       rounds=int(out["rounds"]),
                       completed=int(out["completed"]),
                       active_lanes=int(out["active_lanes"]),
                       newly_completed=int(
                           out["newly_completed_lanes"].size))
        _record_batch_summary(t2 - t0, t2 - t1, nbytes, out, newly_rounds,
                              type(protocol).__name__)
    return state, out


# ------------------------------------------------------------- query plane


def _query_body(graph, protocol, qb0, key, *, max_rounds, ring=None):
    """The batched query loop: advance every running lane of a
    :class:`~p2pnetwork_tpu.models.querybatch.QueryBatch` per iteration
    until ALL admitted queries settle (or ``max_rounds`` more global
    rounds pass) — ``_batch_body``'s sibling for the non-boolean lane
    families (min-plus routing, DHT chases, push-sum). Per-lane
    completion/round accounting lives in the family's step; this loop
    only asks "is anything still running" and folds the per-round send
    subtotal into the exact two-limb counter. The packed summary adds
    the query plane's per-lane ANSWERS (``protocol.lane_values``) to the
    batch plane's per-lane tail — one transfer for the whole K-query
    result set. Callers hand in a REFRESHED batch (the entry point
    does); ``ring`` is the flight-recorder carry (one row per global
    round, same single-body discipline as the other loops)."""
    capacity = int(qb0.admitted.shape[0])

    def cond(carry):
        qb, r = carry[0], carry[2]
        return jnp.any(qb.admitted & ~qb.done) & (r < max_rounds)

    def body(carry):
        qb, k, r, hi, lo, occ = carry[:6]
        k, sub = jax.random.split(k)
        qb, stats = protocol.step(graph, qb, sub)
        hi, lo = accum.add((hi, lo), stats["messages"])
        active = jnp.sum((qb.admitted & ~qb.done).astype(jnp.int32))
        # Lane occupancy — the query plane's "how full is the batch"
        # analog of frontier occupancy: running lanes / capacity.
        occ_r = active.astype(jnp.float32) / capacity
        out = (qb, k, r + 1, hi, lo, occ + occ_r)
        if ring is None:
            return out
        return out + (flightrec.write_row(
            carry[6], r,
            occupancy=occ_r,
            new=stats["messages"],
            total=flightrec.total_f32(hi, lo),
            coverage=jnp.sum(qb.done.astype(jnp.int32)),
            active_lanes=active,
            ici_bytes=0.0),)

    init = (qb0, key, jnp.int32(0), *accum.zero(), jnp.float32(0.0))
    if ring is not None:
        init = init + (ring,)
    final = jax.lax.while_loop(cond, body, init)
    qb, _, rounds, hi, lo, occ = final[:6]
    packed = accum.pack_query_summary(
        rounds,
        jnp.sum((qb.admitted & ~qb.done).astype(jnp.int32)),
        jnp.sum(qb.done.astype(jnp.int32)),
        (hi, lo),
        occ / jnp.maximum(rounds, 1),
        bitset.pack_bits(qb.done),
        qb.rounds,
        protocol.lane_values(graph, qb),
        values_float=protocol.VALUES_FLOAT,
    )
    if ring is None:
        return qb, packed
    return qb, packed, final[6]


def _query_loop(graph, protocol, qb0, key, *, max_rounds):
    return _query_body(graph, protocol, qb0, key, max_rounds=max_rounds)


_query_loop_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds"),
    donate_argnames=("qb0",))(_query_loop)
_query_loop_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- the deliberate donate=False escape hatch, same as the batch twins
    jax.jit, static_argnames=("protocol", "max_rounds"))(_query_loop)


def _query_loop_rec(graph, protocol, qb0, key, ring, *, max_rounds):
    """The recording form of :func:`_query_body` (wrapper so the jit
    variants can name ``ring`` in ``donate_argnames``) — same RNG chain
    and state math by construction."""
    return _query_body(graph, protocol, qb0, key, max_rounds=max_rounds,
                       ring=ring)


_query_loop_rec_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds"),
    donate_argnames=("qb0", "ring"))(_query_loop_rec)
_query_loop_rec_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- same donate=False escape hatch as the non-recording twin
    jax.jit, static_argnames=("protocol", "max_rounds"))(_query_loop_rec)


def _record_query_summary(wall_s: float, transfer_s: float,
                          transfer_bytes: int, out: dict,
                          newly_done_rounds, protocol_name: str) -> None:
    """Bridge one batched query-run summary into the registry: the
    shared sim_* run counters under ``loop="query"`` plus the query
    plane's own instruments — ``sim_query_active_lanes`` (queries still
    running at return: >0 means max_rounds froze stragglers) and one
    ``sim_query_completion_rounds`` observation per lane that settled
    in this call."""
    _record_run_summary("query", wall_s, transfer_s, transfer_bytes, out,
                        protocol_name)
    reg = telemetry.default_registry()
    reg.gauge("sim_query_active_lanes",
              "Query lanes still running (admitted, not settled) when "
              "the last run_queries_until_done call returned — nonzero "
              "means max_rounds froze stragglers.").set(
                  float(out["active_lanes"]))
    hist = reg.histogram(
        "sim_query_completion_rounds",
        "Rounds each batched query took to settle (one observation per "
        "lane completed in a run_queries_until_done call).",
        buckets=_COMPLETION_BUCKETS)
    for r in newly_done_rounds.tolist():  # host ints (numpy, post-unpack)
        hist.observe(r)
    history.default_history().sample()


def run_queries_until_done(graph: Graph, protocol, batch, key: jax.Array,
                           *, max_rounds: int = 1024,
                           donate: bool = True, recorder=None):
    """Advance ALL in-flight queries of a lane-packed
    :class:`~p2pnetwork_tpu.models.querybatch.QueryBatch` until every
    admitted lane settles (or ``max_rounds`` global rounds pass) — the
    query-family sibling of :func:`run_batch_until_coverage`, one
    compiled program per call for K routing lookups / DHT chases /
    aggregations at once.

    ``protocol`` is a query family (models/querybatch.py
    ``MinPlusQueries`` / ``DhtLookups`` / ``PushSumQueries``):
    ``step(graph, batch, key) -> (batch, stats)`` with per-lane
    completion folded into the state, ``stats["messages"]`` the
    round's aggregate send subtotal (< 2^32 — budget ``K * E``), and
    ``lane_values(graph, batch)`` the per-lane answers. Completed lanes
    freeze; admission of new queries happens between calls via
    ``protocol.admit`` — the same serving seam as the flood plane.

    Returns ``(batch, out)``: aggregates (``rounds``, exact
    ``messages``, ``active_lanes``, ``completed``, ``occupancy_mean`` —
    mean running-lane fraction), per-lane vectors (``lane_done``,
    ``lane_rounds`` — resume-cumulative, ``lane_values`` — the ANSWERS,
    f32 or i32 per family, ``newly_completed_lanes``) and, when any lane
    settled this call, ``completion_rounds_p50``/``p99`` over those
    lanes. One packed device->host transfer however large K is.

    ``donate=True`` (default) hands the batch's buffers to the loop and
    invalidates the caller's copy (see :func:`run_from`). ``recorder``
    rides the per-round flight ring in the donated carry and attaches
    ``out["flight_record"]`` — per-lane results stay bit-identical to
    recorder-off runs. With a trace plane installed (telemetry/spans.py)
    the call runs under a ``query_run`` span with the same per-lane
    ``lane_admit`` / ``lane_resume`` / ``lane_complete`` /
    ``lane_freeze`` events as the batch plane."""
    t0 = time.perf_counter()
    _check_not_donated(batch)  # friendly error before refresh reads it
    done0 = np.asarray(batch.done)
    tracer = spans.current_tracer()
    admitted0 = np.asarray(batch.admitted) if tracer is not None else None
    rounds0 = np.asarray(batch.rounds) if tracer is not None else None
    with spans.span("query_run", loop="engine", max_rounds=max_rounds):
        if tracer is not None:
            _emit_batch_entry_events(admitted0, done0, rounds0)
        # Entry-time refresh — identity for today's families (their
        # completions latch; nothing is mask-derived), kept eager for
        # template parity with the batch plane: a future mask-derived
        # refresh inside the donated jit would dead-code its stale
        # input leaf and silently drop that donation (BatchFlood.refresh
        # documents the incident).
        batch = protocol.refresh(graph, batch)
        capacity = int(batch.admitted.shape[0])
        if recorder is None:
            loop_fn = _pick_loop(_query_loop_donating, _query_loop_keeping,
                                 donate, batch, graph, key)
            state, packed = loop_fn(graph, protocol, batch, key,
                                    max_rounds=max_rounds)
            ring = None
        else:
            loop_fn = _pick_loop(_query_loop_rec_donating,
                                 _query_loop_rec_keeping, donate, batch,
                                 graph, key)
            state, packed, ring = loop_fn(graph, protocol, batch, key,
                                          recorder.init(),
                                          max_rounds=max_rounds)
        t1 = time.perf_counter()
        nbytes = sum(int(getattr(leaf, "nbytes", 0))
                     for leaf in jax.tree_util.tree_leaves((packed, ring)))
        if ring is not None:
            packed, ring = jax.device_get((packed, ring))
        out = accum.unpack_query_summary(
            packed, capacity, values_float=protocol.VALUES_FLOAT)
        if ring is not None:
            out["flight_record"] = flightrec.trim(ring, out["rounds"])
        t2 = time.perf_counter()
        newly = out["lane_done"] & ~done0
        out["newly_completed_lanes"] = np.flatnonzero(newly).astype(np.int32)
        newly_rounds = out["lane_rounds"][newly]
        if newly_rounds.size:
            out["completion_rounds_p50"] = float(
                np.percentile(newly_rounds, 50))
            out["completion_rounds_p99"] = float(
                np.percentile(newly_rounds, 99))
        if tracer is not None:
            _emit_batch_exit_events(admitted0, done0, out)
        _record_query_summary(t2 - t0, t2 - t1, nbytes, out, newly_rounds,
                              type(protocol).__name__)
    return state, out


def donating_carry_loops() -> dict:
    """The donating state-carry loops, by name — the exact jitted objects
    the resume entry points dispatch, exposed as a stable seam for
    graftaudit's donation audit (analysis/ir/donation.py: the compiled
    ``input_output_alias`` must cover every carry leaf). Keyed by name so
    a renamed or removed loop fails the audit loudly instead of leaving
    the aliasing gate silently pointed at nothing."""
    return {
        "run_from": _run_from_donating,
        "coverage_from": _coverage_loop_donating,
        "converged_from": _converged_loop_donating,
        "batch_from": _batch_loop_donating,
        "query_from": _query_loop_donating,
        # The flight-recorder twins: the ring is an extra donated carry
        # leaf, and the audit must prove it stays aliased (a recorder
        # that double-buffers its ring would silently tax every
        # recorded run).
        "run_from_rec": _run_from_rec_donating,
        "coverage_from_rec": _coverage_loop_rec_donating,
        "batch_from_rec": _batch_loop_rec_donating,
        "query_from_rec": _query_loop_rec_donating,
    }


#: Memoized stats-key sets per (protocol, graph structure) — the abstract
#: trace of init+step runs once, not per call (the run-to-* entry points
#: sit on paths budgeted in milliseconds). FIFO-bounded: a sweep over many
#: protocol configs must not grow it without limit or pin every protocol
#: instance alive (ADVICE r3).
_stats_keys_cache: dict = {}
_STATS_KEYS_CACHE_MAX = 128


def _require_stats(graph, protocol, state0, key, required):
    """Check the protocol's stats dict exposes ``required`` keys, by
    abstract tracing (no device work) — a typo'd or missing stat must be a
    clear ValueError up front, not a KeyError from inside the jitted
    loop. Returns the full stats-key frozenset so callers can sniff
    OPTIONAL stats (``frontier_occupancy``) off the same cached trace."""
    cache_key = (protocol, jax.tree_util.tree_structure(graph))
    keys = _stats_keys_cache.get(cache_key)
    if keys is None:
        shapes = jax.eval_shape(
            lambda g, k, s0: protocol.step(
                g, protocol.init(g, k) if s0 is None else s0, k
            )[1],
            graph, key, state0,
        )
        if len(_stats_keys_cache) >= _STATS_KEYS_CACHE_MAX:
            _stats_keys_cache.pop(next(iter(_stats_keys_cache)))
        keys = _stats_keys_cache[cache_key] = frozenset(shapes)
    missing = [r for r in required if r not in keys]
    if missing:
        raise ValueError(
            f"{type(protocol).__name__} exposes stats {sorted(keys)}; "
            f"this loop needs {sorted(missing)}"
        )
    return keys


def _stat_while(graph, protocol, state0, key, *, stat, keep_going, value0,
                steps_per_round=1, ring=None):
    """The shared device-side early-exit loop: run protocol rounds while
    ``keep_going(stats[stat], rounds)`` holds, accumulating messages in the
    two-limb counter and returning the packed one-transfer summary. Both
    run-to-coverage and run-to-convergence are this loop with a different
    predicate and seed value.

    ``steps_per_round=T`` batches T protocol steps into each while-loop
    iteration as a ``lax.scan`` — rounds-bound protocols (the walker
    cohort runs thousands of rounds at a per-iteration floor set by
    while_loop dispatch, not bandwidth) amortize that floor T-fold.
    BIT-EXACT vs T=1 by construction, not approximately: each sub-step
    re-evaluates ``keep_going`` and applies the protocol step only while
    it holds (a crossed target freezes state/rounds/messages for the
    remainder of the super-step), and the sub-step RNG chain is the same
    ``k, sub = split(k)`` sequence the T=1 body walks. The only cost is
    up to T-1 discarded trailing step computations in the final
    super-step.

    When the protocol's stats include ``frontier_occupancy`` (the flood
    family), its per-round values accumulate device-side and the packed
    summary carries their mean in the fifth slot — zero for protocols
    without the stat (the entry points know which is which and drop the
    meaningless zeros).

    ``ring`` (optional ``f32[capacity, K]``, sim/flightrec.py) appends
    the flight-recorder ring to the carry: one row write per APPLIED
    round — frozen sub-steps of a batched super-step write nothing —
    and the final ring comes back as a third return value. The ring
    never feeds the loop's math, so results are bit-identical either
    way."""
    T = int(steps_per_round)
    if T < 1:
        raise ValueError(f"steps_per_round must be >= 1, got {T}")

    def _occ(stats):
        return jnp.float32(stats.get("frontier_occupancy", 0.0))

    def _row(rg, rounds_before, stats, hi, lo):
        # Per-round flight record: the loop's tracked stat rides the
        # coverage column (a coverage fraction for the flood loops).
        return flightrec.write_row(
            rg, rounds_before, occupancy=_occ(stats),
            new=stats["messages"], total=flightrec.total_f32(hi, lo),
            coverage=stats[stat], active_lanes=1, ici_bytes=0.0)

    def cond(carry):
        return keep_going(carry[3], carry[2])

    def body(carry):
        state, k, rounds, _, hi, lo, occ = carry[:7]
        k, sub = jax.random.split(k)
        state, stats = protocol.step(graph, state, sub)
        hi, lo = accum.add((hi, lo), stats["messages"])
        out = (state, k, rounds + 1, jnp.float32(stats[stat]), hi, lo,
               occ + _occ(stats))
        if ring is None:
            return out
        return out + (_row(carry[7], rounds, stats, hi, lo),)

    def batched_body(carry):
        def substep(c, _):
            state, k, rounds, value, hi, lo, occ = c[:7]
            live = keep_going(value, rounds)
            # k advances unconditionally: the while carry never exposes
            # it, and frozen sub-steps discard everything drawn from it,
            # so the chain the APPLIED steps see matches T=1 exactly.
            k, sub = jax.random.split(k)
            new_state, stats = protocol.step(graph, state, sub)
            state = jax.tree_util.tree_map(
                lambda new, old: jnp.where(live, new, old), new_state, state)
            hi, lo = accum.add(
                (hi, lo),
                jnp.where(live, stats["messages"],
                          jnp.zeros_like(stats["messages"])))
            new_rounds = jnp.where(live, rounds + 1, rounds)
            value = jnp.where(live, jnp.float32(stats[stat]), value)
            occ = occ + jnp.where(live, _occ(stats), jnp.float32(0.0))
            out = (state, k, new_rounds, value, hi, lo, occ)
            if ring is None:
                return out, None
            # Frozen sub-steps keep the ring untouched (their discarded
            # step would otherwise overwrite the last applied row).
            return out + (jnp.where(live, _row(c[7], rounds, stats, hi, lo),
                                    c[7]),), None

        carry, _ = jax.lax.scan(substep, carry, None, length=T)
        return carry

    init = (state0, key, jnp.int32(0), value0, *accum.zero(),
            jnp.float32(0.0))
    if ring is not None:
        init = init + (ring,)
    final = jax.lax.while_loop(cond, body if T == 1 else batched_body, init)
    state, _, rounds, value, hi, lo, occ = final[:7]
    occ_mean = occ / jnp.maximum(rounds, 1)
    packed = _pack_summary(rounds, value, (hi, lo), extra=occ_mean)
    if ring is None:
        return state, packed
    return state, packed, final[7]


def _coverage_body(graph, protocol, state0, key, coverage_target, max_rounds,
                   steps_per_round=1, ring=None):
    cov0 = (
        jnp.float32(protocol.coverage(graph, state0))
        if hasattr(protocol, "coverage")
        else jnp.float32(0.0)
    )
    return _stat_while(
        graph, protocol, state0, key, stat="coverage",
        keep_going=lambda v, r: (v < coverage_target) & (r < max_rounds),
        value0=cov0, steps_per_round=steps_per_round, ring=ring,
    )


@functools.partial(jax.jit, static_argnames=("protocol", "max_rounds",
                                             "steps_per_round"))
def _coverage_with_init(graph, protocol, key, *, coverage_target, max_rounds,
                        steps_per_round=1):
    """init + loop in one XLA program (the fresh-run entry pays zero eager
    dispatches — protocol.init's scatter and the seed coverage all trace)."""
    return _coverage_body(graph, protocol, protocol.init(graph, key), key,
                          coverage_target, max_rounds, steps_per_round)


def _coverage_loop(graph, protocol, state0, key, *, coverage_target,
                   max_rounds, steps_per_round=1):
    return _coverage_body(graph, protocol, state0, key, coverage_target,
                          max_rounds, steps_per_round)


_coverage_loop_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds", "steps_per_round"),
    donate_argnames=("state0",))(_coverage_loop)
_coverage_loop_keeping = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds",
                              "steps_per_round"))(_coverage_loop)


def _coverage_loop_rec(graph, protocol, state0, key, ring, *,
                       coverage_target, max_rounds, steps_per_round=1):
    """The run-to-coverage resume loop with the flight-recorder ring in
    the carry (sim/flightrec.py) — returns ``(state, packed, ring)``;
    the ring is a donated carry leaf of the donating variant exactly
    like the state (graftaudit's donation audit covers this seam)."""
    return _coverage_body(graph, protocol, state0, key, coverage_target,
                          max_rounds, steps_per_round, ring=ring)


_coverage_loop_rec_donating = functools.partial(
    jax.jit, static_argnames=("protocol", "max_rounds", "steps_per_round"),
    donate_argnames=("state0", "ring"))(_coverage_loop_rec)
_coverage_loop_rec_keeping = functools.partial(  # graftlint: ignore[carry-no-donate] -- same donate=False escape hatch as the non-recording twin
    jax.jit, static_argnames=("protocol", "max_rounds",
                              "steps_per_round"))(_coverage_loop_rec)
