"""Round engine: compiled protocol execution.

The reference's runtime is its thread-and-poll loops (SURVEY.md section 1
"concurrency model"); the sim backend's runtime is this module — ``lax.scan``
over protocol rounds, compiled once, with per-round stats as device-side
reductions, plus a ``lax.while_loop`` variant for run-to-coverage with no
host round-trips (the north-star benchmark loop).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.telemetry import jaxhooks
from p2pnetwork_tpu.utils import accum

# Compile/recompile accounting rides jax.monitoring's lowering-duration
# events into the default registry (jax_compiles_total /
# jax_compile_seconds_total{stage}) — a run-to-* loop whose shapes churn
# shows up as a climbing compile count, not just mysterious wall time.
jaxhooks.install()


def _record_run_summary(loop: str, wall_s: float, transfer_s: float,
                        transfer_bytes: int, out: dict) -> None:
    """Bridge one host-side run summary into the registry post-transfer.

    The compiled loops are pure device programs — the only host hooks are
    their entry and the packed-summary transfer, so that is where the
    telemetry plane observes the sim backend."""
    reg = telemetry.default_registry()
    reg.counter("sim_runs_total", "Completed run-to-* loop invocations.",
                ("loop",)).labels(loop).inc()
    reg.counter("sim_rounds_total", "Protocol rounds executed on device.",
                ("loop",)).labels(loop).inc(float(out["rounds"]))
    reg.counter("sim_messages_total",
                "Messages moved by protocol rounds (exact two-limb totals).",
                ("loop",)).labels(loop).inc(float(out["messages"]))
    reg.histogram("sim_run_seconds",
                  "Wall seconds per run-to-* invocation (dispatch through "
                  "summary transfer).", ("loop",)).labels(loop).observe(wall_s)
    reg.counter("sim_transfer_seconds_total",
                "Seconds blocked on device->host summary transfers (includes "
                "waiting out the device program on async backends)."
                ).inc(transfer_s)
    reg.counter("sim_transfer_bytes_total",
                "Bytes moved by device->host summary transfers."
                ).inc(transfer_bytes)
    if loop.startswith("coverage") and "coverage" in out:
        # (the converged loop reuses the packed f32 slot for its stat, so
        # its summary also carries a "coverage" key — not a coverage)
        reg.gauge("sim_last_coverage", "Coverage reached by the most recent "
                  "run-to-coverage loop.", ("loop",)).labels(loop).set(
                      float(out["coverage"]))


def _timed_summary(loop: str, t0: float, state, packed):
    """Unpack the packed one-transfer summary, timing the transfer, and
    record the whole invocation into the registry."""
    t1 = time.perf_counter()
    out = _unpack_summary(packed)
    t2 = time.perf_counter()
    nbytes = sum(int(getattr(leaf, "nbytes", 0))
                 for leaf in jax.tree_util.tree_leaves(packed))
    _record_run_summary(loop, t2 - t0, t2 - t1, nbytes, out)
    return state, out


@functools.partial(jax.jit, static_argnames=("protocol", "rounds"))
def run(graph: Graph, protocol, key: jax.Array, rounds: int):
    """Run ``rounds`` synchronous rounds from the protocol's initial state;
    returns (final_state, stacked stats).

    Stats come back as arrays of shape [rounds] per entry — the full
    per-round history of the device-side counters in one transfer.
    """
    return run_from(graph, protocol, protocol.init(graph, key), key, rounds)


@functools.partial(jax.jit, static_argnames=("protocol", "rounds"))
def run_from(graph: Graph, protocol, state, key: jax.Array, rounds: int):
    """Run ``rounds`` rounds continuing from an existing ``state`` (resume
    path — e.g. after loading a checkpoint, or incremental stepping from
    JaxSimNode)."""

    def body(carry, round_key):
        st, = carry
        st, stats = protocol.step(graph, st, round_key)
        return (st,), stats

    keys = jax.random.split(jax.random.fold_in(key, 1), rounds)
    (state,), stats = jax.lax.scan(body, (state,), keys)
    return state, stats


def run_until_coverage(
    graph: Graph,
    protocol,
    key: jax.Array,
    *,
    coverage_target: float = 0.99,
    max_rounds: int = 1024,
    steps_per_round: int = 1,
):
    """Run until ``stats['coverage'] >= coverage_target`` (or max_rounds).

    Device-side early exit via ``lax.while_loop`` — the whole
    run-to-99%-coverage measurement executes as one XLA program (init
    included) with zero host synchronization per round. Returns
    (final_state, dict with ``rounds``, ``coverage``, ``messages`` totals;
    ``messages`` is an exact Python int — see
    :func:`run_until_coverage_from`).

    Requires the protocol's stats to include ``coverage`` and ``messages``
    (e.g. models.flood.Flood).
    """
    _require_stats(graph, protocol, None, key, ("coverage", "messages"))
    t0 = time.perf_counter()
    state, packed = _coverage_with_init(
        graph, protocol, key,
        coverage_target=coverage_target, max_rounds=max_rounds,
        steps_per_round=steps_per_round,
    )
    return _timed_summary("coverage", t0, state, packed)


def run_until_coverage_from(
    graph: Graph,
    protocol,
    state0,
    key: jax.Array,
    *,
    coverage_target: float = 0.99,
    max_rounds: int = 1024,
    steps_per_round: int = 1,
):
    """Run-to-coverage continuing from an existing ``state0`` (resume path).

    If the protocol exposes ``coverage(graph, state)`` (Flood, SIR do), the
    loop starts from the true coverage of ``state0`` — resuming an
    already-finished run executes zero rounds instead of one spurious one.

    ``messages`` in the returned dict is an exact Python int: the loop
    accumulates device-side in a two-limb (hi, lo) counter (utils/accum.py)
    so totals past 2^31 — routine at 10M-node scale — do not wrap int32.
    The whole summary (rounds, coverage, both limbs) comes back in ONE
    packed transfer — on tunneled backends every extra round trip is
    milliseconds.
    """
    _require_stats(graph, protocol, state0, key, ("coverage", "messages"))
    t0 = time.perf_counter()
    state, packed = _coverage_loop(
        graph, protocol, state0, key,
        coverage_target=coverage_target, max_rounds=max_rounds,
        steps_per_round=steps_per_round,
    )
    return _timed_summary("coverage_from", t0, state, packed)


# One-transfer run summaries, shared with the sharded coverage loops.
_pack_summary = accum.pack_summary
_unpack_summary = accum.unpack_summary


def run_until_converged(
    graph: Graph,
    protocol,
    key: jax.Array,
    *,
    stat: str,
    threshold: float,
    max_rounds: int = 1024,
    state0=None,
    steps_per_round: int = 1,
):
    """Run until the scalar ``stats[stat]`` drops BELOW ``threshold`` — the
    run-to-coverage loop's sibling for convergence-style protocols
    (PageRank to a residual, PushSum/Gossip to a variance), as one
    device-side ``lax.while_loop`` with the packed single-transfer summary.

    Returns ``(state, dict(rounds, value, messages))`` where ``value`` is
    the stat after the final round (inf if zero rounds ran) and
    ``messages`` an exact Python int. Pass ``state0`` to resume.

    Thresholds have an f32 floor: an L1 residual summed over N ranks
    bottoms out around N * eps * scale (measured ~1.4e-8 at 50K nodes), so
    an unreachable threshold runs to ``max_rounds`` — size it to the
    population, or watch ``value`` in the summary."""
    _require_stats(graph, protocol, state0, key, (stat, "messages"))
    t0 = time.perf_counter()
    state, packed = _converged_loop(
        graph, protocol, state0, key, stat=stat, threshold=threshold,
        max_rounds=max_rounds, steps_per_round=steps_per_round,
    )
    state, out = _timed_summary("converged", t0, state, packed)
    out["value"] = out.pop("coverage")  # pack_summary's f32 slot, reused
    return state, out


@functools.partial(jax.jit,
                   static_argnames=("protocol", "stat", "max_rounds",
                                    "steps_per_round"))
def _converged_loop(graph, protocol, state0, key, *, stat, threshold,
                    max_rounds, steps_per_round=1):
    if state0 is None:
        state0 = protocol.init(graph, key)
    return _stat_while(
        graph, protocol, state0, key, stat=stat,
        keep_going=lambda v, r: (v >= threshold) & (r < max_rounds),
        value0=jnp.float32(jnp.inf), steps_per_round=steps_per_round,
    )


#: Memoized stats-key sets per (protocol, graph structure) — the abstract
#: trace of init+step runs once, not per call (the run-to-* entry points
#: sit on paths budgeted in milliseconds). FIFO-bounded: a sweep over many
#: protocol configs must not grow it without limit or pin every protocol
#: instance alive (ADVICE r3).
_stats_keys_cache: dict = {}
_STATS_KEYS_CACHE_MAX = 128


def _require_stats(graph, protocol, state0, key, required) -> None:
    """Check the protocol's stats dict exposes ``required`` keys, by
    abstract tracing (no device work) — a typo'd or missing stat must be a
    clear ValueError up front, not a KeyError from inside the jitted
    loop."""
    cache_key = (protocol, jax.tree_util.tree_structure(graph))
    keys = _stats_keys_cache.get(cache_key)
    if keys is None:
        shapes = jax.eval_shape(
            lambda g, k, s0: protocol.step(
                g, protocol.init(g, k) if s0 is None else s0, k
            )[1],
            graph, key, state0,
        )
        if len(_stats_keys_cache) >= _STATS_KEYS_CACHE_MAX:
            _stats_keys_cache.pop(next(iter(_stats_keys_cache)))
        keys = _stats_keys_cache[cache_key] = frozenset(shapes)
    missing = [r for r in required if r not in keys]
    if missing:
        raise ValueError(
            f"{type(protocol).__name__} exposes stats {sorted(keys)}; "
            f"this loop needs {sorted(missing)}"
        )


def _stat_while(graph, protocol, state0, key, *, stat, keep_going, value0,
                steps_per_round=1):
    """The shared device-side early-exit loop: run protocol rounds while
    ``keep_going(stats[stat], rounds)`` holds, accumulating messages in the
    two-limb counter and returning the packed one-transfer summary. Both
    run-to-coverage and run-to-convergence are this loop with a different
    predicate and seed value.

    ``steps_per_round=T`` batches T protocol steps into each while-loop
    iteration as a ``lax.scan`` — rounds-bound protocols (the walker
    cohort runs thousands of rounds at a per-iteration floor set by
    while_loop dispatch, not bandwidth) amortize that floor T-fold.
    BIT-EXACT vs T=1 by construction, not approximately: each sub-step
    re-evaluates ``keep_going`` and applies the protocol step only while
    it holds (a crossed target freezes state/rounds/messages for the
    remainder of the super-step), and the sub-step RNG chain is the same
    ``k, sub = split(k)`` sequence the T=1 body walks. The only cost is
    up to T-1 discarded trailing step computations in the final
    super-step."""
    T = int(steps_per_round)
    if T < 1:
        raise ValueError(f"steps_per_round must be >= 1, got {T}")

    def cond(carry):
        _, _, rounds, value, _, _ = carry
        return keep_going(value, rounds)

    def body(carry):
        state, k, rounds, _, hi, lo = carry
        k, sub = jax.random.split(k)
        state, stats = protocol.step(graph, state, sub)
        hi, lo = accum.add((hi, lo), stats["messages"])
        return (state, k, rounds + 1, jnp.float32(stats[stat]), hi, lo)

    def batched_body(carry):
        def substep(c, _):
            state, k, rounds, value, hi, lo = c
            live = keep_going(value, rounds)
            # k advances unconditionally: the while carry never exposes
            # it, and frozen sub-steps discard everything drawn from it,
            # so the chain the APPLIED steps see matches T=1 exactly.
            k, sub = jax.random.split(k)
            new_state, stats = protocol.step(graph, state, sub)
            state = jax.tree_util.tree_map(
                lambda new, old: jnp.where(live, new, old), new_state, state)
            hi, lo = accum.add(
                (hi, lo),
                jnp.where(live, stats["messages"],
                          jnp.zeros_like(stats["messages"])))
            rounds = jnp.where(live, rounds + 1, rounds)
            value = jnp.where(live, jnp.float32(stats[stat]), value)
            return (state, k, rounds, value, hi, lo), None

        carry, _ = jax.lax.scan(substep, carry, None, length=T)
        return carry

    init = (state0, key, jnp.int32(0), value0, *accum.zero())
    state, _, rounds, value, hi, lo = jax.lax.while_loop(
        cond, body if T == 1 else batched_body, init)
    return state, _pack_summary(rounds, value, (hi, lo))


def _coverage_body(graph, protocol, state0, key, coverage_target, max_rounds,
                   steps_per_round=1):
    cov0 = (
        jnp.float32(protocol.coverage(graph, state0))
        if hasattr(protocol, "coverage")
        else jnp.float32(0.0)
    )
    return _stat_while(
        graph, protocol, state0, key, stat="coverage",
        keep_going=lambda v, r: (v < coverage_target) & (r < max_rounds),
        value0=cov0, steps_per_round=steps_per_round,
    )


@functools.partial(jax.jit, static_argnames=("protocol", "max_rounds",
                                             "steps_per_round"))
def _coverage_with_init(graph, protocol, key, *, coverage_target, max_rounds,
                        steps_per_round=1):
    """init + loop in one XLA program (the fresh-run entry pays zero eager
    dispatches — protocol.init's scatter and the seed coverage all trace)."""
    return _coverage_body(graph, protocol, protocol.init(graph, key), key,
                          coverage_target, max_rounds, steps_per_round)


@functools.partial(jax.jit, static_argnames=("protocol", "max_rounds",
                                             "steps_per_round"))
def _coverage_loop(graph, protocol, state0, key, *, coverage_target,
                   max_rounds, steps_per_round=1):
    return _coverage_body(graph, protocol, state0, key, coverage_target,
                          max_rounds, steps_per_round)
