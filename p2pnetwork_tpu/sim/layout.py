"""IO-aware node reordering for build-time graph layouts.

Per PAPERS.md ("On Efficient Scaling of GNNs via IO-Aware Layers", "Fast
Training of Sparse GNNs on Dense Hardware"), sparse propagation wins or
loses memory bandwidth at BUILD time: the node ordering decides whether a
round's gathers walk contiguous runs of HBM or hop across it. This module
computes explicit node permutations host-side (numpy only — no jax
import, so it stays importable and lintable as pure host code):

- ``"degree"`` — degree bucketing: relabel nodes by ascending total
  degree, so neighbor-table rows of similar width are adjacent (uniform
  vector-lane occupancy per tile) and hubs cluster at the top ids;
- ``"rcm"`` — reverse Cuthill–McKee (the level-synchronous variant:
  BFS from a minimal-degree seed, each level ordered by (degree, id),
  final order reversed), the classic bandwidth-minimizing ordering — a
  node's neighbors land near it, so frontier gathers touch contiguous
  rows.

The pass is opt-in at construction — ``from_edges(..., reorder="rcm")``
(every generator forwards it) — and the permutation is recorded on the
graph (``layout_perm[old] = new``, ``layout_inv[new] = old``). All
runtime ids then speak the RELABELED space; map per-node results back
with :func:`to_original_order`. Protocol results are invariant under the
relabeling (tests/test_layout_delta.py proves flood parity through the
mapping), and the permutation participates in the layout-cache
fingerprint (sim/layoutcache.py) via its params.
"""

from __future__ import annotations

import numpy as np

#: Reordering strategies from_edges(reorder=...) accepts.
STRATEGIES = ("degree", "rcm")


def invert_permutation(perm: np.ndarray) -> np.ndarray:
    """``inv[perm[i]] = i`` — the other direction of a node relabeling."""
    perm = np.asarray(perm)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size, dtype=perm.dtype)
    return inv


def _total_degrees(senders, receivers, n_nodes: int) -> np.ndarray:
    return (np.bincount(senders, minlength=n_nodes)
            + np.bincount(receivers, minlength=n_nodes))


def degree_permutation(senders, receivers, n_nodes: int) -> np.ndarray:
    """Degree-bucketing relabel: ``perm[old] = new`` with new ids assigned
    in ascending (total degree, old id) order — deterministic, stable,
    groups rows of similar width."""
    senders = np.asarray(senders, dtype=np.int64).reshape(-1)
    receivers = np.asarray(receivers, dtype=np.int64).reshape(-1)
    deg = _total_degrees(senders, receivers, n_nodes)
    order = np.argsort(deg, kind="stable")  # ties resolve by old id
    return invert_permutation(order).astype(np.int32)


def _adjacency_csr(senders, receivers, n_nodes: int):
    """Undirected adjacency in CSR form (both edge directions pooled) —
    the traversal structure RCM walks. Built with the native radix sort,
    the same path the graph builder uses."""
    from p2pnetwork_tpu import native

    src = np.concatenate([senders, receivers]).astype(np.int32)
    dst = np.concatenate([receivers, senders]).astype(np.int32)
    src, dst = native.sort_pairs(src, dst)
    counts = np.bincount(src, minlength=n_nodes)
    offsets = np.zeros(n_nodes + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    return offsets, dst


def _gather_neighbors(offsets, dst, frontier):
    """All CSR neighbors of ``frontier``, concatenated (with duplicates)."""
    counts = offsets[frontier + 1] - offsets[frontier]
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=dst.dtype)
    # flat[i] walks each frontier node's slice: start + within-slice rank.
    base = np.repeat(offsets[frontier], counts)
    within = np.arange(total) - np.repeat(
        np.cumsum(counts) - counts, counts)
    return dst[base + within]


def rcm_permutation(senders, receivers, n_nodes: int) -> np.ndarray:
    """Reverse Cuthill–McKee relabel, level-synchronous form:
    ``perm[old] = new``.

    Per connected component (seeded at the minimal-(degree, id) unvisited
    node): BFS levels, each level ordered by (degree, old id) ascending —
    the vectorizable variant of the classic per-parent neighbor ordering,
    with the same locality property (a level's nodes land contiguously,
    adjacent levels adjacently). The concatenated order is reversed (the
    "R" in RCM: reversal provably never worsens, usually improves, profile
    width), then isolated (degree-0) nodes append in id order.
    Deterministic for a given edge list."""
    senders = np.asarray(senders, dtype=np.int64).reshape(-1)
    receivers = np.asarray(receivers, dtype=np.int64).reshape(-1)
    deg = _total_degrees(senders, receivers, n_nodes)
    offsets, dst = _adjacency_csr(senders, receivers, n_nodes)
    visited = np.zeros(n_nodes, dtype=bool)
    isolated = deg == 0
    visited |= isolated  # handled separately, after the reversal
    pieces = []
    while True:
        seeds = np.flatnonzero(~visited)
        if seeds.size == 0:
            break
        seed = seeds[np.lexsort((seeds, deg[seeds]))[0]]
        visited[seed] = True
        level = np.array([seed], dtype=np.int64)
        pieces.append(level)
        while level.size:
            nxt = np.unique(_gather_neighbors(offsets, dst, level))
            nxt = nxt[~visited[nxt]]
            if nxt.size == 0:
                break
            nxt = nxt[np.lexsort((nxt, deg[nxt]))]
            visited[nxt] = True
            pieces.append(nxt)
            level = nxt
    if pieces:
        order = np.concatenate(pieces)[::-1]
    else:
        order = np.zeros(0, dtype=np.int64)
    order = np.concatenate([order, np.flatnonzero(isolated)])
    return invert_permutation(order.astype(np.int32))


def node_permutation(senders, receivers, n_nodes: int, *,
                     strategy: str) -> np.ndarray:
    """Dispatch a reorder strategy name to its permutation
    (``perm[old] = new`` over ``[0, n_nodes)``)."""
    if strategy == "degree":
        return degree_permutation(senders, receivers, n_nodes)
    if strategy == "rcm":
        return rcm_permutation(senders, receivers, n_nodes)
    raise ValueError(
        f"unknown reorder strategy {strategy!r}; expected one of "
        f"{STRATEGIES}")


def _permute(x, perm):
    """Fancy-index ``x`` by a stored permutation without forcing device
    arrays to host: a jax ``x`` gathers with the device-resident ``perm``
    (no sync — safe inside per-round monitoring loops); a numpy ``x``
    pulls the permutation across once."""
    if isinstance(x, np.ndarray):
        perm = np.asarray(perm)
    return x[perm]


def to_original_order(x, graph):
    """View a per-node array of a reordered graph in the ORIGINAL id
    space: ``out[old_id] = x[perm[old_id]]``. Identity for graphs built
    without ``reorder``. Works on numpy and jax arrays (plain fancy
    indexing; the permutation indexes the leading axis)."""
    if graph.layout_perm is None:
        return x
    return _permute(x, graph.layout_perm)


def to_layout_order(x, graph):
    """The other direction: take a per-node array in ORIGINAL id order
    into the graph's relabeled layout (``out[new_id] = x[inv[new_id]]``)."""
    if graph.layout_inv is None:
        return x
    return _permute(x, graph.layout_inv)
