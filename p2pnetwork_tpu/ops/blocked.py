"""Blocked edge aggregation: segment reduction as one-hot matmuls.

XLA lowers ``segment_sum``/``segment_max`` to scatter, which serializes badly
on TPU. But with edges sorted by receiver, each 128-node output block owns a
contiguous edge range; padding those ranges to a common width turns the
whole reduction into a batched matmul against one-hot destination masks —
dense MXU work with zero scatters:

    out[b, v] = sum_e contrib[b, e] * (local_dst[b, e] == v)

This module builds the blocked representation (host-side, one-off) and runs
the einsum lowering; ops/pallas_edge.py is the fused Pallas kernel of the
same scheme (it never materializes the one-hot in HBM).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.sim.graph import Graph, _round_up

#: Output rows per block — one VPU/MXU lane tile.
NODE_BLOCK = 128


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BlockedEdges:
    """Edges regrouped by 128-node destination block.

    ``src``/``local_dst``/``mask`` have shape ``[n_blocks, width]`` where
    ``width`` covers the largest per-block edge count (multiple of 128).
    ``local_dst`` is the destination index within its block (0..127).
    """

    src: jax.Array  # i32[NB, W]
    local_dst: jax.Array  # i32[NB, W]
    mask: jax.Array  # bool[NB, W]
    block: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_blocks(self) -> int:
        return self.src.shape[0]

    @property
    def width(self) -> int:
        return self.src.shape[1]


def build_blocked(graph: Graph, block: int = NODE_BLOCK) -> BlockedEdges:
    """Group the graph's (dst-sorted) edges by destination block."""
    emask = np.asarray(graph.edge_mask)
    senders = np.asarray(graph.senders)[emask]
    receivers = np.asarray(graph.receivers)[emask]
    n_pad = graph.n_nodes_padded
    nb = _round_up(n_pad, block) // block

    blk = receivers // block
    counts = np.bincount(blk, minlength=nb)
    width = _round_up(max(int(counts.max()), 1), 128)

    src = np.zeros((nb, width), dtype=np.int32)
    local_dst = np.zeros((nb, width), dtype=np.int32)
    mask = np.zeros((nb, width), dtype=bool)
    # receivers are sorted, so each block's edges are contiguous.
    starts = np.searchsorted(blk, np.arange(nb))
    ends = np.searchsorted(blk, np.arange(nb), side="right")
    for b in range(nb):
        lo, hi = starts[b], ends[b]
        n = hi - lo
        src[b, :n] = senders[lo:hi]
        local_dst[b, :n] = receivers[lo:hi] % block
        mask[b, :n] = True

    return BlockedEdges(
        src=jnp.asarray(src),
        local_dst=jnp.asarray(local_dst),
        mask=jnp.asarray(mask),
        block=block,
    )


def propagate_sum_blocked(blocked: BlockedEdges, signal: jax.Array,
                          node_mask: jax.Array) -> jax.Array:
    """Per-node sum over incoming edges via batched one-hot matmul.

    ``signal`` f32[N_pad] -> f32[N_pad]; all MXU, no scatter.
    """
    contrib = signal[blocked.src] * blocked.mask.astype(signal.dtype)  # [NB, W]
    onehot = (
        blocked.local_dst[:, :, None]
        == jnp.arange(blocked.block, dtype=jnp.int32)[None, None, :]
    ).astype(signal.dtype)  # [NB, W, B]
    out = jnp.einsum(
        "nw,nwb->nb", contrib, onehot, preferred_element_type=jnp.float32
    )
    out = out.reshape(-1)[: node_mask.shape[0]]
    return out * node_mask.astype(signal.dtype)


def propagate_or_blocked(blocked: BlockedEdges, signal: jax.Array,
                         node_mask: jax.Array) -> jax.Array:
    """Per-node OR over incoming edges (0/1 contributions: sum > 0)."""
    out = propagate_sum_blocked(blocked, signal.astype(jnp.float32), node_mask)
    return (out > 0) & node_mask
