"""Blocked edge aggregation: segment reduction as one-hot matmuls.

XLA lowers ``segment_sum``/``segment_max`` to scatter, which serializes badly
on TPU. But with edges sorted by receiver, each 128-node output block owns a
contiguous edge range; padding those ranges to a common width turns the
whole reduction into a batched matmul against one-hot destination masks —
dense MXU work with zero scatters:

    out[b, v] = sum_e contrib[b, e] * (local_dst[b, e] == v)

This module builds the blocked representation (host-side, one-off) and runs
the einsum lowering; ops/pallas_edge.py is the fused Pallas kernel of the
same scheme (it never materializes the one-hot in HBM).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.sim.graph import Graph, _padded_row_fill, _round_up

#: Output rows per block — one VPU/MXU lane tile.
NODE_BLOCK = 128


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BlockedEdges:
    """Edges regrouped by 128-node destination block.

    ``src``/``local_dst``/``mask`` have shape ``[n_blocks, width]`` where
    ``width`` covers the largest per-block edge count (multiple of 128).
    ``local_dst`` is the destination index within its block (0..127).
    """

    src: jax.Array  # i32[NB, W]
    local_dst: jax.Array  # i32[NB, W]
    mask: jax.Array  # bool[NB, W]
    block: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_blocks(self) -> int:
        return self.src.shape[0]

    @property
    def width(self) -> int:
        return self.src.shape[1]


def build_blocked(graph: Graph, block: int = NODE_BLOCK) -> BlockedEdges:
    """Group the graph's (dst-sorted) edges by destination block."""
    emask = np.asarray(graph.edge_mask)
    senders = np.asarray(graph.senders)[emask]
    receivers = np.asarray(graph.receivers)[emask]
    return build_blocked_from_arrays(senders, receivers, graph.n_nodes_padded, block)


def build_blocked_arrays_np(
    senders: np.ndarray, receivers: np.ndarray, n_pad: int, block: int = NODE_BLOCK
):
    """The blocked layout as HOST arrays ``(src, local_dst, mask)`` —
    callers that repack many layouts (the sharded ring builds one per
    bucket) stay in numpy instead of paying a device round trip each."""
    nb = _round_up(n_pad, block) // block

    blk = receivers // block
    counts = np.bincount(blk, minlength=nb)
    width = _round_up(max(int(counts.max()), 1), 128)

    # receivers are sorted, so each block's edges are contiguous; one fancy
    # index fills every row (vectorized — a per-block Python loop dominates
    # graph build time at millions of edges).
    starts = np.searchsorted(blk, np.arange(nb))
    take, mask = _padded_row_fill(starts, counts, width)
    e = senders.size
    src_pool = senders if e else np.zeros(1, dtype=np.int32)
    dst_pool = receivers if e else np.zeros(1, dtype=np.int32)
    take = np.minimum(take, max(e - 1, 0))
    src = np.where(mask, src_pool[take], 0).astype(np.int32)
    local_dst = np.where(mask, dst_pool[take] % block, 0).astype(np.int32)
    return src, local_dst, mask


def build_blocked_from_arrays(
    senders: np.ndarray, receivers: np.ndarray, n_pad: int, block: int = NODE_BLOCK
) -> BlockedEdges:
    """Blocked representation from host edge arrays (``receivers`` sorted
    non-decreasing; any subset of a graph's active edges qualifies)."""
    src, local_dst, mask = build_blocked_arrays_np(senders, receivers, n_pad, block)
    return BlockedEdges(
        src=jnp.asarray(src),
        local_dst=jnp.asarray(local_dst),
        mask=jnp.asarray(mask),
        block=block,
    )


def onehot_apply(contrib: jax.Array, local_dst: jax.Array, block: int,
                 out_len: int) -> jax.Array:
    """The one-hot-matmul core: reduce ``contrib [NB, W]`` into its
    destinations — ``out[v] = sum_w contrib[nb, w] * (local_dst == v%block)``
    — as one batched einsum (MXU work, no scatter). f32 accumulation;
    bf16 ``contrib`` is exact for 0/1 payloads.
    """
    onehot = (
        local_dst[:, :, None]
        == jnp.arange(block, dtype=jnp.int32)[None, None, :]
    ).astype(contrib.dtype)  # [NB, W, B]
    out = jnp.einsum(
        "nw,nwb->nb", contrib, onehot, preferred_element_type=jnp.float32
    )
    return out.reshape(-1)[:out_len]


def propagate_sum_blocked(blocked: BlockedEdges, signal: jax.Array,
                          node_mask: jax.Array) -> jax.Array:
    """Per-node sum over incoming edges via batched one-hot matmul.

    ``signal`` f32[N_pad] -> f32[N_pad]; all MXU, no scatter.
    """
    contrib = signal[blocked.src] * blocked.mask.astype(signal.dtype)  # [NB, W]
    out = onehot_apply(contrib, blocked.local_dst, blocked.block,
                       node_mask.shape[0])
    return out * node_mask.astype(signal.dtype)


def propagate_or_blocked(blocked: BlockedEdges, signal: jax.Array,
                         node_mask: jax.Array) -> jax.Array:
    """Per-node OR over incoming edges (0/1 contributions: sum > 0)."""
    out = propagate_sum_blocked(blocked, signal.astype(jnp.float32), node_mask)
    return (out > 0) & node_mask
