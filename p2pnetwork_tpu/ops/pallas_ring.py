"""Pallas TPU ring-DMA halo exchange: async remote copies over ICI.

The sharded ring (parallel/sharded.py) moves each shard's resident
frontier block to its ring neighbor once per ring step. As XLA
``lax.ppermute`` that transfer is a collective the scheduler serializes
against the bucket compute consuming the block; here the same hop is a
``pltpu.make_async_remote_copy`` issued from inside a Pallas kernel — the
DMA engine moves the halo while the shard's local propagation work runs,
the classic communication/computation overlap of the ring-attention /
multi-node-GCN literature (PAPERS.md).

Two kernels:

- :func:`ring_shift` — the bare halo hop: copy the whole payload to the
  next (or previous) ring neighbor. Payload-shape agnostic (bool
  frontier blocks, f32 value blocks, ``u32[W, block]`` lane words — one
  DMA round then moves 32 in-flight messages' boundary state per word).
- :func:`ring_segment_sum` — the FUSED ring step: start the halo DMA of
  the resident block at grid step 0, run the blocked one-hot-matmul
  segment sum (the ops/pallas_edge.py scheme) across the whole grid
  while the transfer is in flight, wait on the receive semaphore at the
  last grid step. The shard-local edge aggregation IS the overlap window.

Both run under ``shard_map`` on a ring mesh and are bit-identical to the
``ppermute`` formulation (the parity contract tests/test_ring.py pins).
On CPU they run in the Pallas interpreter — the interpreter honors
cross-device ``make_async_remote_copy``, so CI proves bit-identity on
the 8-device virtual mesh without chips; real overlap is a chip-only
property (the interpreter executes sequentially).

Kernel functions are named ``ring_halo_*`` on purpose: the name lands in
the ``pallas_call`` eqn's ``name_and_src_info``, which is how the ICI
accounting recognizes DMA traffic a collective census would otherwise
read as zero bytes (parallel/commviz.py ``RING_DMA_MARKER``,
analysis/ir/registry.py collective census).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from p2pnetwork_tpu.ops.pallas_edge import ROW_TILE, TILE_W, _is_cpu

#: Marker every ring-DMA kernel's function name carries — the handle the
#: ICI byte accounting greps for in ``pallas_call`` eqns (commviz /
#: graftaudit). The kernels' FIRST output is, by convention, the DMA
#: payload (the received block), so ``outvars[0]`` prices the hop.
RING_DMA_MARKER = "ring_halo"


def _neighbor(axis_name: str, axis_size: int, reverse: bool):
    """Logical device id of the ring neighbor this kernel copies TO.

    Forward (``reverse=False``) sends to ``my + 1``: after the copy,
    shard ``d`` holds the block previously on ``d - 1`` — exactly
    ``lax.ppermute(x, axis, [(i, (i+1) % S)])`` (sharded._ring_perm).
    Reverse sends to ``my - 1`` (the remask Horner accumulation's
    back-rotation).
    """
    my = lax.axis_index(axis_name)
    if reverse:
        return lax.rem(my + axis_size - 1, axis_size)
    return lax.rem(my + 1, axis_size)


def _ring_halo_copy_kernel(src_ref, dst_ref, send_sem, recv_sem, *,
                           axis_name: str, axis_size: int, reverse: bool):
    neighbor = _neighbor(axis_name, axis_size, reverse)
    copy = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=neighbor,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    copy.start()
    copy.wait()  # graftlint: ignore[wait-untimed] -- Pallas DMA-semaphore wait inside a kernel, not a thread wait; Mosaic has no timeout form


@functools.lru_cache(maxsize=256)
def _shift_call(shape, dtype, axis_name: str, axis_size: int, reverse: bool,
                interpret: bool):
    kernel = functools.partial(
        _ring_halo_copy_kernel, axis_name=axis_name, axis_size=axis_size,
        reverse=reverse,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
        interpret=interpret,
    )


def ring_shift(x: jax.Array, axis_name: str, axis_size: int, *,
               reverse: bool = False,
               interpret: bool | None = None) -> jax.Array:
    """One ring halo hop as an async remote copy: the Pallas twin of
    ``lax.ppermute(x, axis_name, [(i, (i+1) % S)])`` (``reverse=True``
    for the ``[((i+1) % S, i)]`` back-rotation).

    Must run inside a ``shard_map`` body over a ring mesh of
    ``axis_size`` devices; ``x`` is the per-shard block (any shape or
    dtype — frontier bools, value floats, lane words). Under
    ``axis_size == 1`` the hop is the identity, matching what the
    ppermute formulation's callers skip at trace time.
    """
    if axis_size == 1:
        return x
    if interpret is None:
        interpret = _is_cpu()
    fn = _shift_call(tuple(x.shape), jnp.dtype(x.dtype).name, axis_name,
                     axis_size, reverse, interpret)
    return fn(x)


def _ring_halo_segsum_kernel(rot_ref, contrib_ref, dst_ref,
                             rot_out_ref, out_ref, send_sem, recv_sem, *,
                             axis_name: str, axis_size: int,
                             n_i: int, n_j: int, tile_w: int, precision):
    """Fused ring step: the halo DMA of the resident block rides UNDER the
    blocked one-hot segment sum. Grid step (0, 0) starts the copy; every
    step accumulates its ``[ROW_TILE, TILE_W]`` strip's partial product
    (ops/pallas_edge.py scheme — the one-hot never touches HBM); the last
    step waits on the receive semaphore. The whole edge aggregation is
    the transfer's overlap window."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    neighbor = _neighbor(axis_name, axis_size, reverse=False)
    copy = pltpu.make_async_remote_copy(
        src_ref=rot_ref,
        dst_ref=rot_out_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=neighbor,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )

    @pl.when((i == 0) & (j == 0))
    def _():
        copy.start()

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    contrib = contrib_ref[:]  # [ROW_TILE, TILE_W] f32
    dst = dst_ref[:]  # [ROW_TILE, TILE_W] i32
    rows, block = contrib.shape[0], out_ref.shape[-1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (rows, tile_w, block), 2)
    onehot = (dst[:, :, None] == iota).astype(jnp.float32)
    partial = jax.lax.dot_general(
        contrib[:, None, :],  # [R, 1, W]
        onehot,  # [R, W, B]
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=precision,
    )  # [R, 1, B]
    out_ref[:] += partial[:, 0, :]

    @pl.when((i == n_i - 1) & (j == n_j - 1))
    def _():
        copy.wait()  # graftlint: ignore[wait-untimed] -- Pallas DMA-semaphore wait (recv fence of the fused ring step), not a thread wait


@functools.lru_cache(maxsize=256)
def _segsum_call(rot_shape, rot_dtype, nb_pad: int, w: int, block: int,
                 tile_w: int, axis_name: str, axis_size: int, exact: bool,
                 interpret: bool):
    n_i, n_j = nb_pad // ROW_TILE, w // tile_w
    precision = (jax.lax.Precision.HIGHEST if exact
                 else jax.lax.Precision.DEFAULT)
    kernel = functools.partial(
        _ring_halo_segsum_kernel, axis_name=axis_name, axis_size=axis_size,
        n_i=n_i, n_j=n_j, tile_w=tile_w, precision=precision,
    )
    return pl.pallas_call(
        kernel,
        grid=(n_i, n_j),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec((ROW_TILE, tile_w), lambda i, j: (i, j)),
            pl.BlockSpec((ROW_TILE, tile_w), lambda i, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec((ROW_TILE, block), lambda i, j: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(rot_shape, rot_dtype),
            jax.ShapeDtypeStruct((nb_pad, block), jnp.float32),
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
        interpret=interpret,
    )


def ring_segment_sum(rot: jax.Array, contrib: jax.Array,
                     local_dst: jax.Array, axis_name: str, axis_size: int,
                     block: int = 128, tile_w: int = TILE_W, *,
                     exact: bool = True,
                     interpret: bool | None = None):
    """The fused ring step: ``(rot_next, out)`` where ``rot_next`` is
    ``rot`` received from the ring's previous shard (the forward halo
    hop) and ``out[n, b] = sum_w contrib[n, w] * (local_dst[n, w] == b)``
    — the blocked segment sum of ops/pallas_edge.py with the halo DMA
    overlapped under its grid.

    ``contrib`` f32[NB, W] (masked slots 0), ``local_dst`` i32[NB, W] in
    [0, block). Padding contracts, ``exact`` semantics and the returned
    sum are ops/pallas_edge.segment_sum_pallas_impl's exactly; ``rot``
    is any per-shard block. Must run inside a ``shard_map`` body over a
    ring of ``axis_size >= 2`` devices (at 1 there is no halo — callers
    use the plain kernel).
    """
    if axis_size < 2:
        raise ValueError("ring_segment_sum needs a ring of >= 2 shards")
    nb, w = contrib.shape
    if block % 128 != 0:
        raise ValueError(
            f"block must be a multiple of 128 (lane width), got {block}")
    if w % tile_w != 0:
        pad = tile_w - w % tile_w
        contrib = jnp.pad(contrib, ((0, 0), (0, pad)))
        local_dst = jnp.pad(local_dst, ((0, 0), (0, pad)))
        w += pad
    nb_pad = nb
    if nb % ROW_TILE != 0:
        row_pad = ROW_TILE - nb % ROW_TILE
        contrib = jnp.pad(contrib, ((0, row_pad), (0, 0)))
        local_dst = jnp.pad(local_dst, ((0, row_pad), (0, 0)))
        nb_pad += row_pad
    if interpret is None:
        interpret = _is_cpu()
    fn = _segsum_call(tuple(rot.shape), jnp.dtype(rot.dtype).name, nb_pad,
                      w, block, tile_w, axis_name, axis_size, exact,
                      interpret)
    rot_next, out = fn(rot, contrib, local_dst)
    return rot_next, out[:nb]
