"""Frontier-compacted propagation: gather only the active rows.

The dense lowerings (ops/segment.py) price a propagation round by the
GRAPH — every padded edge slot rides the ~8 cycles/element gather floor
(BENCH.md) whether one node is active or half the population is. But a
flood's life is asymmetric: the first and last rounds move a sliver of
the graph. This module prices the round by the FRONTIER instead: inside
jit, ``nonzero``-compact the active nodes into a bounded ``k``-slot
buffer, gather exactly their out-edge rows through the source-CSR view
(``Graph.src_eid``/``src_offsets``), and scatter the contributions into
the receiver vector — ``k * max_out_span`` touched slots, independent of
``E_pad``. A ``lax.cond`` falls back to the dense path the moment the
active count exceeds the buffer, so the compiled program carries both
rounds and the round's cost tracks its frontier. This is the
frontier/activity compaction the GNN-acceleration literature rides on
dense hardware (PAPERS.md: *Fast Training of Sparse Graph Neural
Networks on Dense Hardware*; *A Survey on GNN Acceleration*).

Crossover: the sparse round touches ``k * max_out_span`` gathered slots
plus a same-sized scatter; the dense round touches ``E_pad`` slots. The
auto budget therefore sizes ``k`` so the sparse slot count stays under
``E_pad / CROSSOVER_SLOT_FACTOR`` — the factor 2 default covers the
scatter's second pass over the gathered slots (scatter ~ gather on the
TPU's flat per-element floor, BENCH.md "segment buckets"). It is a
measured starting point, not a guess-forever: bench.py attributes
per-round frontier occupancy into BENCH_TELEMETRY.json so the constant
can be re-fit from real runs; override per call via ``crossover=`` (an
int node budget, or a float fraction of padded nodes).

Results are BIT-exact vs the dense paths: OR/max/min are associative and
commutative in f32/int, and every per-edge contribution
(``signal[sender]``, ``dist[sender] + weight``) is computed from the
same operands with the same op as the dense lowering — only the
iteration order differs, which these reductions cannot observe
(tests/test_frontier.py sweeps the equivalence).

Dynamic (runtime-connected) edges never reach this module: the
``propagate_*`` entry points fold the dynamic COO region in before
method dispatch, exactly as for every other lowering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph

#: Sparse slots (budget * max_out_span) stay under E_pad / this factor.
#: 2.0 = "sparse must beat dense even if its per-slot cost doubles"
#: (gather + scatter passes vs the dense path's single gather).
CROSSOVER_SLOT_FACTOR = 2.0

#: Floor for the compaction buffer — one lane-friendly tile; below this
#: the buffer bookkeeping costs more than the slots it saves.
_MIN_BUDGET = 128


def require_csr(graph: Graph) -> None:
    if graph.src_eid is None:
        raise ValueError(
            "method='frontier' requires the source-CSR out-edge view — "
            "build with from_edges(source_csr=True) or "
            "graph.with_source_csr()"
        )


def budget(graph: Graph, crossover=None) -> int:
    """STATIC node budget ``k`` of the compaction buffer (trace-time int).

    ``crossover=None`` auto-sizes from the slot arithmetic above;
    a float in (0, 1] is a fraction of padded nodes; an int is the node
    budget itself. Auto returns **0 — sparse disabled —** when even the
    ``_MIN_BUDGET`` floor would break the slot bound (a hub graph whose
    ``max_out_span`` row spans much of ``E_pad``: the sparse gather is
    always ``k * span`` slots whatever the frontier, so there it can
    only LOSE to dense). Otherwise the result is clamped to
    ``[_MIN_BUDGET, n_nodes_padded]`` — a budget covering every node
    simply makes the sparse path unconditional. Explicit overrides are
    honored as given (clamped to ``n_pad``).
    """
    n_pad = graph.n_nodes_padded
    span = max(graph.max_out_span, 1)
    if crossover is None:
        k = graph.n_edges_padded // max(int(CROSSOVER_SLOT_FACTOR * span), 1)
        if k < _MIN_BUDGET:
            return 0
    elif isinstance(crossover, float):
        if not 0.0 < crossover <= 1.0:
            raise ValueError(f"crossover fraction must be in (0, 1], got "
                             f"{crossover}")
        k = int(crossover * n_pad)
    else:
        k = int(crossover)
    return max(_MIN_BUDGET, min(k, n_pad))


def budget_slots(graph: Graph, crossover=None) -> int:
    """Gathered/scattered slots of one SPARSE round: ``k · max_out_span``
    (0 = sparse disabled on this graph). This is the IR-level invariant
    the compiled program must honor — graftaudit's
    ``ir-gather-slot-budget`` rule (analysis/ir/rules.py) checks every
    gather/scatter of the sparse branch against exactly this number, so
    the bound lives here, next to the budget arithmetic it derives from,
    not re-derived in the auditor."""
    k = budget(graph, crossover)
    return k * max(graph.max_out_span, 1) if k else 0


def budget_slots_lanes(graph: Graph, crossover=None, n_words: int = 1) -> int:
    """The slot bound of one LANE-PACKED sparse round
    (:func:`propagate_or_lanes_frontier`): the compacted gather is the
    same ``k · span`` edge slots as the single-message path — one u32
    gather serves all 32 lanes of a word — but the 32-message-wide
    scatter moves a bit-plane row per slot, so the scattered element
    count is ``k · span · 32`` per word (× ``n_words`` under the vmap).
    graftaudit checks the batched lowerings against exactly this number
    (0 = sparse disabled)."""
    from p2pnetwork_tpu.ops import bitset

    return budget_slots(graph, crossover) * bitset.WORD * max(n_words, 1)


def occupancy(graph: Graph, frontier: jax.Array) -> jax.Array:
    """Active fraction of live nodes — the device-side stat the sparse/
    dense crossover is measured by (f32 scalar)."""
    n = jnp.maximum(jnp.sum(graph.node_mask), 1)
    live = jnp.sum((frontier & graph.node_mask).astype(jnp.int32))
    return (live / n).astype(jnp.float32)


def _gather_active(graph: Graph, active: jax.Array, n_active: jax.Array,
                   k: int):
    """Compact the active nodes and gather their full out-edge rows.

    Returns ``(f, eid, evalid)``: the ``k`` compacted node ids, their
    ``[k, max_out_span]`` edge ids, and the liveness mask (in-row AND
    slot-valid AND runtime ``edge_mask`` — failed edges stay in the
    build-time CSR rows and are masked here, the adaptive-flood rule).
    Only correct when ``n_active <= k`` — the callers' ``lax.cond``
    guarantees it (``nonzero`` truncates past ``k``).
    """
    n_pad = graph.n_nodes_padded
    idx = jnp.nonzero(active, size=k, fill_value=n_pad - 1)[0].astype(
        jnp.int32)
    valid = jnp.arange(k) < n_active
    # fill_value rows can be REAL (node n_pad-1 exists when n_nodes is an
    # exact pad multiple); `valid` masks them out of every contribution.
    f = jnp.where(valid, idx, n_pad - 1)
    w = max(graph.max_out_span, 1)
    eid, in_row = graph.gather_row_slots(
        graph.src_offsets[f], graph.src_offsets[f + 1], w)
    evalid = in_row & valid[:, None] & graph.edge_mask[eid]
    return f, eid, evalid


def propagate_or_frontier(graph: Graph, signal: jax.Array, dense_fn,
                          crossover=None) -> jax.Array:
    """Frontier-compacted neighbor-OR; ``dense_fn(signal)`` is the dense
    fallback taken when the active count exceeds the budget."""
    require_csr(graph)
    k = budget(graph, crossover)
    if k == 0:  # sparse can't win on this graph (see budget) — trace-time
        return dense_fn(signal)
    n_active = jnp.sum(signal.astype(jnp.int32))

    def sparse(sig):
        n_pad = graph.n_nodes_padded
        _, eid, evalid = _gather_active(graph, sig, n_active, k)
        cand = jnp.where(evalid, graph.receivers[eid], n_pad).reshape(-1)
        out = jnp.zeros(n_pad, dtype=bool).at[cand].set(True, mode="drop")
        return out & graph.node_mask

    return jax.lax.cond(n_active <= k, sparse, dense_fn, signal)


def propagate_or_lanes_frontier(graph: Graph, lanes: jax.Array, dense_fn,
                                crossover=None) -> jax.Array:
    """Frontier-compacted LANE-PACKED neighbor-OR: one compaction serves
    B = 32·W concurrent broadcasts (``lanes`` is ``u32[W, N_pad]``, bit L
    of word w = message 32w+L — ops/bitset.py lane algebra).

    A node is in the *batch frontier* if ANY lane of ANY word set it —
    the compaction (``nonzero`` into the same ``k``-slot buffer as the
    single-message path) runs ONCE on that union, its gathered edge rows
    are shared by every word, and each word then pays one ``k·span`` u32
    gather of its lane values plus one 32-message-wide scatter-OR
    (``bitset.or_scatter_lanes``) — vmapped over words for B > 32. The
    ``lax.cond`` sits OUTSIDE the vmap on the union count, so the
    sparse/dense decision is shared (a vmapped cond would lower to a
    select that executes both branches for every word, wiping out the
    compaction win); one word with a dense frontier routes the whole
    batch dense, which costs at most the dense bound it would pay anyway.
    ``dense_fn(lanes)`` is that fallback."""
    require_csr(graph)
    k = budget(graph, crossover)
    if k == 0:  # sparse can't win on this graph (see budget) — trace-time
        return dense_fn(lanes)
    n_active = jnp.sum(jnp.any(lanes != 0, axis=0).astype(jnp.int32))

    def sparse(ln):
        from p2pnetwork_tpu.ops import bitset

        n_pad = graph.n_nodes_padded
        f, eid, evalid = _gather_active(
            graph, jnp.any(ln != 0, axis=0), n_active, k)
        cand = jnp.where(evalid, graph.receivers[eid], n_pad).reshape(-1)

        def word(wl):
            vals = jnp.where(evalid, wl[f][:, None],
                             jnp.uint32(0)).reshape(-1)
            return bitset.or_scatter_lanes(n_pad, cand, vals)

        out = jax.vmap(word)(ln)
        return out & jnp.where(graph.node_mask, jnp.uint32(0xFFFFFFFF),
                               jnp.uint32(0))

    return jax.lax.cond(n_active <= k, sparse, dense_fn, lanes)


def propagate_max_frontier(graph: Graph, signal: jax.Array,
                           neutral: jax.Array, dense_fn,
                           crossover=None) -> jax.Array:
    """Frontier-compacted neighbor-max. Active = holding a non-neutral
    value (``!=`` keeps NaN signals active, matching dense NaN
    propagation); neutral senders contribute the identity either way."""
    require_csr(graph)
    k = budget(graph, crossover)
    if k == 0:  # sparse can't win on this graph (see budget) — trace-time
        return dense_fn(signal)
    active = signal != neutral
    n_active = jnp.sum(active.astype(jnp.int32))

    def sparse(sig):
        n_pad = graph.n_nodes_padded
        f, eid, evalid = _gather_active(graph, sig != neutral, n_active, k)
        vals = jnp.where(evalid, sig[f][:, None], neutral).reshape(-1)
        cand = jnp.where(evalid, graph.receivers[eid], n_pad).reshape(-1)
        agg = jnp.full(n_pad, neutral, dtype=sig.dtype).at[cand].max(
            vals, mode="drop")
        return jnp.where(graph.node_mask, agg, neutral)

    return jax.lax.cond(n_active <= k, sparse, dense_fn, signal)


def propagate_min_plus_frontier(graph: Graph, dist: jax.Array, dense_fn,
                                crossover=None) -> jax.Array:
    """Frontier-compacted min-plus relaxation (one Bellman-Ford round).
    Active = finite-or-NaN distance; +inf senders contribute +inf to
    every receiver in the dense path too, so skipping them is exact.
    Weights ride the per-edge channel gathered at the same edge ids the
    dense path reads, so each contribution is the identical f32 add."""
    require_csr(graph)
    k = budget(graph, crossover)
    if k == 0:  # sparse can't win on this graph (see budget) — trace-time
        return dense_fn(dist)
    active = dist != jnp.inf
    n_active = jnp.sum(active.astype(jnp.int32))

    def sparse(d):
        n_pad = graph.n_nodes_padded
        f, eid, evalid = _gather_active(graph, d != jnp.inf, n_active, k)
        w_e = 1.0 if graph.edge_weight is None else graph.edge_weight[eid]
        vals = jnp.where(evalid, d[f][:, None] + w_e, jnp.inf).reshape(-1)
        cand = jnp.where(evalid, graph.receivers[eid], n_pad).reshape(-1)
        agg = jnp.full(n_pad, jnp.inf, dtype=d.dtype).at[cand].min(
            vals, mode="drop")
        return jnp.where(graph.node_mask, agg, jnp.inf)

    return jax.lax.cond(n_active <= k, sparse, dense_fn, dist)
