"""Non-boolean query lanes: f32/i32 lane carriers + the byte budget.

The batched message plane (ops/bitset.py, models/messagebatch.py) packs 32
BOOLEAN predicates per uint32 word — 32 concurrent broadcasts in the
footprint of one. The query families this module serves (min-plus routing,
DHT successor chases, push-sum aggregation — models/querybatch.py) carry
REAL values per lane: an f32 distance, an i32 cursor, two f32 masses. No
bit packing exists for those — K lanes cost K full-width columns of HBM,
which is the PR-10 expansion lesson ([N, 32] bit-plane blowups cost
400 MB/round at B=1024) made permanent: **K is budgeted by bytes**, via
:func:`lane_budget`, and every family's ``init``/``admit`` refuses an
over-budget K with a loud typed error instead of silently OOMing mid-run.

Layout: lane matrices are **node-major** — ``dtype[N_pad, K]``, the lane
axis innermost — so one gathered node row moves K contiguous lane values
(the f32 analog of 32 bit lanes riding one u32 word). The transposed
``[K, N]`` layout turns every per-edge access into a K-strided walk; on
the CPU backend that is the difference between a streaming kernel and a
scatter of cache misses (measured ~50x at the 100k-node ratchet class's
K=64).

Kernels (each = the scalar ops/segment.py kernel applied per lane column,
value-for-value):

- :func:`propagate_min_plus_lanes` — K Bellman-Ford relaxations per
  round. ``gather`` unrolls the neighbor table's degree axis into D
  row-gather+minimum passes over the lane matrix (contiguous K-wide
  rows; the fast path); ``segment`` lifts the sorted-COO segment-min to
  ``[E_pad, K]`` operands (any graph, no table needed — segment ops
  take ND data with segments along axis 0).
- :func:`propagate_sum_lanes` — the same two lowerings for sums.
- :func:`dht_hop_lanes` — one greedy DHT hop per lane: gather each
  cursor's neighbor row, score it against the lane's key under the
  overlay's distance metric (ring / xor), step to the closest strictly
  improving neighbor.

Both propagate lowerings are exact per lane: min is order-blind in f32,
and the sum lowerings accumulate in the receiver-sorted edge order (the
neighbor table enumerates exactly that order), matching
``propagate_sum(method="segment")`` bitwise — the float-op-order contract
the push-sum family pins (tests/test_querybatch.py).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph

__all__ = [
    "DEFAULT_LANE_BUDGET_BYTES",
    "LaneBudgetExceeded",
    "lane_bytes",
    "lane_budget",
    "propagate_min_plus_lanes",
    "propagate_sum_lanes",
    "dht_hop_lanes",
]

#: Default per-state lane-carry budget. Sized for the CI/CPU world and the
#: single-chip HBM story alike: 1 GiB of lane carry double-buffers to
#: ~2 GiB inside a donationless loop, comfortably inside one v4 chip's
#: HBM next to the graph. ``P2P_LANE_BUDGET_BYTES`` overrides (serving
#: deployments size it to the chip minus the graph's resident footprint).
DEFAULT_LANE_BUDGET_BYTES = 1 << 30


class LaneBudgetExceeded(ValueError):
    """Lane admission refused: the requested K does not fit the byte
    budget.

    The non-boolean lane families carry ``itemsize * n_pad`` bytes PER
    LANE per carrier — there is no 32-per-word packing to hide behind
    (boolean lanes get that for free; see :func:`lane_bytes`). Sizing K
    "like the batched floods" silently multiplies HBM by the itemsize,
    which is exactly how the PR-10 ``[N, 32]`` expansion reached
    400 MB/round. This error names the numbers so the caller can budget:
    ``requested_bytes`` for the asked-for capacity, ``budget_bytes`` for
    the ceiling, plus the ``capacity``/``dtype``/``n_pad``/``carriers``
    that produced them."""

    def __init__(self, requested_bytes: int, budget_bytes: int, *,
                 capacity: int, dtype, n_pad: int, carriers: int):
        self.requested_bytes = int(requested_bytes)
        self.budget_bytes = int(budget_bytes)
        self.capacity = int(capacity)
        self.dtype = jnp.dtype(dtype)
        self.n_pad = int(n_pad)
        self.carriers = int(carriers)
        super().__init__(
            f"{capacity} lanes of {self.dtype.name}[{n_pad}] x "
            f"{carriers} carrier(s) need {self.requested_bytes:,} bytes "
            f"of lane carry — over the {self.budget_bytes:,}-byte budget. "
            f"Lower K, shrink the graph, or raise the budget "
            f"(budget_bytes= / P2P_LANE_BUDGET_BYTES).")


def lane_bytes(capacity: int, dtype, n_pad: int, *,
               carriers: int = 1) -> int:
    """Bytes of lane carry for ``capacity`` lanes of one ``dtype[n_pad]``
    signal, times ``carriers`` state arrays (push-sum carries two).

    ``bool`` lanes are the exception that motivates the whole helper:
    they pack 32 per uint32 word (ops/bitset.py), so their cost is
    ``ceil(capacity / 32)`` words — the batched flood plane's 32-free
    lanes. Every other dtype pays full width per lane, which is the
    asymmetry callers must budget for: 1024 boolean lanes on a 100k-node
    graph cost ~12.8 MB per predicate; 1024 f32 lanes cost ~400 MB."""
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    if n_pad < 1:
        raise ValueError(f"n_pad must be >= 1, got {n_pad}")
    if carriers < 1:
        raise ValueError(f"carriers must be >= 1, got {carriers}")
    dt = jnp.dtype(dtype)
    if dt == jnp.dtype(bool):
        words = -(-int(capacity) // 32)
        return words * 4 * int(n_pad) * int(carriers)
    return int(capacity) * dt.itemsize * int(n_pad) * int(carriers)


def lane_budget(capacity: int, dtype, n_pad: int, *, carriers: int = 1,
                budget_bytes: int = None) -> int:
    """Check ``capacity`` lanes against the byte budget; returns the
    byte cost or raises :class:`LaneBudgetExceeded`.

    The gate every query family's ``init``/``admit`` runs before touching
    device memory. ``budget_bytes=None`` reads ``P2P_LANE_BUDGET_BYTES``
    (default :data:`DEFAULT_LANE_BUDGET_BYTES`); pass an explicit budget
    to size a deployment's lane pool against its real HBM headroom."""
    cost = lane_bytes(capacity, dtype, n_pad, carriers=carriers)
    if budget_bytes is None:
        budget_bytes = int(os.environ.get("P2P_LANE_BUDGET_BYTES",
                                          DEFAULT_LANE_BUDGET_BYTES))
    if cost > int(budget_bytes):
        raise LaneBudgetExceeded(cost, budget_bytes, capacity=capacity,
                                 dtype=dtype, n_pad=n_pad,
                                 carriers=carriers)
    return cost


def _auto_lane_method(graph: Graph) -> str:
    """``auto`` for the lane kernels: the neighbor-table gather under the
    scalar path's waste bound, else the ND segment form. The skew/MXU
    lowerings have no lane form (same surface as propagate_or_lanes)."""
    return "gather" if segment._gather_ok(graph) else "segment"


def _require_no_dyn(graph: Graph, what: str) -> None:
    if graph.dyn_senders is not None:
        raise ValueError(
            f"{what} does not fold the dynamic runtime-edge region — "
            "consolidate the topology (sim/topology.py consolidate) "
            "before batching queries over it")


def propagate_min_plus_lanes(graph: Graph, dist: jax.Array,
                             method: str = "auto") -> jax.Array:
    """K min-plus relaxations in one program: ``dist`` is the node-major
    lane matrix ``f32[N_pad, K]``; lane k's column relaxes exactly like
    ``ops/segment.propagate_min_plus`` on that column —
    ``out[v, k] = min(dist[u, k] + w(u, v))`` over live incoming edges,
    ``+inf`` at dead/in-edge-less nodes. Weights come from the graph
    (unit hop cost when unweighted), as in the scalar kernel.

    ``method``: ``"gather"`` unrolls the complete neighbor table's
    degree axis — D row-gather+minimum passes, each moving contiguous
    K-wide lane rows (the fast path; same complete-table requirement as
    the scalar gather); ``"segment"`` lifts the sorted-COO segment-min
    to ``[E_pad, K]`` operands (any graph); ``"auto"`` picks gather
    under the scalar waste bound. Exact per lane for every method (min
    is order-blind in f32)."""
    _require_no_dyn(graph, "propagate_min_plus_lanes")
    if method == "auto":
        method = _auto_lane_method(graph)
    weighted = graph.edge_weight is not None
    if method == "gather":
        segment._require_complete_table(graph)
        if weighted and graph.neighbor_weight is None:
            raise ValueError(
                "method='gather' on a weighted graph needs the aligned "
                "neighbor_weight view — build with from_edges(weights=...)"
                " or Graph.with_weights, or use method='segment'")
        out = jnp.full_like(dist, jnp.inf)
        for d in range(graph.neighbors.shape[1]):
            w = graph.neighbor_weight[:, d, None] if weighted else 1.0
            cand = jnp.where(graph.neighbor_mask[:, d, None],
                             dist[graph.neighbors[:, d]] + w, jnp.inf)
            out = jnp.minimum(out, cand)
    elif method == "segment":
        w = graph.edge_weight[:, None] if weighted else 1.0
        contrib = jnp.where(graph.edge_mask[:, None],
                            dist[graph.senders] + w, jnp.inf)
        out = jax.ops.segment_min(
            contrib, graph.receivers, num_segments=graph.n_nodes_padded,
            indices_are_sorted=True)
    else:
        raise ValueError(
            f"propagate_min_plus_lanes supports method 'segment', "
            f"'gather' or 'auto', got {method!r} (the skew/MXU lowerings "
            f"have no lane form)")
    return jnp.where(graph.node_mask[:, None], out, jnp.inf)


def propagate_sum_lanes(graph: Graph, vals: jax.Array,
                        method: str = "auto") -> jax.Array:
    """K neighbor-sums in one program: ``vals`` is ``f32[N_pad, K]``;
    lane k's column sums like ``propagate_sum(method="segment")`` on that
    column, bitwise — both lowerings here accumulate in the
    receiver-sorted edge order (the neighbor table's rows enumerate
    exactly that order), the float-op-order contract the push-sum family
    pins."""
    _require_no_dyn(graph, "propagate_sum_lanes")
    if method == "auto":
        method = _auto_lane_method(graph)
    if method == "gather":
        segment._require_complete_table(graph)
        out = jnp.zeros_like(vals)
        for d in range(graph.neighbors.shape[1]):
            row = vals[graph.neighbors[:, d]]
            out = out + jnp.where(graph.neighbor_mask[:, d, None], row,
                                  0.0)
    elif method == "segment":
        contrib = jnp.where(graph.edge_mask[:, None], vals[graph.senders],
                            0.0)
        out = jax.ops.segment_sum(
            contrib, graph.receivers, num_segments=graph.n_nodes_padded,
            indices_are_sorted=True)
    else:
        raise ValueError(
            f"propagate_sum_lanes supports method 'segment', 'gather' or "
            f"'auto', got {method!r} (the skew/MXU lowerings have no "
            f"lane form)")
    return out * graph.node_mask.astype(vals.dtype)[:, None]


#: Distance sentinel for masked DHT hop candidates — strictly above any
#: real metric value (node ids are i32, so ring/xor distances < 2^31).
_DHT_FAR = jnp.uint32(0xFFFFFFFF)

#: The DHT overlay metrics: how far a node id is from a key.
DHT_METRICS = ("ring", "xor")


def dht_distance(node: jax.Array, key: jax.Array, n: int,
                 metric: str) -> jax.Array:
    """Overlay distance from ``node`` to ``key`` as ``u32`` (broadcasts).

    ``ring``: clockwise identifier-ring distance ``(key - node) mod n`` —
    what a Chord lookup greedily minimizes hopping its fingers.
    ``xor``: Kademlia's XOR metric ``node ^ key``."""
    if metric == "ring":
        return jnp.mod(key - node, jnp.int32(n)).astype(jnp.uint32)
    if metric == "xor":
        return (node ^ key).astype(jnp.uint32)
    raise ValueError(
        f"unknown DHT metric {metric!r} — one of {DHT_METRICS}")


def dht_hop_lanes(graph: Graph, cur: jax.Array, keys: jax.Array,
                  metric: str = "ring"):
    """One greedy DHT hop for K lookups at once: ``cur``/``keys`` are
    ``i32[K]`` cursors and lookup keys; returns ``(next_cur, hopped)``
    where each lane steps to its cursor's live neighbor closest to the
    key under ``metric`` — but only when that neighbor is STRICTLY
    closer than the cursor itself (``hopped`` bool[K]); a lane at a
    local minimum keeps its cursor, which is the lookup's terminal
    condition (arrived when the cursor IS the key's node, stuck
    otherwise — dead responsible node, partitioned overlay).

    The per-round cost is ``K x max_degree`` — one neighbor-row gather
    per lane (thousands of lookups per compiled round ride one gather),
    which is the whole point: a Chord/Kademlia overlay
    (sim/graph.py ``chord`` / ``kademlia``) resolves lookups in
    O(log n) such rounds. Ties break to the lowest neighbor-slot index,
    deterministically. Requires the complete neighbor table (a
    width-capped table would silently drop routing fingers)."""
    segment._require_complete_table(graph)
    _require_no_dyn(graph, "dht_hop_lanes")
    if metric not in DHT_METRICS:
        raise ValueError(
            f"unknown DHT metric {metric!r} — one of {DHT_METRICS}")
    n = graph.n_nodes
    nbrs = graph.neighbors[cur]                      # i32[K, D]
    valid = graph.neighbor_mask[cur] & graph.node_mask[nbrs]
    d_nbr = jnp.where(valid, dht_distance(nbrs, keys[:, None], n, metric),
                      _DHT_FAR)
    d_cur = dht_distance(cur, keys, n, metric)
    slot = jnp.argmin(d_nbr, axis=1)                 # first-min tie-break
    lane = jnp.arange(cur.shape[0])
    hopped = d_nbr[lane, slot] < d_cur
    return jnp.where(hopped, nbrs[lane, slot], cur), hopped
