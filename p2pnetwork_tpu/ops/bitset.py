"""Bit-packed node predicates: 32 bool lanes per uint32 word.

The flood-family protocols carry two bool[N_pad] predicates (``seen``,
``frontier``) through every ``lax.scan`` / ``lax.while_loop`` iteration.
XLA materializes a bool as one byte, so at 10M padded nodes each predicate
is ~10 MB of carry state double-buffered per round. Packed as uint32 words
the same predicate is 32x smaller, set algebra becomes word-level bitwise
ops (OR = union, AND-NOT = difference), and coverage counting becomes
``lax.population_count`` + a word-sum — the packed-bitset state the sparse
GNN-on-dense-hardware literature rides (PAPERS.md: *Fast Training of
Sparse Graph Neural Networks on Dense Hardware*).

Padding convention: node counts are padded to a multiple of 128
(sim/graph.py ``node_pad_multiple``), which divides 32 exactly, so a
``bool[N_pad]`` packs into ``N_pad // 32`` words with no ragged tail. Bit
``i`` of word ``w`` is node ``32*w + i`` (LSB-first). All functions are
jittable and shape-static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

WORD = 32  #: bits per packed word (uint32 lanes)


def n_words(n_bits: int) -> int:
    """Words needed to hold ``n_bits`` predicates."""
    return (n_bits + WORD - 1) // WORD


def pack_bits(bits: jax.Array) -> jax.Array:
    """``bool[n] -> u32[ceil(n/32)]`` (LSB-first within each word).

    A ragged tail (``n`` not a multiple of 32) zero-pads — harmless for
    the set algebra since the pad bits never get set.
    """
    n = bits.shape[0]
    pad = -n % WORD
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros(pad, dtype=bool)])
    lanes = bits.reshape(-1, WORD).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))[None, :]
    return jnp.sum(lanes * weights, axis=1, dtype=jnp.uint32)


def unpack_bits(words: jax.Array, n_bits: int) -> jax.Array:
    """``u32[W] -> bool[n_bits]`` — inverse of :func:`pack_bits`."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)[None, :]
    lanes = (words[:, None] >> shifts) & jnp.uint32(1)
    return lanes.reshape(-1)[:n_bits].astype(bool)


def popcount(words: jax.Array) -> jax.Array:
    """Total set bits across the whole bitset, as i32 — the word-level
    coverage numerator (``popcount(seen & node_bits)``)."""
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32))


def test_bits(words: jax.Array, idx: jax.Array) -> jax.Array:
    """Membership gather: ``bool`` of bit ``idx[i]`` for each index —
    reads a packed predicate (e.g. ``seen[cand]``) without unpacking.
    Indices must be in range (callers clamp/mask like any other gather).
    """
    w = (idx >> 5).astype(jnp.int32)
    b = (idx & 31).astype(jnp.uint32)
    return ((words[w] >> b) & jnp.uint32(1)).astype(bool)


def set_bits(words: jax.Array, idx: jax.Array, valid: jax.Array) -> jax.Array:
    """Scatter-OR: the bitset with bit ``idx[i]`` set wherever ``valid[i]``.

    Duplicate indices are fine (OR is idempotent). Routed through a
    transient bool scatter + repack rather than a word-level scatter:
    ``.at[].set/max`` cannot OR two different bits landing in one word,
    and the transient costs O(N) bytes once per call, not per carry.
    """
    n = words.shape[0] * WORD
    hit = jnp.zeros(n, dtype=bool).at[
        jnp.where(valid, idx, n)
    ].set(True, mode="drop")
    return words | pack_bits(hit)
