"""Bit-packed node predicates: 32 bool lanes per uint32 word.

The flood-family protocols carry two bool[N_pad] predicates (``seen``,
``frontier``) through every ``lax.scan`` / ``lax.while_loop`` iteration.
XLA materializes a bool as one byte, so at 10M padded nodes each predicate
is ~10 MB of carry state double-buffered per round. Packed as uint32 words
the same predicate is 32x smaller, set algebra becomes word-level bitwise
ops (OR = union, AND-NOT = difference), and coverage counting becomes
``lax.population_count`` + a word-sum — the packed-bitset state the sparse
GNN-on-dense-hardware literature rides (PAPERS.md: *Fast Training of
Sparse Graph Neural Networks on Dense Hardware*).

Padding convention: node counts are padded to a multiple of 128
(sim/graph.py ``node_pad_multiple``), which divides 32 exactly, so a
``bool[N_pad]`` packs into ``N_pad // 32`` words with no ragged tail. Bit
``i`` of word ``w`` is node ``32*w + i`` (LSB-first). All functions are
jittable and shape-static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

WORD = 32  #: bits per packed word (uint32 lanes)


def n_words(n_bits: int) -> int:
    """Words needed to hold ``n_bits`` predicates."""
    return (n_bits + WORD - 1) // WORD


def pack_bits(bits: jax.Array) -> jax.Array:
    """``bool[n] -> u32[ceil(n/32)]`` (LSB-first within each word).

    A ragged tail (``n`` not a multiple of 32) zero-pads — harmless for
    the set algebra since the pad bits never get set.
    """
    n = bits.shape[0]
    pad = -n % WORD
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros(pad, dtype=bool)])
    lanes = bits.reshape(-1, WORD).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32))[None, :]
    return jnp.sum(lanes * weights, axis=1, dtype=jnp.uint32)


def unpack_bits(words: jax.Array, n_bits: int) -> jax.Array:
    """``u32[W] -> bool[n_bits]`` — inverse of :func:`pack_bits`."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)[None, :]
    lanes = (words[:, None] >> shifts) & jnp.uint32(1)
    return lanes.reshape(-1)[:n_bits].astype(bool)


def popcount(words: jax.Array) -> jax.Array:
    """Total set bits across the whole bitset, as i32 — the word-level
    coverage numerator (``popcount(seen & node_bits)``)."""
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32))


def test_bits(words: jax.Array, idx: jax.Array) -> jax.Array:
    """Membership gather: ``bool`` of bit ``idx[i]`` for each index —
    reads a packed predicate (e.g. ``seen[cand]``) without unpacking.
    Indices must be in range (callers clamp/mask like any other gather).
    """
    w = (idx >> 5).astype(jnp.int32)
    b = (idx & 31).astype(jnp.uint32)
    return ((words[w] >> b) & jnp.uint32(1)).astype(bool)


def set_bits(words: jax.Array, idx: jax.Array, valid: jax.Array) -> jax.Array:
    """Scatter-OR: the bitset with bit ``idx[i]`` set wherever ``valid[i]``.

    Duplicate indices are fine (OR is idempotent). Routed through a
    transient bool scatter + repack rather than a word-level scatter:
    ``.at[].set/max`` cannot OR two different bits landing in one word,
    and the transient costs O(N) bytes once per call, not per carry.
    """
    n = words.shape[0] * WORD
    hit = jnp.zeros(n, dtype=bool).at[
        jnp.where(valid, idx, n)
    ].set(True, mode="drop")
    return words | pack_bits(hit)


# --------------------------------------------------------------- lane algebra
#
# The functions above pack 32 NODES into one word (one predicate, bit i of
# word w = node 32w+i). The lane view below is the TRANSPOSE: one uint32
# PER NODE whose bit L is the predicate of *message lane* L — 32 concurrent
# broadcast states in the footprint of one (``u32[N]`` instead of 32 ×
# ``bool[N]``). A batch of B messages stacks ceil(B/32) such lane vectors;
# lane index ``b = 32*w + L`` matches :func:`pack_bits`'s LSB-first order,
# so a ``bool[B]`` per-message flag packs into the per-word lane masks with
# the same function. This is the carry layout of the batched message plane
# (models/messagebatch.py, engine.run_batch_until_coverage).


def expand_lanes(lanes: jax.Array) -> jax.Array:
    """``u32[...] -> bool[..., 32]``: bit L of each word becomes lane
    column L — the transient bit-plane view the lane-wide scatter and the
    per-lane reductions operate on."""
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    return ((lanes[..., None] >> shifts) & jnp.uint32(1)).astype(bool)


def collapse_lanes(bits: jax.Array) -> jax.Array:
    """``bool[..., 32] -> u32[...]`` — inverse of :func:`expand_lanes`."""
    weights = jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)
    return jnp.sum(bits.astype(jnp.uint32) * weights, axis=-1,
                   dtype=jnp.uint32)


#: (shift, mask) schedule of the 32x32 bit-matrix transpose (Hacker's
#: Delight 7-3, vectorized): 5 masked swap passes, each a few u32 ops per
#: word — the whole transpose costs ~5 passes over the input, no
#: expansion.
_TRANSPOSE_STEPS = (
    (16, 0x0000FFFF), (8, 0x00FF00FF), (4, 0x0F0F0F0F),
    (2, 0x33333333), (1, 0x55555555),
)


def transpose_bits32(a: jax.Array) -> jax.Array:
    """Transpose 32x32 bit blocks: ``u32[..., 32] -> u32[..., 32]`` where
    output word L's bit i is input word ``31-i``'s bit ``31-L`` (per
    trailing block) — the Hacker's Delight 7-3 masked-swap transpose,
    which under the LSB-first lane convention lands both axes REVERSED.
    Reductions that only COUNT bits (population_count) are order-blind,
    so callers flip just the lane axis; anything reading individual bits
    must account for both reversals.

    This converts the lane-packed layout (bit L of node-word i = lane L)
    into a per-lane layout whose words ``lax.population_count`` can eat —
    an O(5-passes) alternative to materializing the ``[N, 32]`` bit-plane
    expansion, which at batch scale is hundreds of MB per round."""
    shape = a.shape
    for j, m in _TRANSPOSE_STEPS:
        m = jnp.uint32(m)
        pairs = a.reshape(*shape[:-1], 32 // (2 * j), 2, j)
        top, bot = pairs[..., 0, :], pairs[..., 1, :]
        t = (top ^ (bot >> j)) & m
        a = jnp.stack([top ^ t, bot ^ (t << j)], axis=-2).reshape(shape)
    return a


def lane_counts(lanes: jax.Array, weights: jax.Array = None) -> jax.Array:
    """Per-lane population count across nodes: ``i32[32]`` where entry L
    counts the nodes whose lane-L bit is set in ``lanes`` (``u32[N]``) —
    the lane-masked popcount batched completion detection rides. With
    ``weights`` (``i32[N]``), each set bit contributes its node's weight
    instead of 1 (per-lane message counts: weights = out_degree).

    The unweighted path rides :func:`transpose_bits32` + population_count
    (a few u32 passes over N words); the weighted path has to touch a
    per-(node, lane) product, so it materializes the bit-plane expansion
    — keep it OUT of per-round loops (the batched engine derives per-lane
    message totals once per run from the ``sent`` predicate instead)."""
    if weights is None:
        n = lanes.shape[0]
        if n % WORD:  # pad to whole 32-word blocks (zero bits count 0)
            lanes = jnp.concatenate(
                [lanes, jnp.zeros(WORD - n % WORD, dtype=jnp.uint32)])
        blocks = transpose_bits32(lanes.reshape(-1, WORD))
        counts = jnp.sum(jax.lax.population_count(blocks).astype(jnp.int32),
                         axis=0)
        return counts[::-1]  # transpose lands the lane axis reversed
    planes = expand_lanes(lanes).astype(jnp.int32)
    return jnp.sum(planes * weights[:, None].astype(jnp.int32), axis=0)


def or_scatter_lanes(n: int, idx: jax.Array, vals: jax.Array) -> jax.Array:
    """32-lane-wide scatter-OR: ``u32[n]`` with ``out[idx[i]] |= vals[i]``.

    The word-level sibling of :func:`set_bits`'s problem — ``.at[].max``
    cannot OR two different uint32 patterns landing on one receiver — and
    the same fix lifted to lanes: scatter the transient BIT-PLANE rows
    (``bool[k, 32]``) with ``.at[].max`` (max ≡ OR per bool lane; duplicate
    receivers compose correctly), then repack. One scatter op serves all
    32 message lanes of a word. Out-of-range ``idx`` drops (mask invalid
    slots by pointing them at ``n``, exactly like :func:`set_bits`)."""
    planes = jnp.zeros((n, WORD), dtype=bool).at[idx].max(
        expand_lanes(vals), mode="drop")
    return collapse_lanes(planes)
