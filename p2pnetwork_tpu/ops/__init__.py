"""Aggregation primitives: segment/gather (segment.py), one-hot-matmul
blocked (blocked.py), and the fused Pallas TPU kernel (pallas_edge.py)."""

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.ops.segment import (frontier_messages, propagate_max,
                                        propagate_min_plus, propagate_or,
                                        propagate_sum)

__all__ = ["segment", "propagate_max", "propagate_min_plus",
           "propagate_or", "propagate_sum",
           "frontier_messages"]
