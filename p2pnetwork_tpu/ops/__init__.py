"""Aggregation primitives: segment/gather (segment.py), one-hot-matmul
blocked (blocked.py), the fused Pallas TPU kernel (pallas_edge.py), the
frontier-compacted sparse fast path (frontier.py), and bit-packed node
predicates (bitset.py)."""

from p2pnetwork_tpu.ops import bitset, frontier, segment
from p2pnetwork_tpu.ops.segment import (frontier_messages, propagate_max,
                                        propagate_min_plus, propagate_or,
                                        propagate_sum)

__all__ = ["segment", "bitset", "frontier", "propagate_max",
           "propagate_min_plus", "propagate_or", "propagate_sum",
           "frontier_messages"]
