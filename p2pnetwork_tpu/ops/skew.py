"""Two-level (skew-split) neighbor table — hub-proof gather aggregation.

The padded neighbor table ``[N, max_degree]`` is the fast aggregation
layout for quasi-regular graphs, but its gather cost is per padded SLOT
(~8 cycles/element on the TPU — BENCH.md "gather floor"), and the width is
set by the single largest in-degree: one Barabási–Albert hub at degree
~1400 widens every row, measured at 178× padding waste on 100K BA, where
the sorted-segment lowering wins 33×. Segment, though, pays its own
per-edge constant (~33 cycles measured) on EVERY edge — it is the right
floor for the hub's edges and the wrong one for the quasi-regular mass.

This module splits the difference structurally. Rows are **virtual**: a
node of in-degree ``d`` owns ``ceil(d / W)`` rows of a FIXED width ``W``
(the two-level representation VERDICT r4 names): a quasi-regular node is
one row, a hub is many. The aggregation is then

1. gather + reduce each virtual row — ``[R, W]`` slots at the gather
   floor, where ``R·W ≈ E · (small constant)`` by construction, whatever
   the degree distribution (the hub cannot widen anyone else's row);
2. combine virtual rows into their owners with a sorted segment
   reduction over ``R ≈ N`` elements — the segment constant paid per
   ROW, not per edge.

Cost model (the constants measured on-chip, BENCH.md): ``8·R·W + 33·R``
cycles vs segment's ``33·E`` — ``pick_width`` minimizes it over candidate
widths from the build-time degree histogram. On 1M BA (m=5, ~10M directed
edges) the model predicts ~2× over segment; on quasi-regular families the
plain table/hybrid layouts stay preferable and ``auto`` keeps choosing
them.

Rows inherit the receiver-sorted COO order, so ``owner`` is
non-decreasing (``indices_are_sorted=True`` holds) and each row covers a
contiguous edge range ``[start, start + W)`` — which is what lets runtime
edge failures re-mask the table exactly, device-side, with no rebuild
(sim/failures.py).

The reference has no analog: its per-peer neighbor state is a Python list
of socket threads, and "aggregation" is a sequential send loop
[ref: p2pnetwork/node.py:110-112].
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

#: Candidate virtual-row widths: sublane-multiple sizes from "hub chunk"
#: down to "half a vreg lane tile".
WIDTH_CANDIDATES = (8, 16, 32, 64, 128)

#: Measured per-slot gather cost and per-element sorted-segment cost, in
#: TPU cycles (BENCH.md "gather floor" + the BA segment measurement) —
#: only their RATIO matters to the width choice.
_GATHER_CYCLES_PER_SLOT = 8.0
_SEGMENT_CYCLES_PER_ELEM = 33.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SkewTable:
    """Virtual-row (two-level) incoming-neighbor table.

    ``src``/``mask`` are ``[R_pad, W]``: the sending node per slot and the
    validity mask. ``owner[r]`` is the receiving node whose in-edges row
    ``r`` holds (non-decreasing; padding rows own ``n_pad - 1`` with an
    all-False mask). ``start[r]`` is the row's first slot as an offset
    into the receiver-sorted COO edge arrays — the slot->edge map that
    makes exact runtime edge re-masking possible. ``weight`` is the
    aligned per-slot cost view on weighted graphs (None otherwise).
    """

    src: jax.Array  # i32[R_pad, W]
    mask: jax.Array  # bool[R_pad, W]
    owner: jax.Array  # i32[R_pad], non-decreasing
    start: jax.Array  # i32[R_pad]
    weight: Optional[jax.Array] = None  # f32[R_pad, W]

    @property
    def n_rows(self) -> int:
        return self.src.shape[0]

    @property
    def width(self) -> int:
        return self.src.shape[1]

    @property
    def n_slots(self) -> int:
        return self.src.shape[0] * self.src.shape[1]

    def edge_slots(self, e_pad: int) -> jax.Array:
        """``[R_pad, W]`` COO edge id per slot — THE slot->edge map (row
        ``r``'s slot ``s`` is edge ``start[r] + s``; rows inherit the
        receiver-sorted order). Clipped in-bounds for padding slots,
        whose masks are False. The single definition both the
        edge-liveness re-mask and the aligned-weight rebuild use — they
        must never disagree."""
        return jnp.minimum(
            self.start[:, None] + jnp.arange(self.width)[None, :], e_pad - 1
        )


def pick_width(in_degrees: np.ndarray,
               candidates=WIDTH_CANDIDATES) -> int:
    """Choose the virtual-row width minimizing the modeled round cost
    ``gather·slots(W) + segment·rows(W)`` over the build-time degree
    histogram. Small widths waste fewer slots on low-degree rows but pay
    the per-row combine on more rows; hubs are indifferent (their slot
    count is ~d either way)."""
    d = np.asarray(in_degrees, dtype=np.int64)
    d = d[d > 0]
    if d.size == 0:
        return candidates[0]
    best_w, best_cost = candidates[0], np.inf
    for w in candidates:
        rows = (d + w - 1) // w
        cost = (_GATHER_CYCLES_PER_SLOT * float(rows.sum()) * w  # graftlint: ignore[host-sync-in-loop] -- numpy-only cost model, no device values
                + _SEGMENT_CYCLES_PER_ELEM * float(rows.sum()))  # graftlint: ignore[host-sync-in-loop] -- numpy-only cost model
        if cost < best_cost:
            best_w, best_cost = w, cost
    return best_w


def build_skew_from_arrays(
    senders: np.ndarray,
    receivers: np.ndarray,
    n_pad: int,
    e_pad: int,
    width: int = 0,
    weights: Optional[np.ndarray] = None,
    row_pad_multiple: int = 8,
) -> SkewTable:
    """Build the table host-side from the receiver-sorted BUILD-time edge
    list (the unpadded prefix of the COO arrays — padding slots enter no
    row; runtime liveness is a re-mask, not a rebuild).

    ``width=0`` picks via :func:`pick_width`. ``e_pad`` seeds the padding
    rows' ``start`` with an in-bounds sentinel.
    """
    from p2pnetwork_tpu.sim.graph import _padded_row_fill

    senders = np.asarray(senders, dtype=np.int32)
    receivers = np.asarray(receivers, dtype=np.int32)
    e = senders.size
    counts = np.bincount(receivers, minlength=n_pad).astype(np.int64) \
        if e else np.zeros(n_pad, dtype=np.int64)
    if width <= 0:
        width = pick_width(counts)

    rows_per = (counts + width - 1) // width  # zero-degree nodes: no row
    r_total = int(rows_per.sum())
    r_pad = max(
        ((r_total + row_pad_multiple - 1) // row_pad_multiple)
        * row_pad_multiple,
        row_pad_multiple,
    )

    owner = np.full(r_pad, n_pad - 1, dtype=np.int32)
    start = np.full(r_pad, e_pad - 1, dtype=np.int32)
    src = np.zeros((r_pad, width), dtype=np.int32)
    mask = np.zeros((r_pad, width), dtype=bool)
    weight = None
    if weights is not None:
        weight = np.zeros((r_pad, width), dtype=np.float32)

    if r_total:
        node_ids = np.nonzero(rows_per)[0]
        node_starts = np.concatenate(
            ([0], np.cumsum(counts))).astype(np.int64)[:-1]
        own = np.repeat(node_ids, rows_per[node_ids]).astype(np.int32)
        # Slice index within each node's row group: 0..rows_per-1.
        grp = np.cumsum(rows_per[node_ids]) - rows_per[node_ids]
        j = np.arange(r_total, dtype=np.int64) - np.repeat(
            grp, rows_per[node_ids])
        row_start = node_starts[own] + j * width
        row_count = np.minimum(width, counts[own] - j * width)
        take, valid = _padded_row_fill(row_start, row_count, width)
        take_safe = np.minimum(take, max(e - 1, 0))
        pool = senders if e else np.zeros(1, dtype=np.int32)
        owner[:r_total] = own
        start[:r_total] = row_start.astype(np.int32)
        src[:r_total] = np.where(valid, pool[take_safe], 0)
        mask[:r_total] = valid
        if weights is not None:
            wpool = (np.asarray(weights, dtype=np.float32)
                     if e else np.zeros(1, dtype=np.float32))
            weight[:r_total] = np.where(valid, wpool[take_safe], 0.0)

    return SkewTable(
        src=jnp.asarray(src),
        mask=jnp.asarray(mask),
        owner=jnp.asarray(owner),
        start=jnp.asarray(start),
        weight=None if weight is None else jnp.asarray(weight),
    )


def build_skew(graph, width: int = 0) -> SkewTable:
    """Build from a :class:`~p2pnetwork_tpu.sim.graph.Graph` (pulls the
    edge arrays to host; prefer ``from_edges(skew_table=True)`` at
    construction for large graphs). Rows cover the BUILD-time edge
    prefix — the slot->edge map failures re-mask instead of rebuilding —
    and the graph's CURRENT ``edge_mask`` is applied immediately, so a
    table attached after failures does not resurrect dead edges (the
    mask covers dead endpoints too: node failures re-mask edge_mask)."""
    e = graph.n_edges
    w = (None if graph.edge_weight is None
         else np.asarray(graph.edge_weight)[:e])
    t = build_skew_from_arrays(
        np.asarray(graph.senders)[:e],
        np.asarray(graph.receivers)[:e],
        graph.n_nodes_padded,
        graph.n_edges_padded,
        width=width,
        weights=w,
    )
    return remask_edges(t, graph.edge_mask, graph.n_edges_padded)


# ------------------------------------------------------------- lowerings
#
# All four follow the same two-level shape: per-row gather + axis-1
# reduce, then a sorted segment combine over owners. Padding rows own
# n_pad-1 with all-False masks, so they contribute the operation's
# neutral; dead/ownerless nodes are re-masked by the caller's node_mask
# (propagate_* in ops/segment.py applies it).


def or_skew(t: SkewTable, signal: jax.Array, n_pad: int) -> jax.Array:
    vals = signal[t.src] & t.mask
    part = jnp.any(vals, axis=1).astype(jnp.int32)
    agg = jax.ops.segment_max(
        part, t.owner, num_segments=n_pad, indices_are_sorted=True
    )
    return agg > 0


def sum_skew(t: SkewTable, signal: jax.Array, n_pad: int) -> jax.Array:
    vals = signal[t.src] * t.mask.astype(signal.dtype)
    part = jnp.sum(vals, axis=1)
    return jax.ops.segment_sum(
        part, t.owner, num_segments=n_pad, indices_are_sorted=True
    )


def max_skew(t: SkewTable, signal: jax.Array, n_pad: int,
             neutral: jax.Array) -> jax.Array:
    vals = jnp.where(t.mask, signal[t.src], neutral)
    part = jnp.max(vals, axis=1)
    return jax.ops.segment_max(
        part, t.owner, num_segments=n_pad, indices_are_sorted=True
    )


def min_plus_skew(t: SkewTable, dist: jax.Array, n_pad: int) -> jax.Array:
    w = t.weight if t.weight is not None else 1.0
    vals = jnp.where(t.mask, dist[t.src] + w, jnp.inf)
    part = jnp.min(vals, axis=1)
    return jax.ops.segment_min(
        part, t.owner, num_segments=n_pad, indices_are_sorted=True
    )


# ------------------------------------------------------- liveness remask


def remask_nodes(t: Optional[SkewTable],
                 node_alive: jax.Array) -> Optional[SkewTable]:
    """Node-liveness re-mask (sim/failures.py contract): a slot survives
    iff its sender and its row's owner are both alive."""
    if t is None:
        return None
    mask = t.mask & node_alive[t.src] & node_alive[t.owner][:, None]
    return dataclasses.replace(t, mask=mask)


def remask_edges(t: Optional[SkewTable], edge_mask: jax.Array,
                 e_pad: int) -> Optional[SkewTable]:
    """Edge-liveness re-mask: row ``r``'s slot ``s`` is COO edge
    ``start[r] + s`` (rows inherit the receiver-sorted order), so the
    edge mask gathers straight into the table — exact, device-side."""
    if t is None:
        return None
    return dataclasses.replace(
        t, mask=t.mask & edge_mask[t.edge_slots(e_pad)])
