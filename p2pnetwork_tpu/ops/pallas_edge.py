"""Pallas TPU kernel for the edge-aggregation hot path.

Same blocked scheme as ops/blocked.py — segment reduction as one-hot
matmuls — but fused: the one-hot destination mask is generated *inside* the
kernel (an iota + compare in VMEM) and consumed immediately by the MXU, so
it never exists in HBM. The XLA einsum lowering materializes that mask at
``edges * 128 * 4`` bytes (gigabytes at BASELINE scale); fusing it away
makes the kernel's HBM traffic just the contributions and destinations —
this is the bandwidth win that justifies a kernel (SURVEY.md section 7
step 5).

Grid: ``(n_blocks / ROW_TILE, width_tiles)``. Each step loads a
``[ROW_TILE, TILE_W]`` strip of edge contributions + local destinations for
``ROW_TILE`` 128-node output blocks (the row batch keeps the sublane
dimension divisible by 8, a Mosaic block-shape requirement on real TPUs),
builds the ``[ROW_TILE, TILE_W, 128]`` one-hot in VMEM, and accumulates a
batched ``[ROW_TILE, 1, TILE_W] @ [ROW_TILE, TILE_W, 128]`` partial product
into the blocks' output rows (output revisiting across the width dimension).

Padded edge slots carry contribution 0, so no masking is needed in-kernel.
On CPU (tests) the kernel runs in interpreter mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from p2pnetwork_tpu.ops.blocked import BlockedEdges

#: Edge-strip width per grid step.
TILE_W = 512

#: Node blocks processed per grid step (sublane-aligned row batch).
ROW_TILE = 8


def _segsum_kernel(contrib_ref, dst_ref, out_ref, *, block: int, tile_w: int,
                   precision):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    contrib = contrib_ref[:]  # [ROW_TILE, TILE_W] f32
    dst = dst_ref[:]  # [ROW_TILE, TILE_W] i32
    rows = contrib.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (rows, tile_w, block), 2)
    onehot = (dst[:, :, None] == iota).astype(jnp.float32)
    partial = jax.lax.dot_general(
        contrib[:, None, :],  # [R, 1, W]
        onehot,  # [R, W, B]
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=precision,
    )  # [R, 1, B]
    out_ref[:] += partial[:, 0, :]


def _is_cpu() -> bool:
    return jax.default_backend() == "cpu"


def segment_sum_pallas_impl(contrib: jax.Array, local_dst: jax.Array,
                            block: int = 128, tile_w: int = TILE_W,
                            interpret: bool | None = None, exact: bool = True):
    """Blocked segment sum: ``out[n, b] = sum_w contrib[n, w] * (dst[n, w] == b)``.

    ``contrib`` f32[NB, W] (masked slots must be 0), ``local_dst`` i32[NB, W]
    with values in [0, block). Returns f32[NB, block].

    ``exact=True`` runs the MXU at full f32 precision (multi-pass); the
    default single-pass bf16 rounding loses ~2^-8 relative accuracy on
    arbitrary f32 inputs. For 0/1 contributions (the OR path) bf16 inputs
    are exact and the MXU accumulator is f32 either way, so ``exact=False``
    gives the bitwise-identical result at ~3x less MXU work.
    """
    nb, w = contrib.shape
    if block % 128 != 0:
        raise ValueError(f"block must be a multiple of 128 (lane width), got {block}")
    if w % tile_w != 0:
        pad = tile_w - w % tile_w
        contrib = jnp.pad(contrib, ((0, 0), (0, pad)))
        local_dst = jnp.pad(local_dst, ((0, 0), (0, pad)))
        w += pad
    nb_pad = nb
    if nb % ROW_TILE != 0:
        row_pad = ROW_TILE - nb % ROW_TILE
        contrib = jnp.pad(contrib, ((0, row_pad), (0, 0)))
        local_dst = jnp.pad(local_dst, ((0, row_pad), (0, 0)))
        nb_pad += row_pad
    if interpret is None:
        interpret = _is_cpu()
    precision = jax.lax.Precision.HIGHEST if exact else jax.lax.Precision.DEFAULT
    kernel = functools.partial(
        _segsum_kernel, block=block, tile_w=tile_w, precision=precision
    )
    out = pl.pallas_call(
        kernel,
        grid=(nb_pad // ROW_TILE, w // tile_w),
        in_specs=[
            pl.BlockSpec((ROW_TILE, tile_w), lambda i, j: (i, j)),
            pl.BlockSpec((ROW_TILE, tile_w), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((ROW_TILE, block), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb_pad, block), jnp.float32),
        interpret=interpret,
    )(contrib, local_dst)
    return out[:nb]


#: Jitted entry for eager callers. In-jit callers — notably the sharded
#: ring's bucket apply, which runs inside a shard_map body with
#: check_vma=False — use ``segment_sum_pallas_impl`` directly: a nested
#: jit inside a vma-typed shard_map trips a lowering-cache bug in current
#: JAX, which is also why those shard_maps disable vma checking.
segment_sum_pallas = jax.jit(
    segment_sum_pallas_impl,
    static_argnames=("block", "tile_w", "interpret", "exact"),
)


def propagate_sum_pallas(blocked: BlockedEdges, signal: jax.Array,
                         node_mask: jax.Array, tile_w: int = TILE_W,
                         exact: bool = True) -> jax.Array:
    """Per-node incoming sum via the fused kernel. signal f32[N_pad] -> f32[N_pad]."""
    contrib = signal.astype(jnp.float32)[blocked.src] * blocked.mask.astype(jnp.float32)
    out = segment_sum_pallas(
        contrib, blocked.local_dst, blocked.block, tile_w, exact=exact
    )
    out = out.reshape(-1)[: node_mask.shape[0]]
    return out * node_mask.astype(jnp.float32)


def propagate_or_pallas(blocked: BlockedEdges, signal: jax.Array,
                        node_mask: jax.Array, tile_w: int = TILE_W) -> jax.Array:
    """Per-node incoming OR via the fused kernel (0/1 contributions).

    0/1 values are exact in bf16 and the MXU accumulates in f32, so the
    single-pass MXU mode (``exact=False``) is bitwise-identical here.
    """
    out = propagate_sum_pallas(blocked, signal.astype(jnp.float32), node_mask,
                               tile_w, exact=False)
    return (out > 0) & node_mask
