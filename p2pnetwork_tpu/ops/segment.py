"""Edge-aggregation primitives — the hot ops of the simulation backend.

One flooding/gossip round in the reference is an O(peers) sequential Python
loop of socket sends per node [ref: p2pnetwork/node.py:110-112] plus a 10 ms
poll per connection [ref: nodeconnection.py:220]. Here the same round is one
batched aggregation over every edge of the population at once:

- ``propagate_or``  — per-receiver OR of a boolean node signal (flooding:
  "did any of my neighbors have the message?")
- ``propagate_sum`` — per-receiver sum of a float node signal (gossip / SIR:
  infection pressure, value accumulation)
- ``frontier_messages`` — how many point-to-point messages this round
  corresponds to (the sim-side ``message_count`` parity metric).

Two lowerings, chosen by what the graph carries:

- ``segment``: COO edges sorted by receiver -> ``jax.ops.segment_*`` with
  ``indices_are_sorted=True``. General, handles any degree distribution.
- ``gather``: padded neighbor table ``[N, max_degree]`` -> row-wise gather +
  masked reduce along the degree axis. Dense, regular memory traffic that
  maps well onto TPU vector units for quasi-regular graphs; this shape is
  also what the Pallas kernel implements (ops/pallas_edge.py).

A third family prices the round by the FRONTIER instead of the graph:
``method="frontier"`` (ops/frontier.py) compacts the active nodes inside
jit and gathers only their out-edge rows through the source-CSR view,
falling back to the dense path via ``lax.cond`` when the active count
exceeds the crossover budget — the fast path for the sparse first/last
rounds of a flood. Requires ``from_edges(source_csr=True)``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph


#: ``auto`` prefers the neighbor-table gather only while the table's
#: padding waste (slots / true edges) stays under this bound. The padded
#: gather touches rows x width slots at the TPU's flat ~8 cycles/element
#: gather floor (BENCH.md), so a degree-skewed graph poisons it: one hub
#: widens EVERY row. Measured on chip (single-source flood to 99%):
#: WS 1M @ 1.7x waste — gather 1.53 s vs segment 1.87 s (gather wins);
#: ER 100K @ 2.5x — 0.142 vs 0.163 s (gather wins); BA 100K @ 178x —
#: 3.97 vs 0.12 s (gather loses 33x). Break-even sits near 3-4x.
_GATHER_WASTE_BOUND = 4.0


def _gather_ok(graph: Graph) -> bool:
    if graph.neighbors is None or not graph.neighbors_complete:
        return False
    slots = graph.neighbors.shape[0] * graph.neighbors.shape[1]
    return slots <= _GATHER_WASTE_BOUND * max(graph.n_edges, 1)


def _auto_method(graph: Graph) -> str:
    """``auto``'s routing: the plain table while its waste is bounded; the
    two-level skew table when the graph carries one (its waste is bounded
    by construction — attaching it signals a degree-skewed family, where
    it beats segment's per-edge constant); segment otherwise."""
    if _gather_ok(graph):
        return "gather"
    if graph.skew is not None:
        return "skew"
    return "segment"


def _require_skew(graph: Graph) -> None:
    if graph.skew is None:
        raise ValueError(
            "method='skew' requires the two-level neighbor table — build "
            "with from_edges(skew_table=True) or graph.with_skew_table()"
        )


def _require_complete_table(graph: Graph) -> None:
    if graph.neighbors is None:
        raise ValueError("method='gather' requires a graph with a neighbor table")
    if not graph.neighbors_complete:
        raise ValueError(
            "method='gather' on a width-capped neighbor table "
            "(from_edges(max_degree=...)) would silently drop edges; use "
            "method='segment' for exact aggregation on this graph"
        )


def _dynamic_or(graph: Graph, signal: jax.Array) -> jax.Array:
    """OR-aggregate the dynamic edge region (sim/topology.py), if any."""
    contrib = (signal[graph.dyn_senders] & graph.dyn_mask).astype(jnp.int32)
    agg = jax.ops.segment_max(
        contrib, graph.dyn_receivers, num_segments=graph.n_nodes_padded
    )
    return (agg > 0) & graph.node_mask


def _dynamic_sum(graph: Graph, signal: jax.Array) -> jax.Array:
    """Sum-aggregate the dynamic edge region (sim/topology.py), if any."""
    contrib = signal[graph.dyn_senders] * graph.dyn_mask.astype(signal.dtype)
    agg = jax.ops.segment_sum(
        contrib, graph.dyn_receivers, num_segments=graph.n_nodes_padded
    )
    return agg * graph.node_mask.astype(signal.dtype)


def propagate_or(graph: Graph, signal: jax.Array, method: str = "auto", *,
                 frontier_crossover=None) -> jax.Array:
    """Per-node OR over incoming neighbors: ``out[v] = any(signal[u], u->v)``.

    ``signal`` is bool[N_pad]; masked (padding) edges and nodes contribute
    nothing. ``method`` is ``"segment"``, ``"gather"`` or ``"auto"``
    (gather when the graph carries a complete neighbor table whose
    padding waste stays under ``_GATHER_WASTE_BOUND`` — degree-skewed
    tables route to segment). Dynamic edges (sim/topology.py) are folded
    in for every method. ``frontier_crossover`` overrides the
    ``method="frontier"`` sparse budget (ops/frontier.py ``budget``:
    float = fraction of padded nodes, int = node budget) — the supported
    "apply" step for a crossover re-fit from measured occupancy.
    """
    if graph.dyn_senders is not None:
        static = dataclasses.replace(graph, dyn_senders=None,
                                     dyn_receivers=None, dyn_mask=None)
        return (propagate_or(static, signal, method,
                             frontier_crossover=frontier_crossover)
                | _dynamic_or(graph, signal))
    if method == "frontier":
        from p2pnetwork_tpu.ops import frontier as FR

        return FR.propagate_or_frontier(
            graph, signal, lambda sig: propagate_or(graph, sig, "auto"),
            crossover=frontier_crossover)
    if method == "auto":
        method = _auto_method(graph)
    if method == "gather":
        _require_complete_table(graph)
        vals = signal[graph.neighbors] & graph.neighbor_mask
        return jnp.any(vals, axis=1) & graph.node_mask
    if method == "skew":
        from p2pnetwork_tpu.ops import skew as SK

        _require_skew(graph)
        return SK.or_skew(graph.skew, signal,
                          graph.n_nodes_padded) & graph.node_mask
    if method in ("blocked", "pallas"):
        from p2pnetwork_tpu.ops import blocked as B
        from p2pnetwork_tpu.ops import pallas_edge as PK

        if graph.blocked is None:
            raise ValueError(f"method={method!r} requires graph.with_blocked()")
        fn = B.propagate_or_blocked if method == "blocked" else PK.propagate_or_pallas
        return fn(graph.blocked, signal, graph.node_mask)
    if method in ("hybrid", "hybrid-blocked"):
        from p2pnetwork_tpu.ops import diag as D

        if graph.hybrid is None:
            raise ValueError(f"method={method!r} requires graph.with_hybrid()")
        kernel = "pallas" if method == "hybrid" else "blocked"
        return D.propagate_or_hybrid(graph.hybrid, signal, graph.node_mask,
                                     kernel=kernel)
    contrib = (signal[graph.senders] & graph.edge_mask).astype(jnp.int32)
    agg = jax.ops.segment_max(
        contrib,
        graph.receivers,
        num_segments=graph.n_nodes_padded,
        indices_are_sorted=True,
    )
    return (agg > 0) & graph.node_mask


def _dynamic_or_lanes(graph: Graph, word: jax.Array) -> jax.Array:
    """Lane-packed OR over the dynamic edge region (sim/topology.py), one
    word (``u32[N_pad]``) at a time: bit-plane expand the (small) dynamic
    region's contributions and segment-max them per lane."""
    from p2pnetwork_tpu.ops import bitset

    contrib = jnp.where(graph.dyn_mask, word[graph.dyn_senders],
                        jnp.uint32(0))
    planes = jax.ops.segment_max(
        bitset.expand_lanes(contrib).astype(jnp.uint8),
        graph.dyn_receivers, num_segments=graph.n_nodes_padded,
    )
    return bitset.collapse_lanes(planes > 0) & jnp.where(
        graph.node_mask, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))


def propagate_or_lanes(graph: Graph, lanes: jax.Array,
                       method: str = "auto", *,
                       frontier_crossover=None) -> jax.Array:
    """Lane-packed neighbor-OR: 32·W concurrent boolean signals advanced
    by one round in one program — ``lanes`` is ``u32[W, N_pad]`` where bit
    L of word w at node v is message ``32w+L``'s signal (ops/bitset.py
    lane algebra), and ``out[w, v] = OR(lanes[w, u], u->v)`` word-level.

    This is :func:`propagate_or` batched across messages instead of
    called B times: the graph traffic (neighbor-row gathers, edge
    contributions) is priced PER WORD, so 32 messages ride each gathered
    element. Methods:

    - ``"gather"``: one u32 gather of each node's neighbor row serves all
      32 lanes of a word; the degree-axis reduce is a word-level bitwise
      OR. Same complete-table requirement as :func:`propagate_or`.
    - ``"segment"``: per-edge contributions bit-plane-expanded (uint8
      ``[E_pad, 32]``) through the sorted-receiver segment-max — the
      any-graph fallback (no table needed).
    - ``"frontier"``: union-frontier compaction shared across all words,
      32-message-wide scatter-OR (ops/frontier.py
      ``propagate_or_lanes_frontier``); dense fallback is ``auto``.
    - ``"auto"``: gather under the same waste bound as the scalar path,
      else segment (the skew/MXU lowerings have no word-level form —
      degree-skewed tables route to segment).

    Dynamic edges fold in for every method. Padding lanes are harmless:
    an unused lane's bits are never seeded, and OR propagates nothing
    from nothing. ``frontier_crossover`` as in :func:`propagate_or`.
    """
    if graph.dyn_senders is not None:
        static = dataclasses.replace(graph, dyn_senders=None,
                                     dyn_receivers=None, dyn_mask=None)
        return (propagate_or_lanes(static, lanes, method,
                                   frontier_crossover=frontier_crossover)
                | jax.vmap(lambda w: _dynamic_or_lanes(graph, w))(lanes))
    if method == "frontier":
        from p2pnetwork_tpu.ops import frontier as FR

        return FR.propagate_or_lanes_frontier(
            graph, lanes, lambda ln: propagate_or_lanes(graph, ln, "auto"),
            crossover=frontier_crossover)
    if method == "auto":
        method = "gather" if _gather_ok(graph) else "segment"
    node_lanes = jnp.where(graph.node_mask, jnp.uint32(0xFFFFFFFF),
                           jnp.uint32(0))
    if method == "gather":
        _require_complete_table(graph)

        def word_gather(wl):
            vals = jnp.where(graph.neighbor_mask, wl[graph.neighbors],
                             jnp.uint32(0))
            return jax.lax.reduce(vals, jnp.uint32(0),
                                  jax.lax.bitwise_or, (1,))

        return jax.vmap(word_gather)(lanes) & node_lanes
    if method == "segment":
        from p2pnetwork_tpu.ops import bitset

        def word_segment(wl):
            contrib = jnp.where(graph.edge_mask, wl[graph.senders],
                                jnp.uint32(0))
            planes = jax.ops.segment_max(
                bitset.expand_lanes(contrib).astype(jnp.uint8),
                graph.receivers, num_segments=graph.n_nodes_padded,
                indices_are_sorted=True,
            )
            return bitset.collapse_lanes(planes > 0)

        return jax.vmap(word_segment)(lanes) & node_lanes
    raise ValueError(
        f"propagate_or_lanes supports method 'segment', 'gather', "
        f"'frontier' or 'auto', got {method!r} (the skew/MXU lowerings "
        f"have no word-level form)"
    )


def propagate_sum(graph: Graph, signal: jax.Array, method: str = "auto",
                  exact: bool = True) -> jax.Array:
    """Per-node sum over incoming neighbors: ``out[v] = sum(signal[u], u->v)``.
    Dynamic edges (sim/topology.py) are folded in for every method.

    ``exact=False`` lets the MXU-kernel methods run single-pass (inputs
    rounded to bf16). Safe whenever the signal's values are exactly
    representable in bf16 — 0/1 indicators (SIR infection pressure) and
    small integers: products stay exact and the accumulator is f32 either
    way, so the result is bit-identical at ~3x less MXU work.
    """
    if graph.dyn_senders is not None:
        static = dataclasses.replace(graph, dyn_senders=None,
                                     dyn_receivers=None, dyn_mask=None)
        return (propagate_sum(static, signal, method, exact)
                + _dynamic_sum(graph, signal))
    if method == "auto":
        method = _auto_method(graph)
    if method == "gather":
        _require_complete_table(graph)
        vals = signal[graph.neighbors] * graph.neighbor_mask.astype(signal.dtype)
        return jnp.sum(vals, axis=1) * graph.node_mask.astype(signal.dtype)
    if method == "skew":
        from p2pnetwork_tpu.ops import skew as SK

        _require_skew(graph)
        agg = SK.sum_skew(graph.skew, signal, graph.n_nodes_padded)
        return agg * graph.node_mask.astype(signal.dtype)
    if method in ("blocked", "pallas"):
        from p2pnetwork_tpu.ops import blocked as B
        from p2pnetwork_tpu.ops import pallas_edge as PK

        if graph.blocked is None:
            raise ValueError(f"method={method!r} requires graph.with_blocked()")
        if method == "blocked":
            return B.propagate_sum_blocked(graph.blocked, signal, graph.node_mask)
        return PK.propagate_sum_pallas(graph.blocked, signal, graph.node_mask,
                                       exact=exact)
    if method in ("hybrid", "hybrid-blocked"):
        from p2pnetwork_tpu.ops import diag as D

        if graph.hybrid is None:
            raise ValueError(f"method={method!r} requires graph.with_hybrid()")
        kernel = "pallas" if method == "hybrid" else "blocked"
        return D.propagate_sum_hybrid(graph.hybrid, signal, graph.node_mask,
                                      exact=exact, kernel=kernel)
    contrib = signal[graph.senders] * graph.edge_mask.astype(signal.dtype)
    agg = jax.ops.segment_sum(
        contrib,
        graph.receivers,
        num_segments=graph.n_nodes_padded,
        indices_are_sorted=True,
    )
    return agg * graph.node_mask.astype(signal.dtype)


def neutral_min(dtype) -> jax.Array:
    """The max-aggregation identity for ``dtype`` (-inf / int min)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    if jnp.issubdtype(dtype, jnp.bool_):
        raise ValueError(
            "max-aggregation over bool signals is just OR — use "
            "propagate_or / sharded.propagate(op='or') instead"
        )
    return jnp.array(jnp.iinfo(dtype).min, dtype)


def _dynamic_max(graph: Graph, signal: jax.Array) -> jax.Array:
    """Max-aggregate the dynamic edge region (sim/topology.py), if any."""
    neutral = neutral_min(signal.dtype)
    contrib = jnp.where(graph.dyn_mask, signal[graph.dyn_senders], neutral)
    return jax.ops.segment_max(
        contrib, graph.dyn_receivers, num_segments=graph.n_nodes_padded
    )


def propagate_max(graph: Graph, signal: jax.Array,
                  method: str = "auto", *,
                  frontier_crossover=None) -> jax.Array:
    """Per-node max over incoming neighbors: ``out[v] = max(signal[u], u->v)``.

    Nodes with no (live) incoming edges get the dtype's max-identity
    (-inf / int min); dead nodes likewise — callers typically fold the
    result with their own value (``jnp.maximum(value, incoming)``), which
    makes both neutral. Methods: ``"segment"`` or ``"gather"`` (``"auto"``
    picks gather when a complete, not-too-padded neighbor table exists —
    see ``_GATHER_WASTE_BOUND``). The blocked /
    pallas / hybrid lowerings do not apply — they ride one-hot MXU
    matmuls, which compute sums, not maxima.
    """
    neutral = neutral_min(signal.dtype)
    if graph.dyn_senders is not None:
        static = dataclasses.replace(graph, dyn_senders=None,
                                     dyn_receivers=None, dyn_mask=None)
        return jnp.maximum(
            propagate_max(static, signal, method,
                          frontier_crossover=frontier_crossover),
            _dynamic_max(graph, signal))
    if method == "frontier":
        from p2pnetwork_tpu.ops import frontier as FR

        return FR.propagate_max_frontier(
            graph, signal, neutral,
            lambda sig: propagate_max(graph, sig, "auto"),
            crossover=frontier_crossover)
    if method == "auto":
        method = _auto_method(graph)
    if method == "gather":
        _require_complete_table(graph)
        vals = jnp.where(graph.neighbor_mask, signal[graph.neighbors],
                         neutral)
        agg = jnp.max(vals, axis=1)
    elif method == "skew":
        from p2pnetwork_tpu.ops import skew as SK

        _require_skew(graph)
        agg = SK.max_skew(graph.skew, signal, graph.n_nodes_padded, neutral)
    elif method == "segment":
        contrib = jnp.where(graph.edge_mask, signal[graph.senders], neutral)
        agg = jax.ops.segment_max(
            contrib,
            graph.receivers,
            num_segments=graph.n_nodes_padded,
            indices_are_sorted=True,
        )
    else:
        raise ValueError(
            f"propagate_max supports method 'segment', 'gather', 'skew' or "
            f"'frontier', got {method!r} (max does not ride the "
            f"one-hot-matmul lowerings)"
        )
    return jnp.where(graph.node_mask, agg, neutral)


#: Cost of a dynamic runtime link (sim/topology.py connect) in weighted
#: propagation — the dynamic region has no weight channel, so new links
#: enter at unit cost until topology.consolidate folds them in (where
#: they keep that cost as a static weight).
DYNAMIC_LINK_COST = 1.0


def _dynamic_min_plus(graph: Graph, dist: jax.Array) -> jax.Array:
    """Min-plus over the dynamic edge region (unit link cost)."""
    contrib = jnp.where(graph.dyn_mask,
                        dist[graph.dyn_senders] + DYNAMIC_LINK_COST,
                        jnp.inf)
    return jax.ops.segment_min(
        contrib, graph.dyn_receivers, num_segments=graph.n_nodes_padded
    )


def propagate_min_plus(graph: Graph, dist: jax.Array,
                       method: str = "auto", *,
                       frontier_crossover=None) -> jax.Array:
    """Per-node min-plus relaxation: ``out[v] = min(dist[u] + w(u, v))``
    over live incoming edges — one Bellman-Ford round over the whole
    population, the tropical-semiring sibling of :func:`propagate_max`.

    Weights come from ``graph.edge_weight`` (``from_edges(weights=...)``
    / ``Graph.with_weights``); an unweighted graph costs 1 per hop, so
    the fixpoint is BFS hop distance. Nodes with no live in-edge — and
    dead nodes — get ``+inf``; callers fold with their own value
    (``jnp.minimum``), which makes that neutral. ``dist`` is ``f32``.
    Methods as in propagate_max: ``"segment"`` / ``"gather"`` (gather
    needs the aligned ``neighbor_weight`` view on weighted graphs; auto
    falls back to segment when it is absent).
    """
    if graph.dyn_senders is not None:
        static = dataclasses.replace(graph, dyn_senders=None,
                                     dyn_receivers=None, dyn_mask=None)
        return jnp.minimum(
            propagate_min_plus(static, dist, method,
                               frontier_crossover=frontier_crossover),
            _dynamic_min_plus(graph, dist))
    if method == "frontier":
        from p2pnetwork_tpu.ops import frontier as FR

        return FR.propagate_min_plus_frontier(
            graph, dist, lambda d: propagate_min_plus(graph, d, "auto"),
            crossover=frontier_crossover)
    weighted = graph.edge_weight is not None
    if method == "auto":
        method = _auto_method(graph)
        if method == "gather" and weighted and graph.neighbor_weight is None:
            method = "segment"
        if method == "skew" and weighted and graph.skew.weight is None:
            method = "segment"
    if method == "gather":
        _require_complete_table(graph)
        if weighted and graph.neighbor_weight is None:
            raise ValueError(
                "method='gather' on a weighted graph needs the aligned "
                "neighbor_weight view — build with from_edges(weights=...) "
                "or Graph.with_weights, or use method='segment'"
            )
        w = graph.neighbor_weight if weighted else 1.0
        vals = jnp.where(graph.neighbor_mask, dist[graph.neighbors] + w,
                         jnp.inf)
        agg = jnp.min(vals, axis=1)
    elif method == "skew":
        from p2pnetwork_tpu.ops import skew as SK

        _require_skew(graph)
        if weighted and graph.skew.weight is None:
            raise ValueError(
                "method='skew' on a weighted graph needs the aligned "
                "weight view — build via from_edges(weights=..., "
                "skew_table=True) or Graph.with_weights, or use "
                "method='segment'"
            )
        agg = SK.min_plus_skew(graph.skew, dist, graph.n_nodes_padded)
    elif method == "segment":
        w = graph.edge_weight if weighted else 1.0
        contrib = jnp.where(graph.edge_mask, dist[graph.senders] + w,
                            jnp.inf)
        agg = jax.ops.segment_min(
            contrib,
            graph.receivers,
            num_segments=graph.n_nodes_padded,
            indices_are_sorted=True,
        )
    else:
        raise ValueError(
            f"propagate_min_plus supports method 'segment', 'gather', "
            f"'skew' or 'frontier', got {method!r} (min does not ride the "
            f"one-hot-matmul lowerings)"
        )
    return jnp.where(graph.node_mask, agg, jnp.inf)


def frontier_messages(graph: Graph, frontier: jax.Array) -> jax.Array:
    """Number of point-to-point sends this round: every node holding the
    frontier flag sends to each of its outgoing edges — the batched
    equivalent of the reference's per-edge ``send_to_nodes`` loop and its
    ``message_count_send`` counter [ref: node.py:110-116]."""
    return jnp.sum(jnp.where(frontier, graph.out_degree, 0))
