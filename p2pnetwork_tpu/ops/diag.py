"""Hybrid diagonal + blocked edge aggregation — the gather-free fast path.

XLA's TPU gather costs ~8 cycles per element regardless of source-array
size or index order (measured: 11M-element gathers take ~90 ms whether the
source is 4 KB or 4 MB, sorted or random) — it is the entire cost of a
propagation round at BASELINE scale. This module removes the gather for the
structured part of the graph.

Most peer topologies that arise from ring/lattice construction (the
Watts–Strogatz small-world benchmark family, rings, k-regular lattices)
concentrate their edges on a few **circular diagonals**: edge sets of the
form ``{(v + off) mod n -> v : mask[v]}``. Aggregating one diagonal is a
circular shift plus an elementwise mask — pure VPU traffic, no gather, no
matmul, and XLA fuses all diagonals into one pass over the node arrays:

    out[v] |= signal[(v + off) mod n] & mask[v]        (flood OR)
    out[v] += signal[(v + off) mod n] * mask[v]        (gossip/SIR sum)

Edges off the kept diagonals (e.g. the rewired ~p fraction of a WS graph)
fall back to the blocked one-hot-matmul representation (ops/blocked.py /
ops/pallas_edge.py), so the expensive per-edge machinery only pays for the
unstructured remainder. Graphs with no diagonal structure (Erdős–Rényi,
Barabási–Albert) degrade gracefully: every edge lands in the remainder and
the hybrid path equals the blocked path.

The reference has no analog — its "aggregation" is one Python ``send`` per
edge per 10 ms poll tick [ref: p2pnetwork/node.py:110-112,
nodeconnection.py:220]; diagonal extraction is a TPU-side representation
choice, chosen because shifts are free on the VPU and gathers are not.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.ops.blocked import BlockedEdges, build_blocked_from_arrays


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HybridEdges:
    """Graph edges split into circular diagonals + unstructured remainder.

    ``masks[d, v]`` is True iff the edge ``(v + offsets[d]) mod n -> v``
    exists. ``remainder`` holds every other edge in blocked form (None when
    the diagonals cover the whole graph).
    """

    masks: jax.Array  # bool[D, n] (D may be 0)
    remainder: Optional[BlockedEdges]
    offsets: Tuple[int, ...] = dataclasses.field(metadata=dict(static=True))
    n: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_diag_edges(self) -> int:
        return int(self.masks.sum()) if len(self.offsets) else 0


def build_hybrid(
    graph,
    block: int = 512,
    max_diags: int = 64,
    min_count: Optional[int] = None,
) -> HybridEdges:
    """Extract the dominant circular diagonals of ``graph`` (host-side).

    An offset is kept when it carries at least ``min_count`` edges (default
    ``max(n // 256, 128)`` — roughly where one fused VPU pass over the node
    array beats per-edge gather cost) and at most ``max_diags`` offsets are
    kept (compile-time unroll bound).
    """
    emask = np.asarray(graph.edge_mask)
    return build_hybrid_from_arrays(
        np.asarray(graph.senders)[emask],
        np.asarray(graph.receivers)[emask],
        graph.n_nodes,
        graph.n_nodes_padded,
        block=block,
        max_diags=max_diags,
        min_count=min_count,
    )


def select_diagonals(
    senders: np.ndarray,
    receivers: np.ndarray,
    n: int,
    max_diags: int = 64,
    min_count: Optional[int] = None,
):
    """Pick the dominant circular offsets of an edge list (host-side).

    Returns ``(kept_offsets, per_offset_sel, diag_sel)``: the chosen
    offsets (by descending edge count), for each one the indices of its
    covered edges — deduplicated to ONE edge per receiver, so sums count
    every edge instance exactly once — and the overall covered bitmap.
    Shared by the single-chip hybrid build and the sharded ring's
    decomposition (parallel/sharded.py), so selection tuning cannot
    silently diverge the two paths. Edges touching padded ids (``>= n``,
    possible when folded-in dynamic links involve spare nodes) are never
    candidates — their offsets-mod-n would alias real diagonals.
    """
    if min_count is None:
        min_count = max(n // 256, 128)
    diag_sel = np.zeros(senders.shape[0], dtype=bool)
    senders = np.asarray(senders)
    receivers = np.asarray(receivers)
    real = np.flatnonzero((senders < n) & (receivers < n))
    kept: list = []
    per_sel: list = []
    if real.size:
        # (s - r) mod n without the modulo: ids are < n so the difference
        # is in (-n, n) and one conditional add folds it into [0, n). The
        # per-element int64 division of `% n` was a measured hotspot of
        # graph build at BASELINE scale.
        d = senders[real].astype(np.int32) - receivers[real].astype(np.int32)
        off = np.where(d < 0, d + np.int32(n), d)
        counts = np.bincount(off)
        # Filter (self-loops, below-threshold) BEFORE truncating to
        # max_diags — a frequent self-loop offset ranking in the top
        # max_diags must not displace a qualifying real diagonal into the
        # per-edge remainder. Vectorized: `counts` has up to n entries.
        ok = counts >= min_count
        ok[0] = False
        cand = np.flatnonzero(ok)
        kept = [int(o) for o in cand[np.argsort(counts[cand])[::-1]][:max_diags]]
        # One sort pass gives every diagonal's edge set as a contiguous
        # slice (instead of a full O(E) scan per kept offset), through the
        # native radix kernel: on low-cardinality offset distributions
        # (WS lattices) it matches numpy's comparison sort, and on
        # high-entropy ones (heavily rewired / scale-free graphs) it is
        # ~5x faster at 20M edges (measured; numpy fallback built in).
        from p2pnetwork_tpu import native

        sorted_off, by_off = native.sort_pairs(
            off, np.arange(off.shape[0], dtype=np.int32)
        )
        lo = np.searchsorted(sorted_off, kept)
        hi = np.searchsorted(sorted_off, kept, side="right")
        # Both sorters are STABLE (native LSD radix; numpy fallback uses
        # kind="stable"), so when the input edges arrive receiver-sorted —
        # the documented precondition of both call sites — each offset's
        # slice keeps its receivers non-decreasing and first-per-receiver
        # is one neighbor compare instead of an np.unique sort per offset.
        rsorted = bool(receivers.size == 0 or
                       (receivers[1:] >= receivers[:-1]).all())
        for d, o in enumerate(kept):
            sel = real[by_off[lo[d]:hi[d]]]
            # A mask slot holds ONE edge; duplicate (offset, receiver)
            # pairs beyond the first stay in the remainder.
            rs = receivers[sel]
            if rsorted:
                first = np.empty(rs.shape[0], dtype=bool)
                if rs.shape[0]:
                    first[0] = True
                    np.not_equal(rs[1:], rs[:-1], out=first[1:])
                sel = sel[first]
            else:
                _, first = np.unique(rs, return_index=True)
                sel = sel[first]
            per_sel.append(sel)
            diag_sel[sel] = True
    return kept, per_sel, diag_sel


def build_hybrid_from_arrays(
    senders: np.ndarray,
    receivers: np.ndarray,
    n: int,
    n_pad: int,
    *,
    block: int = 512,
    max_diags: int = 64,
    min_count: Optional[int] = None,
) -> HybridEdges:
    """:func:`build_hybrid` on host edge arrays (``receivers`` sorted
    non-decreasing, active edges only) — lets graph construction build the
    representation before anything is transferred to device."""
    senders = np.asarray(senders)
    receivers = np.asarray(receivers)

    kept, per_sel, diag_sel = select_diagonals(
        senders, receivers, n, max_diags, min_count
    )
    offsets: Tuple[int, ...] = ()
    masks = np.zeros((0, n), dtype=bool)
    if kept:
        offsets = tuple(kept)
        masks = np.zeros((len(kept), n), dtype=bool)
        for d, sel in enumerate(per_sel):
            masks[d, receivers[sel]] = True

    rem_s = senders[~diag_sel].astype(np.int32, copy=False)
    rem_r = receivers[~diag_sel].astype(np.int32, copy=False)
    remainder = None
    if rem_s.size:
        # The remainder inherits receiver-sortedness from the graph's edges.
        remainder = build_blocked_from_arrays(rem_s, rem_r, n_pad, block)

    return HybridEdges(
        masks=jnp.asarray(masks),
        remainder=remainder,
        offsets=offsets,
        n=n,
    )


def _diag_or(hybrid: HybridEdges, core: jax.Array) -> jax.Array:
    """OR-aggregate the diagonal edges. ``core`` is bool[n] (unpadded)."""
    acc = jnp.zeros(hybrid.n, dtype=bool)
    for d, off in enumerate(hybrid.offsets):
        acc = acc | (jnp.roll(core, -off) & hybrid.masks[d])
    return acc


def _diag_sum(hybrid: HybridEdges, core: jax.Array) -> jax.Array:
    """Sum-aggregate the diagonal edges. ``core`` is f32[n] (unpadded)."""
    acc = jnp.zeros(hybrid.n, dtype=core.dtype)
    for d, off in enumerate(hybrid.offsets):
        acc = acc + jnp.roll(core, -off) * hybrid.masks[d].astype(core.dtype)
    return acc


def propagate_or_hybrid(
    hybrid: HybridEdges, signal: jax.Array, node_mask: jax.Array,
    kernel: str = "pallas",
) -> jax.Array:
    """Per-node OR over incoming edges: diagonals by shift, rest by kernel.

    ``kernel="pallas"`` (default) runs the remainder through the fused
    Pallas bucket kernel — the single-chip fast path. ``kernel="blocked"``
    uses the pure-jnp one-hot einsum (ops/blocked.py) instead: same
    result, but every op is partitionable, so the GSPMD auto path
    (parallel/auto.py) can shard it — a pallas_call is an opaque custom
    call the partitioner would have to replicate."""
    n_pad = node_mask.shape[0]
    out = jnp.pad(_diag_or(hybrid, signal[: hybrid.n]), (0, n_pad - hybrid.n))
    if hybrid.remainder is not None:
        if kernel == "pallas":
            from p2pnetwork_tpu.ops import pallas_edge as PK

            rem = PK.propagate_or_pallas(hybrid.remainder, signal, node_mask)
        else:
            from p2pnetwork_tpu.ops import blocked as B

            rem = B.propagate_or_blocked(hybrid.remainder, signal, node_mask)
        out = out | rem
    return out & node_mask


def propagate_sum_hybrid(
    hybrid: HybridEdges, signal: jax.Array, node_mask: jax.Array,
    exact: bool = True, kernel: str = "pallas",
) -> jax.Array:
    """Per-node sum over incoming edges: diagonals by shift, rest by kernel.
    ``exact=False``: single-pass MXU for the remainder (see ops/segment.py).
    ``kernel`` as in :func:`propagate_or_hybrid` (the blocked einsum is
    always exact)."""
    n_pad = node_mask.shape[0]
    out = jnp.pad(_diag_sum(hybrid, signal[: hybrid.n]), (0, n_pad - hybrid.n))
    if hybrid.remainder is not None:
        if kernel == "pallas":
            from p2pnetwork_tpu.ops import pallas_edge as PK

            rem = PK.propagate_sum_pallas(hybrid.remainder, signal,
                                          node_mask, exact=exact)
        else:
            from p2pnetwork_tpu.ops import blocked as B

            rem = B.propagate_sum_blocked(hybrid.remainder, signal,
                                          node_mask)
        out = out + rem
    return out * node_mask.astype(out.dtype)
