"""graftscope trace plane: trace ids, spans with parent links, exporters.

The metrics registry answers "how much/how many"; this module answers
"WHEN, inside WHAT": a :class:`Tracer` collects spans (named intervals
with parent links forming one tree per trace) and point events (zero-
duration spans) from the instrumented seams — the engine's batched runs
(``batch_run`` spans with per-lane ``lane_admit`` / ``lane_resume`` /
``lane_complete`` / ``lane_freeze`` events), the message batch's
control plane (``lane_submit`` on :meth:`BatchFlood.admit`,
``lane_retire`` on :meth:`BatchFlood.retire`), and the supervise
plane's chunk boundaries (``supervised_run`` / ``chunk`` spans,
``checkpoint`` / ``resume`` events).

Two exports, one span tree:

- :meth:`Tracer.to_chrome` — Chrome/Perfetto trace-event JSON (the
  ``traceEvents`` array of ``ph: "X"`` complete events; load it at
  https://ui.perfetto.dev or chrome://tracing). Span/parent ids ride in
  ``args`` so tooling — and the schema tests — can rebuild the tree.
- :meth:`Tracer.to_records` — the shared telemetry JSONL schema
  (telemetry/export.py): ``type: "event"`` records that interleave with
  metric samples and EventLog lines in one file.

Installation is process-wide and OFF by default: every instrumentation
site goes through :func:`emit` / :func:`span`, which cost one
None-check when no tracer is installed. Thread-safe by construction —
span storage is lock-guarded, and the "current span" context is
thread-local, so a watchdog thread's events nest under ITS stack, not
the run thread's. Stdlib-only, like the rest of the telemetry plane.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import IO, List, Optional, Union

from p2pnetwork_tpu import concurrency

__all__ = ["Span", "Tracer", "install_tracer", "uninstall_tracer",
           "current_tracer", "emit", "span"]


class Span:
    """One span: a named interval in a trace tree. ``t1 is None`` while
    still open. ``args`` are the caller's structured attributes (lane
    ids, round counts, paths)."""

    __slots__ = ("span_id", "trace_id", "parent_id", "name", "t0", "t1",
                 "tid", "args")

    def __init__(self, span_id: int, trace_id: str,
                 parent_id: Optional[int], name: str, t0: float,
                 tid: int, args: dict):
        self.span_id = span_id
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = t0
        self.t1: Optional[float] = None
        self.tid = tid
        self.args = args


_trace_seq = [0]
_trace_seq_lock = concurrency.lock()


def _new_trace_id() -> str:
    with _trace_seq_lock:
        _trace_seq[0] += 1
        n = _trace_seq[0]
    return f"trace-{os.getpid():x}-{n}"


class Tracer:
    """A span collector for one trace. The constructor opens the ROOT
    span (named after the trace) — every span whose caller gives no
    parent and has no enclosing :meth:`span` context nests under it, so
    a finished trace is always ONE tree.

    ``max_spans`` bounds the store like every other graftscope plane
    (the flight ring's ``capacity``, the history ring's deque): past
    it, the OLDEST non-root spans drop (``dropped_spans`` counts them)
    — a process-wide tracer left installed on a serving loop keeps the
    recent past instead of growing without bound. The root span is
    pinned (never dropped), so the tree keeps its anchor; a surviving
    span whose dropped ancestor is gone re-parents visually to nothing
    — exporters still emit its recorded ``parent_id``.

    ``clock`` is injectable for deterministic tests; it must return
    seconds (float) and be monotone non-decreasing.
    """

    def __init__(self, name: str = "run", clock=time.time,
                 max_spans: int = 100_000):
        if max_spans < 1:
            raise ValueError(f"max_spans must be >= 1, got {max_spans}")
        self._clock = clock
        self._lock = concurrency.lock()
        self._spans = collections.deque(maxlen=max_spans)
        self._by_id = {}  # span_id -> Span for O(1) end(); evicts with
        self._dropped = 0  # the deque
        self._root_span: Optional[Span] = None  # pinned, not in the deque
        self._next_id = 1
        self._tls = threading.local()  # per-thread current-span stack
        self.trace_id = _new_trace_id()
        self.root = self.begin(name, parent=-1)

    # ------------------------------------------------------------- recording

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _current(self) -> Optional[int]:
        st = self._stack()
        return st[-1] if st else getattr(self, "root", None)

    def begin(self, name: str, parent: Optional[int] = None,
              trace: Optional[str] = None, **args) -> int:
        """Open a span; returns its id. ``parent=None`` nests under the
        calling thread's current :meth:`span` context (the root span
        when there is none); ``parent=-1`` makes a root (no parent).
        ``trace`` overrides the span's trace id — graftsight's ticket-
        scoped correlation: lifecycle events for one serve ticket carry
        ``tkt-<id>`` so :meth:`to_chrome` can export that ticket's tree
        alone, while the span still nests in this tracer's store."""
        if parent is None:
            parent = self._current()
        elif parent == -1:
            parent = None
        t0 = self._clock()
        tid = threading.get_ident()
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            sp = Span(sid, trace if trace is not None else self.trace_id,
                      parent, name, t0, tid, args)
            if self._root_span is None:
                self._root_span = sp  # pinned outside the bounded deque
            else:
                if len(self._spans) == self._spans.maxlen:
                    # the deque evicts its oldest span on append
                    self._dropped += 1
                    self._by_id.pop(self._spans[0].span_id, None)
                self._spans.append(sp)
            self._by_id[sid] = sp
        return sid

    def end(self, span_id: int) -> None:
        t1 = self._clock()
        with self._lock:
            sp = self._by_id.get(span_id)
            if sp is not None and sp.t1 is None:
                sp.t1 = t1

    def point(self, name: str, parent: Optional[int] = None,
              trace: Optional[str] = None, **args) -> int:
        """A zero-duration span (an instantaneous lifecycle event)."""
        sid = self.begin(name, parent=parent, trace=trace, **args)
        self.end(sid)
        return sid

    @contextlib.contextmanager
    def span(self, name: str, trace: Optional[str] = None, **args):
        """Open a span for the dynamic extent of the block; spans and
        events recorded inside (on this thread) nest under it."""
        sid = self.begin(name, trace=trace, **args)
        st = self._stack()
        st.append(sid)
        try:
            yield sid
        finally:
            st.pop()
            self.end(sid)

    def close(self) -> None:
        """End the root span (idempotent; exporters treat still-open
        spans as ending 'now', so closing is optional but tidy)."""
        self.end(self.root)

    # ------------------------------------------------------------- reading

    def spans(self) -> List[Span]:
        """Every retained span, root first (oldest-dropped past
        ``max_spans`` — see :attr:`dropped_spans`)."""
        with self._lock:
            root = [] if self._root_span is None else [self._root_span]
            return root + list(self._spans)

    @property
    def dropped_spans(self) -> int:
        """Spans evicted by the ``max_spans`` bound (0 = complete)."""
        with self._lock:
            return self._dropped

    def find(self, name: str) -> List[Span]:
        return [sp for sp in self.spans() if sp.name == name]

    def traces(self) -> dict:
        """Retained span counts per trace id, insertion-ordered — the
        tracer's own trace id first, then every ticket-scoped override
        (:meth:`begin`'s ``trace=``) in first-seen order. What the
        ``/dashboard`` recent-traces table lists."""
        counts: dict = {}
        for sp in self.spans():
            counts[sp.trace_id] = counts.get(sp.trace_id, 0) + 1
        return counts

    # ------------------------------------------------------------ exporters

    def to_chrome(self, trace_id: Optional[str] = None) -> dict:
        """The Chrome/Perfetto trace-event document: one ``ph: "X"``
        complete event per span (µs timestamps), span/parent/trace ids
        in ``args`` so the tree survives the format. ``trace_id``
        filters to one logical trace (a single ticket's lifecycle when
        the serve plane stamped ``tkt-<id>`` trace overrides).

        The top-level ``metadata`` reports what the document does NOT
        contain: ``dropped_spans`` counts spans evicted by the
        ``max_spans`` bound — a serving soak that overflowed the store
        exports a document that says so instead of silently reading as
        complete."""
        now = self._clock()
        events = []
        traces = set()
        for sp in self.spans():
            traces.add(sp.trace_id)
            if trace_id is not None and sp.trace_id != trace_id:
                continue
            t1 = now if sp.t1 is None else sp.t1
            events.append({
                "name": sp.name,
                "cat": "graftscope",
                "ph": "X",
                "ts": sp.t0 * 1e6,
                "dur": max(t1 - sp.t0, 0.0) * 1e6,
                "pid": os.getpid(),
                "tid": sp.tid,
                "args": {"span_id": sp.span_id, "parent_id": sp.parent_id,
                         "trace_id": sp.trace_id, **sp.args},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "dropped_spans": self.dropped_spans,
                "spans": len(events),
                "traces": 1 if trace_id is not None else len(traces),
                "trace_id": trace_id if trace_id is not None
                            else self.trace_id,
            },
        }

    def to_records(self) -> List[dict]:
        """Every span as one record in the shared telemetry JSONL schema
        (telemetry/export.py) — ``type: "event"``, span identity in
        ``labels``, duration and caller attributes in ``data``."""
        now = self._clock()
        out = []
        for sp in self.spans():
            t1 = now if sp.t1 is None else sp.t1
            out.append({
                "type": "event", "name": sp.name, "ts": sp.t0,
                "labels": {
                    "trace": sp.trace_id,
                    "span": str(sp.span_id),
                    "parent": "" if sp.parent_id is None
                              else str(sp.parent_id),
                },
                "data": {"duration_s": max(t1 - sp.t0, 0.0), **sp.args},
            })
        return out

    def write_jsonl(self, sink: Union[str, IO, None]) -> int:
        from p2pnetwork_tpu.telemetry import export

        return export.write_records(self.to_records(), sink)

    def write_chrome(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_chrome(), f, indent=1)
        return path


# --------------------------------------------------------- process install

_installed: Optional[Tracer] = None
_install_lock = concurrency.lock()


def install_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install ``tracer`` as the process-wide trace collector, returning
    the previous one (restore it when done — tests do)."""
    global _installed
    with _install_lock:
        prev, _installed = _installed, tracer
    return prev


def uninstall_tracer() -> Optional[Tracer]:
    return install_tracer(None)


def current_tracer() -> Optional[Tracer]:
    with _install_lock:
        return _installed


def emit(name: str, trace: Optional[str] = None, **args) -> None:
    """Record a point event on the installed tracer; no-op (one
    None-check) when tracing is off — the instrumentation seams call
    this unconditionally. ``trace`` stamps a logical trace id on the
    event (graftsight's per-ticket correlation)."""
    t = current_tracer()
    if t is not None:
        t.point(name, trace=trace, **args)


@contextlib.contextmanager
def span(name: str, trace: Optional[str] = None, **args):
    """A span on the installed tracer for the dynamic extent of the
    block; a plain no-op context when tracing is off."""
    t = current_tracer()
    if t is None:
        yield None
        return
    with t.span(name, trace=trace, **args) as sid:
        yield sid
