"""Compile-time accounting via ``jax.monitoring`` duration events.

The sim backend's hot paths are jitted programs; "where did the time go"
therefore starts with "how much of it was compilation". JAX emits named
duration events for every lowering stage (``/jax/core/compile/
jaxpr_trace_duration``, ``.../jaxpr_to_mlir_module_duration``,
``.../backend_compile_duration``); this module routes them into telemetry
registries as

- ``jax_compiles_total`` — backend-compile count (a recompile detector:
  a loop whose shapes churn shows this climbing per call),
- ``jax_compile_seconds_total{stage=...}`` — wall time per lowering stage.

``jax.monitoring`` has no per-listener unregister (only a global
``clear_event_listeners``), so ONE process-wide listener is installed on
first use and fans out to a set of subscribed registries; subscription is
what is added and removed. Import of jax is deferred and failure-tolerant:
a sockets-only install (no jax) just reports hooks unavailable.
"""

from __future__ import annotations

from typing import Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.telemetry.registry import Registry, default_registry

__all__ = ["install", "uninstall", "installed", "compile_seconds",
           "compile_count"]

_lock = concurrency.lock()
_registries: set = set()
_listener_registered = False

_BACKEND_COMPILE = "backend_compile"


def _on_event_duration(event: str, duration: float, **kw) -> None:
    if "/compile/" not in event:
        return
    stage = event.rsplit("/", 1)[-1]
    if stage.endswith("_duration"):
        stage = stage[: -len("_duration")]
    with _lock:
        subscribed = list(_registries)
    # Resolved OUTSIDE the lock: default_registry() takes the registry
    # module's own lock (open-call discipline — no nesting). None
    # subscribes "whatever the process default is NOW", so a test that
    # swaps the default registry keeps receiving compile events.
    targets = {default_registry() if r is None else r for r in subscribed}
    for reg in targets:
        reg.counter(
            "jax_compile_seconds_total",
            "Wall seconds spent in jit lowering/compilation, by stage.",
            ("stage",),
        ).labels(stage=stage).inc(duration)
        if stage == _BACKEND_COMPILE:
            reg.counter(
                "jax_compiles_total",
                "Number of backend (XLA) compilations.",
            ).inc()


def install(registry: Optional[Registry] = None) -> bool:
    """Subscribe ``registry`` to jit compile events — ``None`` means "the
    process default registry, resolved per event" (survives
    ``set_default_registry`` swaps). Idempotent. Returns False when jax (or
    its monitoring API) is unavailable — callers treat compile metrics as
    absent."""
    global _listener_registered
    try:
        import jax.monitoring as monitoring
    except Exception:
        return False
    with _lock:
        if not _listener_registered:
            try:
                # Registration must be atomic with the flag: two racing
                # installs outside the lock would double-register and
                # double-count every compile. jax.monitoring appends to a
                # plain list without locks of its own, so the nesting is
                # acyclic by construction.
                monitoring.register_event_duration_secs_listener(  # graftlint: ignore[lock-open-call]
                    _on_event_duration)
            except Exception:
                return False
            _listener_registered = True
        _registries.add(registry)
    return True


def uninstall(registry: Optional[Registry] = None) -> None:
    """Unsubscribe ``registry`` from compile events (the process listener
    stays — jax.monitoring cannot remove a single listener)."""
    with _lock:
        _registries.discard(registry)


def installed(registry: Optional[Registry] = None) -> bool:
    with _lock:
        return registry in _registries


def compile_seconds(registry: Optional[Registry] = None,
                    stage: str = _BACKEND_COMPILE) -> float:
    """Total wall seconds recorded for one lowering stage so far (callers
    take before/after deltas around the region they attribute)."""
    return (registry or default_registry()).value(
        "jax_compile_seconds_total", stage=stage)


def compile_count(registry: Optional[Registry] = None) -> float:
    return (registry or default_registry()).value("jax_compiles_total")
