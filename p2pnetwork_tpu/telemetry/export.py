"""Registry exporters: Prometheus text exposition and the JSONL stream.

One schema, two encodings. The JSONL stream is the machine-readable side —
one self-describing record per line, each carrying ``type`` (counter /
gauge / histogram / event), ``name``, ``ts``, ``labels`` and the value
payload — shared with ``utils/logging.EventLog.to_jsonl`` so socket events
and metric samples interleave in one file without schema drift. The
Prometheus side is the text-exposition format (0.0.4) a scraper or the
bundled stdlib endpoint (:mod:`p2pnetwork_tpu.telemetry.httpd`) serves.
"""

from __future__ import annotations

import json
import math
import time
from typing import IO, Iterator, Optional, Union

from p2pnetwork_tpu.telemetry.registry import (Registry, _HistogramChild,
                                               default_registry)

__all__ = ["to_prometheus", "metric_records", "write_jsonl", "event_record",
           "write_records"]


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labelnames, labelvalues, extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, labelvalues)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(float(v))


def to_prometheus(registry: Optional[Registry] = None) -> str:
    """Render every family as Prometheus text exposition (version 0.0.4):
    ``# HELP`` / ``# TYPE`` headers, ``_bucket{le=...}`` / ``_sum`` /
    ``_count`` series for histograms."""
    registry = registry or default_registry()
    lines = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for child in m.children():
            if isinstance(child, _HistogramChild):
                for ub, cum in child.cumulative():
                    le = "+Inf" if math.isinf(ub) else _fmt_value(ub)
                    labels = _fmt_labels(m.labelnames, child.labels,
                                         f'le="{le}"')
                    lines.append(f"{m.name}_bucket{labels} {cum}")
                labels = _fmt_labels(m.labelnames, child.labels)
                lines.append(f"{m.name}_sum{labels} {_fmt_value(child.sum)}")
                lines.append(f"{m.name}_count{labels} {child.count}")
            else:
                labels = _fmt_labels(m.labelnames, child.labels)
                lines.append(f"{m.name}{labels} {_fmt_value(child.value)}")
    return "\n".join(lines) + "\n"


def metric_records(registry: Optional[Registry] = None,
                   ts: Optional[float] = None) -> Iterator[dict]:
    """One JSONL-schema dict per sample of every family in ``registry``."""
    registry = registry or default_registry()
    ts = time.time() if ts is None else ts
    for name, fam in registry.snapshot().items():
        for sample in fam["samples"]:
            rec = {"type": fam["type"], "name": name, "ts": ts,
                   "labels": sample["labels"]}
            if fam["type"] == "histogram":
                rec.update(sum=sample["sum"], count=sample["count"],
                           buckets=sample["buckets"])
            else:
                rec["value"] = sample["value"]
            yield rec


def event_record(event: str, timestamp: float, peer_id=None,
                 data=None) -> dict:
    """An EventLog record in the shared JSONL schema — ``type: "event"``
    beside the metric types, so one stream carries both."""
    try:
        json.dumps(data)
    except (TypeError, ValueError):
        data = repr(data)  # exceptions and arbitrary objects ride as repr
    return {"type": "event", "name": event, "ts": timestamp,
            "labels": {} if peer_id is None else {"peer": str(peer_id)},
            "data": data}


def write_records(records, sink: Union[str, IO, None]) -> int:
    """Append schema records as JSON lines to ``sink`` (path = append mode,
    or any writable file object); returns the number of lines written. The
    single sink-dispatch used by every JSONL producer (metric samples here,
    socket events via ``EventLog.to_jsonl``) so their file semantics cannot
    drift apart."""
    records = list(records)
    f, close = (open(sink, "a", encoding="utf-8"), True) \
        if isinstance(sink, str) else (sink, False)
    if f is None:
        return 0
    try:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    finally:
        if close:
            f.close()
    return len(records)


def write_jsonl(registry: Optional[Registry] = None,
                sink: Union[str, IO, None] = None,
                ts: Optional[float] = None) -> int:
    """Append every sample as one JSON line to ``sink`` (path or file
    object); returns the number of lines written."""
    return write_records(metric_records(registry, ts), sink)
