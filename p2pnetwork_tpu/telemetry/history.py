"""graftscope history plane: a bounded time-series ring over the registry.

``/metrics`` is point-in-time — a scrape after the run ended sees only
the final gauge values, and "how did ``sim_batch_active_lanes`` move
across the run" is gone. :class:`History` keeps the recent past: a
fixed-capacity ring of samples, each one timestamped snapshot of the
registry's GAUGES (the point-in-time metrics; counters/histograms are
cumulative and reconstructable from scrapes). Samples are taken
explicitly via :meth:`History.sample` — the sim engine samples the
default history once per run summary (engine ``_timed_summary`` /
``_record_batch_summary``), so a batched serving loop gets one point
per ``run_batch_until_coverage`` call with zero extra wiring — and the
ring is what ``httpd``'s ``/history`` endpoint serves.

Stdlib-only and thread-safe like the registry: sampling happens from
whatever thread finished a run while scrape threads serialize the
ring.
"""

from __future__ import annotations

import collections
import time
from typing import List, Optional, Tuple

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.telemetry.registry import (Gauge, Registry,
                                               default_registry)

__all__ = ["History", "default_history", "set_default_history"]


class History:
    """A fixed-capacity ring of gauge samples.

    ``registry=None`` means "the process default registry, resolved per
    sample" — it survives ``set_default_registry`` swaps, mirroring the
    jaxhooks subscription semantics. ``capacity`` bounds the ring;
    older samples fall off (this is recent-history observability, not
    long-term storage — point a real TSDB at ``/metrics`` for that)."""

    def __init__(self, registry: Optional[Registry] = None,
                 capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._registry = registry
        self.capacity = capacity
        self._lock = concurrency.lock()
        self._ring = collections.deque(maxlen=capacity)

    def _resolve(self) -> Registry:
        return self._registry if self._registry is not None \
            else default_registry()

    def sample(self, ts: Optional[float] = None) -> dict:
        """Take one sample: every gauge child's current value, keyed
        ``(name, label-values)``, timestamped. Returns the row (also
        appended to the ring)."""
        ts = time.time() if ts is None else ts
        reg = self._resolve()
        values = {}
        # Read the registry OUTSIDE this ring's lock (open-call
        # discipline: gauge reads take the metric locks).
        for metric in reg.collect():
            if not isinstance(metric, Gauge):
                continue
            for child in metric.children():
                values[(metric.name, child.labels)] = child.value
        row = {"ts": ts, "values": values}
        with self._lock:
            self._ring.append(row)
        return row

    def rows(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def series(self, name: str,
               *labelvalues) -> List[Tuple[float, float]]:
        """One gauge's sampled series as ``[(ts, value), ...]``, label
        values positional in the gauge's label order (none for an
        unlabeled gauge) — samples where the child did not exist yet
        are skipped."""
        key = (name, tuple(str(v) for v in labelvalues))
        out = []
        for row in self.rows():
            v = row["values"].get(key)
            if v is not None:
                out.append((row["ts"], v))
        return out

    def snapshot(self, last: Optional[int] = None) -> dict:
        """JSON-able transposed view — what ``/history`` serves:
        ``{"capacity", "samples", "series": {name: [{"labels": [...],
        "points": [[ts, value], ...]}]}}`` with points in sample
        order. ``last`` keeps only the most recent N samples (the
        ``/history?n=`` query — a long serving run's scrape need not
        ship the whole ring)."""
        rows = self.rows()
        if last is not None:
            if last < 1:
                raise ValueError(f"last must be >= 1, got {last}")
            rows = rows[-last:]
        series: dict = {}
        for row in rows:
            for (name, labelvals), value in row["values"].items():
                series.setdefault(name, {}).setdefault(
                    labelvals, []).append([row["ts"], value])
        return {
            "capacity": self.capacity,
            "samples": len(rows),
            "series": {
                name: [{"labels": list(labelvals), "points": pts}
                       for labelvals, pts in by_labels.items()]
                for name, by_labels in series.items()
            },
        }


_default = History()
_default_lock = concurrency.lock()


def default_history() -> History:
    """The process-wide history ring the engine's run summaries sample
    and ``/history`` serves by default."""
    with _default_lock:
        return _default


def set_default_history(history: History) -> History:
    """Swap the process-wide history, returning the previous one (tests
    isolate by swapping a fresh ring in and restoring after)."""
    global _default
    with _default_lock:
        prev, _default = _default, history
    return prev
