"""Unified telemetry: one metrics registry across sockets, sim, and bench.

- :mod:`~p2pnetwork_tpu.telemetry.registry` — counters, gauges, exponential-
  bucket histograms; thread-safe, zero-dep; :func:`default_registry` is the
  process-wide plane every instrumentation site reports to.
- :mod:`~p2pnetwork_tpu.telemetry.export` — Prometheus text exposition and
  the shared JSONL schema (metric samples and EventLog events interleave).
- :mod:`~p2pnetwork_tpu.telemetry.httpd` — ``/metrics`` / ``/history`` /
  ``/trace`` scrape endpoints on a stdlib HTTP server.
- :mod:`~p2pnetwork_tpu.telemetry.jaxhooks` — jit compile count / wall-time
  bridged from ``jax.monitoring`` (gated: works without jax installed).
- :mod:`~p2pnetwork_tpu.telemetry.spans` — the graftscope trace plane:
  trace ids + spans with parent links, per-lane lifecycle events,
  Chrome/Perfetto and JSONL exporters.
- :mod:`~p2pnetwork_tpu.telemetry.history` — the graftscope history ring:
  a bounded gauge time-series sampled once per engine run summary.
- :mod:`~p2pnetwork_tpu.telemetry.slo` — the graftsight SLO engine:
  declarative objectives over rolling windows, multi-window burn-rate
  alerts as EventLog records + ``slo_burn_rate`` gauges.
"""

from p2pnetwork_tpu.telemetry.registry import (
    Counter, Gauge, Histogram, Registry,
    DEFAULT_LATENCY_BUCKETS, DEFAULT_SIZE_BUCKETS,
    default_registry, set_default_registry, exponential_buckets,
)
from p2pnetwork_tpu.telemetry.export import (
    event_record, metric_records, to_prometheus, write_jsonl,
)
from p2pnetwork_tpu.telemetry.history import (
    History, default_history, set_default_history,
)
from p2pnetwork_tpu.telemetry.httpd import MetricsServer
from p2pnetwork_tpu.telemetry.slo import (
    Objective, SLOEngine, serve_objectives,
)
from p2pnetwork_tpu.telemetry.spans import (
    Tracer, current_tracer, install_tracer, uninstall_tracer,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "DEFAULT_LATENCY_BUCKETS", "DEFAULT_SIZE_BUCKETS",
    "default_registry", "set_default_registry", "exponential_buckets",
    "event_record", "metric_records", "to_prometheus", "write_jsonl",
    "History", "default_history", "set_default_history",
    "MetricsServer",
    "Objective", "SLOEngine", "serve_objectives",
    "Tracer", "current_tracer", "install_tracer", "uninstall_tracer",
]
