"""Live scrape endpoint: a stdlib HTTP server exposing the registry.

``GET /metrics`` serves Prometheus text exposition (what a Prometheus
scraper or ``curl`` reads); ``GET /metrics.json`` serves the registry
snapshot as JSON for ad-hoc tooling; ``GET /history`` serves the
graftscope history ring (the sampled gauge time-series,
:mod:`p2pnetwork_tpu.telemetry.history`); ``GET /trace`` serves the
installed trace plane as Chrome/Perfetto trace-event JSON
(:mod:`p2pnetwork_tpu.telemetry.spans` — save it and load at
https://ui.perfetto.dev; an empty ``traceEvents`` array when no tracer
is installed, so the endpoint is always parseable). Zero dependencies —
``http.server.ThreadingHTTPServer`` on one daemon thread — so a live
sockets deployment can be watched without installing anything
(GETTING_STARTED.md "Observability").
"""

from __future__ import annotations

import http.server
import json
from typing import Any, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.telemetry.registry import Registry, default_registry
from p2pnetwork_tpu.telemetry import export, history, spans

__all__ = ["MetricsServer"]


class _Handler(http.server.BaseHTTPRequestHandler):
    registry: Registry      # stamped onto the subclass by MetricsServer
    history: Optional[Any]  # History or None (None = process default)
    tracer: Optional[Any]   # Tracer or None (None = installed tracer)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = export.to_prometheus(self.registry).encode("utf-8")
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/metrics.json":
            body = json.dumps(self.registry.snapshot()).encode("utf-8")
            ctype = "application/json"
        elif path == "/history":
            hist = self.history if self.history is not None \
                else history.default_history()
            body = json.dumps(hist.snapshot()).encode("utf-8")
            ctype = "application/json"
        elif path == "/trace":
            tracer = self.tracer if self.tracer is not None \
                else spans.current_tracer()
            doc = tracer.to_chrome() if tracer is not None \
                else {"traceEvents": [], "displayTimeUnit": "ms"}
            body = json.dumps(doc).encode("utf-8")
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        pass


class MetricsServer:
    """Serve ``registry`` over HTTP on a background daemon thread.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` after
    :meth:`start`). ``history``/``tracer`` bind a specific history ring /
    trace collector to ``/history`` and ``/trace``; by default those
    endpoints follow the process-wide
    :func:`~p2pnetwork_tpu.telemetry.history.default_history` and the
    tracer installed via
    :func:`~p2pnetwork_tpu.telemetry.spans.install_tracer`, resolved per
    request. Usable as a context manager::

        with MetricsServer(port=0) as srv:
            print(f"curl http://127.0.0.1:{srv.port}/metrics")
    """

    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 history: Optional[Any] = None,
                 tracer: Optional[Any] = None):
        self.registry = registry or default_registry()
        self.history = history
        self.tracer = tracer
        self.host = host
        self.port = port
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[Any] = None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        handler = type("BoundHandler", (_Handler,),
                       {"registry": self.registry, "history": self.history,
                        "tracer": self.tracer})
        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = concurrency.thread(
            target=self._httpd.serve_forever,
            name=f"MetricsServer({self.host}:{self.port})", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
