"""Live scrape endpoint: a stdlib HTTP server exposing the registry.

``GET /metrics`` serves Prometheus text exposition (what a Prometheus
scraper or ``curl`` reads); ``GET /metrics.json`` serves the registry
snapshot as JSON for ad-hoc tooling; ``GET /history`` serves the
graftscope history ring (the sampled gauge time-series,
:mod:`p2pnetwork_tpu.telemetry.history`); ``GET /trace`` serves the
installed trace plane as Chrome/Perfetto trace-event JSON
(:mod:`p2pnetwork_tpu.telemetry.spans` — save it and load at
https://ui.perfetto.dev; an empty ``traceEvents`` array when no tracer
is installed, so the endpoint is always parseable). Zero dependencies —
``http.server.ThreadingHTTPServer`` on one daemon thread — so a live
sockets deployment can be watched without installing anything
(GETTING_STARTED.md "Observability").

An application can mount its own endpoints NEXT TO the telemetry ones
via ``service=``: any object with ``handle_http(method, path, body)
-> (status, payload_dict) | None`` gets every request the built-in
routes don't claim (``None`` means "not mine" and falls through to 404).
The one real implementation is the serving front-end
(:class:`p2pnetwork_tpu.serve.SimService`: ``/submit``, ``/poll/<t>``,
``/cancel/<t>``, ``/stats``) — duck-typed here so this module stays
stdlib-only and importable without jax.
"""

from __future__ import annotations

import http.server
import json
from typing import Any, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.telemetry.registry import Registry, default_registry
from p2pnetwork_tpu.telemetry import export, history, spans

__all__ = ["MetricsServer"]


class _Handler(http.server.BaseHTTPRequestHandler):
    registry: Registry      # stamped onto the subclass by MetricsServer
    history: Optional[Any]  # History or None (None = process default)
    tracer: Optional[Any]   # Tracer or None (None = installed tracer)
    service: Optional[Any] = None  # handle_http provider or None

    def _respond(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _respond_json(self, status: int, payload: Any) -> None:
        self._respond(status, json.dumps(payload).encode("utf-8"),
                      "application/json")

    def _dispatch_service(self, method: str, body: Optional[dict]) -> bool:
        """Offer the request to the bound service; True when it claimed
        it. Service errors become a 500 with the error named — a buggy
        handler must not wedge the scrape thread."""
        if self.service is None:
            return False
        try:
            resp = self.service.handle_http(method, self.path, body)
        except Exception as e:
            self._respond_json(
                500, {"error": f"{type(e).__name__}: {e}"})
            return True
        if resp is None:
            return False
        status, payload = resp
        self._respond_json(int(status), payload)
        return True

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = export.to_prometheus(self.registry).encode("utf-8")
            self._respond(200, body,
                          "text/plain; version=0.0.4; charset=utf-8")
            return
        if path == "/metrics.json":
            self._respond_json(200, self.registry.snapshot())
            return
        if path == "/history":
            hist = self.history if self.history is not None \
                else history.default_history()
            self._respond_json(200, hist.snapshot())
            return
        if path == "/trace":
            tracer = self.tracer if self.tracer is not None \
                else spans.current_tracer()
            doc = tracer.to_chrome() if tracer is not None \
                else {"traceEvents": [], "displayTimeUnit": "ms"}
            self._respond_json(200, doc)
            return
        if self._dispatch_service("GET", None):
            return
        self.send_error(404)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        raw = self.rfile.read(length) if length > 0 else b""
        body: Optional[dict] = None
        if raw:
            try:
                parsed = json.loads(raw.decode("utf-8"))
                body = parsed if isinstance(parsed, dict) else None
            except ValueError:
                self._respond_json(400, {"error": "body is not JSON"})
                return
        if self._dispatch_service("POST", body):
            return
        self.send_error(404)

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        pass


class MetricsServer:
    """Serve ``registry`` over HTTP on a background daemon thread.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` after
    :meth:`start` — the OS-assigned port is reported, so test fixtures
    and co-located services never race over fixed ports).
    ``history``/``tracer`` bind a specific history ring / trace collector
    to ``/history`` and ``/trace``; by default those endpoints follow the
    process-wide
    :func:`~p2pnetwork_tpu.telemetry.history.default_history` and the
    tracer installed via
    :func:`~p2pnetwork_tpu.telemetry.spans.install_tracer`, resolved per
    request. ``service`` mounts application endpoints beside the
    telemetry ones (module docstring). ``start``/:meth:`close` are
    idempotent and safe to race from several threads — the whole
    lifecycle is serialized by one lock, so concurrent start/close pairs
    settle into a consistent state instead of leaking a server or
    double-binding a port. Usable as a context manager::

        with MetricsServer(port=0) as srv:
            print(f"curl http://127.0.0.1:{srv.port}/metrics")
    """

    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 history: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 service: Optional[Any] = None):
        self.registry = registry or default_registry()
        self.history = history
        self.tracer = tracer
        self.service = service
        self.host = host
        self.port = port
        #: The port asked for at construction: a close() must rebind the
        #: SAME ephemeral request (0 = "any"), not the port the previous
        #: start happened to get (which may be taken by then).
        self._requested_port = port
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[Any] = None
        # Serializes the whole start/stop lifecycle: concurrent starts
        # must agree on ONE bound server, and a close racing a start must
        # observe either the unstarted or the fully-started state.
        self._lifecycle_lock = concurrency.lock()

    def start(self) -> "MetricsServer":
        with self._lifecycle_lock:
            if self._httpd is not None:
                return self
            handler = type("BoundHandler", (_Handler,),
                           {"registry": self.registry,
                            "history": self.history,
                            "tracer": self.tracer,
                            "service": self.service})
            self._httpd = http.server.ThreadingHTTPServer(  # graftlint: ignore[lock-open-call] -- the bind must be atomic with the started-state publish, or two racing starts double-bind
                (self.host, self._requested_port), handler)
            self.port = self._httpd.server_address[1]
            self._thread = concurrency.thread(  # graftlint: ignore[lock-open-call] -- same lifecycle atomicity; the seam factory only constructs
                target=self._httpd.serve_forever,
                name=f"MetricsServer({self.host}:{self.port})", daemon=True)
            self._thread.start()  # graftlint: ignore[lock-open-call] -- same lifecycle atomicity; start() does not block on the serve loop
        return self

    def stop(self) -> None:
        """Shut the server down and release the port. Idempotent — a
        second (or concurrent) call is a no-op; :meth:`close` is the
        same operation under the conventional resource name."""
        with self._lifecycle_lock:
            httpd, thread = self._httpd, self._thread
            self._httpd = self._thread = None
            if httpd is None:
                return
            httpd.shutdown()  # graftlint: ignore[lock-open-call] -- teardown must be atomic with the stopped-state publish; bounded (serve loop poll interval)
            httpd.server_close()  # graftlint: ignore[lock-open-call] -- same teardown atomicity
            if thread is not None:
                thread.join(timeout=5.0)  # graftlint: ignore[lock-open-call] -- same teardown atomicity; bounded join

    def close(self) -> None:
        """Alias of :meth:`stop` (idempotent)."""
        self.stop()

    @property
    def url(self) -> str:
        with self._lifecycle_lock:
            port = self.port
        return f"http://{self.host}:{port}/metrics"

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
