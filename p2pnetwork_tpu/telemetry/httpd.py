"""Live scrape endpoint: a stdlib HTTP server exposing the registry.

``GET /metrics`` serves Prometheus text exposition (what a Prometheus
scraper or ``curl`` reads); ``GET /metrics.json`` serves the registry
snapshot as JSON for ad-hoc tooling; ``GET /history`` serves the
graftscope history ring (the sampled gauge time-series,
:mod:`p2pnetwork_tpu.telemetry.history`; ``?n=`` limits to the last N
samples); ``GET /trace`` serves the installed trace plane as
Chrome/Perfetto trace-event JSON
(:mod:`p2pnetwork_tpu.telemetry.spans` — save it and load at
https://ui.perfetto.dev; an empty ``traceEvents`` array when no tracer
is installed, so the endpoint is always parseable; ``?trace_id=``
exports one logical trace — a single serve ticket's lifecycle when the
graftsight correlation stamped ``tkt-<id>`` trace ids). Malformed query
params are a 400 with the error named, never a 500. ``GET /dashboard``
serves graftsight's self-contained HTML snapshot (metrics + recent
history + SLO state + recent traces + the bound service's tick-phase
profile, all embedded as one JSON document); ``GET /dashboard.json`` is
the same document bare, for tooling. Zero dependencies —
``http.server.ThreadingHTTPServer`` on one daemon thread — so a live
sockets deployment can be watched without installing anything
(GETTING_STARTED.md "Observability").

An application can mount its own endpoints NEXT TO the telemetry ones
via ``service=``: any object with ``handle_http(method, path, body)
-> (status, payload_dict) | None`` gets every request the built-in
routes don't claim (``None`` means "not mine" and falls through to 404).
The one real implementation is the serving front-end
(:class:`p2pnetwork_tpu.serve.SimService`: ``/submit``, ``/poll/<t>``,
``/cancel/<t>``, ``/stats``) — duck-typed here so this module stays
stdlib-only and importable without jax.
"""

from __future__ import annotations

import http.server
import json
import time
import urllib.parse
from typing import Any, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.telemetry.registry import Registry, default_registry
from p2pnetwork_tpu.telemetry import export, history, spans

__all__ = ["MetricsServer", "dashboard_doc"]

#: /dashboard bounds what it embeds — it is a snapshot for a browser
#: tab, not a bulk-export path (/metrics.json, /history and /trace
#: remain the full-fidelity endpoints).
_DASHBOARD_HISTORY_N = 128
_DASHBOARD_TRACES_N = 64


class _BadQuery(ValueError):
    """A malformed query param — the handler answers 400, not 500."""


def _query_int(params: dict, key: str) -> Optional[int]:
    """Parse an optional positive-int query param; :class:`_BadQuery`
    names the offending value on anything else."""
    vals = params.get(key)
    if not vals:
        return None
    try:
        n = int(vals[-1])
    except ValueError:
        raise _BadQuery(f"{key} must be an integer, got {vals[-1]!r}")
    if n < 1:
        raise _BadQuery(f"{key} must be >= 1, got {n}")
    return n


def dashboard_doc(registry: Registry, hist: Any, tracer: Optional[Any],
                  slo: Optional[Any], service: Optional[Any]) -> dict:
    """The one JSON document behind ``/dashboard`` and
    ``/dashboard.json``: metrics snapshot, recent history samples, the
    SLO engine's state (duck-typed ``snapshot()``), a recent-traces
    table, and the bound service's dashboard slice (duck-typed
    ``dashboard_slice()`` — :class:`p2pnetwork_tpu.serve.SimService`
    publishes its tick-phase profile and stats through it). Module-level
    so graftrace scenarios can exercise the exact scrape path without
    sockets."""
    doc: dict = {
        "generated_unix": time.time(),
        "metrics": registry.snapshot(),
        "history": hist.snapshot(last=_DASHBOARD_HISTORY_N),
        "slo": None,
        "traces": None,
        "service": None,
    }
    if slo is not None:
        doc["slo"] = slo.snapshot()
    if tracer is not None:
        by_trace = tracer.traces()
        doc["traces"] = {
            "trace_id": tracer.trace_id,
            "dropped_spans": tracer.dropped_spans,
            "recent": dict(list(by_trace.items())[-_DASHBOARD_TRACES_N:]),
            "total": len(by_trace),
        }
    if service is not None:
        slicer = getattr(service, "dashboard_slice", None)
        if callable(slicer):
            doc["service"] = slicer()
    return doc


#: Self-contained dashboard page: the snapshot JSON rides in a
#: <script type="application/json"> island and a few lines of inline JS
#: render the tables — no assets, no CDN, works from a file:// save.
_DASHBOARD_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>graftsight dashboard</title>
<style>
 body{font-family:monospace;margin:1.5em;background:#111;color:#ddd}
 h1{font-size:1.2em} h2{font-size:1em;margin-top:1.2em;color:#8cf}
 table{border-collapse:collapse;margin:.3em 0}
 td,th{border:1px solid #444;padding:.15em .5em;text-align:left}
 .firing{color:#f66;font-weight:bold} .ok{color:#6d6}
 pre{white-space:pre-wrap}
</style></head><body>
<h1>graftsight dashboard</h1>
<div id="out">(rendering…)</div>
<script id="data" type="application/json">__DATA__</script>
<script>
 const d = JSON.parse(document.getElementById("data").textContent);
 const esc = s => String(s).replace(/[&<>]/g,
   c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
 const row = cells => "<tr>" + cells.map(c => "<td>" + esc(c) +
   "</td>").join("") + "</tr>";
 let h = "<h2>SLOs</h2>";
 if (d.slo && d.slo.objectives) {
   h += "<table><tr><th>objective</th><th>state</th><th>good</th>" +
        "<th>burn fast</th><th>burn slow</th><th>samples</th></tr>";
   for (const [name, o] of Object.entries(d.slo.objectives))
     h += "<tr><td>" + esc(name) + "</td><td class=" +
          (o.firing ? "firing>FIRING" : "ok>ok") + "</td>" +
          [o.good_ratio, o.burn_fast, o.burn_slow, o.samples]
            .map(v => "<td>" + esc(v) + "</td>").join("") + "</tr>";
   h += "</table>";
 } else h += "<p>(no SLO engine bound)</p>";
 h += "<h2>Tick phases</h2>";
 const tp = d.service && d.service.tick_phases;
 if (tp && tp.ticks) {
   h += "<p>ticks: " + esc(tp.ticks) + "</p><table><tr><th>phase</th>" +
        "<th>total s</th><th>mean s</th><th>last s</th><th>max s</th></tr>";
   for (const [ph, s] of Object.entries(tp.per_phase))
     h += row([ph, s.total_s.toExponential(3), s.mean_s.toExponential(3),
               s.last_s.toExponential(3), s.max_s.toExponential(3)]);
   h += "</table>";
 } else h += "<p>(no service bound / no ticks yet)</p>";
 h += "<h2>Recent traces</h2>";
 if (d.traces) {
   h += "<p>dropped spans: " + esc(d.traces.dropped_spans) +
        "</p><table><tr><th>trace id</th><th>spans</th></tr>";
   for (const [t, n] of Object.entries(d.traces.recent)) h += row([t, n]);
   h += "</table>";
 } else h += "<p>(no tracer installed)</p>";
 h += "<h2>History</h2><p>" + esc(d.history.samples) +
      " samples embedded (series: " +
      esc(Object.keys(d.history.series).length) + ")</p>";
 h += "<h2>Raw snapshot</h2><pre>" +
      esc(JSON.stringify(d, null, 1).slice(0, 20000)) + "</pre>";
 document.getElementById("out").innerHTML = h;
</script></body></html>
"""


class _Handler(http.server.BaseHTTPRequestHandler):
    registry: Registry      # stamped onto the subclass by MetricsServer
    history: Optional[Any]  # History or None (None = process default)
    tracer: Optional[Any]   # Tracer or None (None = installed tracer)
    service: Optional[Any] = None  # handle_http provider or None
    slo: Optional[Any] = None      # SLO engine (snapshot()) or None

    def _respond(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _respond_json(self, status: int, payload: Any) -> None:
        self._respond(status, json.dumps(payload).encode("utf-8"),
                      "application/json")

    def _dispatch_service(self, method: str, body: Optional[dict]) -> bool:
        """Offer the request to the bound service; True when it claimed
        it. Service errors become a 500 with the error named — a buggy
        handler must not wedge the scrape thread."""
        if self.service is None:
            return False
        try:
            resp = self.service.handle_http(method, self.path, body)
        except Exception as e:
            self._respond_json(
                500, {"error": f"{type(e).__name__}: {e}"})
            return True
        if resp is None:
            return False
        status, payload = resp
        self._respond_json(int(status), payload)
        return True

    def _resolve_history(self):
        return self.history if self.history is not None \
            else history.default_history()

    def _resolve_tracer(self):
        return self.tracer if self.tracer is not None \
            else spans.current_tracer()

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        split = urllib.parse.urlsplit(self.path)
        path = split.path
        # keep_blank_values: ``?trace_id=`` must reach the validator (and
        # 400) rather than silently parse as "no param".
        params = urllib.parse.parse_qs(split.query, keep_blank_values=True)
        try:
            if path in ("/metrics", "/"):
                body = export.to_prometheus(self.registry).encode("utf-8")
                self._respond(200, body,
                              "text/plain; version=0.0.4; charset=utf-8")
                return
            if path == "/metrics.json":
                self._respond_json(200, self.registry.snapshot())
                return
            if path == "/history":
                n = _query_int(params, "n")
                self._respond_json(200,
                                   self._resolve_history().snapshot(last=n))
                return
            if path == "/trace":
                trace_id = None
                if "trace_id" in params:
                    trace_id = params["trace_id"][-1]
                    if not trace_id:
                        raise _BadQuery("trace_id must be non-empty")
                tracer = self._resolve_tracer()
                doc = tracer.to_chrome(trace_id=trace_id) \
                    if tracer is not None \
                    else {"traceEvents": [], "displayTimeUnit": "ms",
                          "metadata": {"dropped_spans": 0, "spans": 0,
                                       "traces": 0, "trace_id": None}}
                self._respond_json(200, doc)
                return
            if path in ("/dashboard", "/dashboard.json"):
                doc = dashboard_doc(self.registry, self._resolve_history(),
                                    self._resolve_tracer(), self.slo,
                                    self.service)
                if path == "/dashboard.json":
                    self._respond_json(200, doc)
                    return
                # "</" must not terminate the script island early — the
                # standard JSON-in-HTML embedding escape.
                blob = json.dumps(doc).replace("</", "<\\/")
                page = _DASHBOARD_HTML.replace("__DATA__", blob)
                self._respond(200, page.encode("utf-8"),
                              "text/html; charset=utf-8")
                return
        except _BadQuery as e:
            self._respond_json(400, {"error": str(e)})
            return
        if self._dispatch_service("GET", None):
            return
        self.send_error(404)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler's contract
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        raw = self.rfile.read(length) if length > 0 else b""
        body: Optional[dict] = None
        if raw:
            try:
                parsed = json.loads(raw.decode("utf-8"))
                body = parsed if isinstance(parsed, dict) else None
            except ValueError:
                self._respond_json(400, {"error": "body is not JSON"})
                return
        if self._dispatch_service("POST", body):
            return
        self.send_error(404)

    def log_message(self, fmt, *args):  # scrapes must not spam stdout
        pass


class MetricsServer:
    """Serve ``registry`` over HTTP on a background daemon thread.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` after
    :meth:`start` — the OS-assigned port is reported, so test fixtures
    and co-located services never race over fixed ports).
    ``history``/``tracer`` bind a specific history ring / trace collector
    to ``/history`` and ``/trace``; by default those endpoints follow the
    process-wide
    :func:`~p2pnetwork_tpu.telemetry.history.default_history` and the
    tracer installed via
    :func:`~p2pnetwork_tpu.telemetry.spans.install_tracer`, resolved per
    request. ``service`` mounts application endpoints beside the
    telemetry ones (module docstring); ``slo`` binds a graftsight SLO
    engine (:class:`p2pnetwork_tpu.telemetry.slo.SLOEngine`, duck-typed
    ``snapshot()``) into ``/dashboard``. ``start``/:meth:`close` are
    idempotent and safe to race from several threads — the whole
    lifecycle is serialized by one lock, so concurrent start/close pairs
    settle into a consistent state instead of leaking a server or
    double-binding a port. Usable as a context manager::

        with MetricsServer(port=0) as srv:
            print(f"curl http://127.0.0.1:{srv.port}/metrics")
    """

    def __init__(self, registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 history: Optional[Any] = None,
                 tracer: Optional[Any] = None,
                 service: Optional[Any] = None,
                 slo: Optional[Any] = None):
        self.registry = registry or default_registry()
        self.history = history
        self.tracer = tracer
        self.service = service
        self.slo = slo
        self.host = host
        self.port = port
        #: The port asked for at construction: a close() must rebind the
        #: SAME ephemeral request (0 = "any"), not the port the previous
        #: start happened to get (which may be taken by then).
        self._requested_port = port
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[Any] = None
        # Serializes the whole start/stop lifecycle: concurrent starts
        # must agree on ONE bound server, and a close racing a start must
        # observe either the unstarted or the fully-started state.
        self._lifecycle_lock = concurrency.lock()

    def start(self) -> "MetricsServer":
        with self._lifecycle_lock:
            if self._httpd is not None:
                return self
            handler = type("BoundHandler", (_Handler,),
                           {"registry": self.registry,
                            "history": self.history,
                            "tracer": self.tracer,
                            "service": self.service,
                            "slo": self.slo})
            self._httpd = http.server.ThreadingHTTPServer(  # graftlint: ignore[lock-open-call] -- the bind must be atomic with the started-state publish, or two racing starts double-bind
                (self.host, self._requested_port), handler)
            self.port = self._httpd.server_address[1]
            self._thread = concurrency.thread(  # graftlint: ignore[lock-open-call] -- same lifecycle atomicity; the seam factory only constructs
                target=self._httpd.serve_forever,
                name=f"MetricsServer({self.host}:{self.port})", daemon=True)
            self._thread.start()  # graftlint: ignore[lock-open-call] -- same lifecycle atomicity; start() does not block on the serve loop
        return self

    def stop(self) -> None:
        """Shut the server down and release the port. Idempotent — a
        second (or concurrent) call is a no-op; :meth:`close` is the
        same operation under the conventional resource name."""
        with self._lifecycle_lock:
            httpd, thread = self._httpd, self._thread
            self._httpd = self._thread = None
            if httpd is None:
                return
            httpd.shutdown()  # graftlint: ignore[lock-open-call] -- teardown must be atomic with the stopped-state publish; bounded (serve loop poll interval)
            httpd.server_close()  # graftlint: ignore[lock-open-call] -- same teardown atomicity
            if thread is not None:
                thread.join(timeout=5.0)  # graftlint: ignore[lock-open-call] -- same teardown atomicity; bounded join

    def close(self) -> None:
        """Alias of :meth:`stop` (idempotent)."""
        self.stop()

    @property
    def url(self) -> str:
        with self._lifecycle_lock:
            port = self.port
        return f"http://{self.host}:{port}/metrics"

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
