"""Backend-agnostic metrics registry: counters, gauges, histograms.

The reference's observability is a ``debug`` print flag plus three integer
counters [ref: p2pnetwork/node.py:64-67]; before this module the repo had
three disjoint islands — ``utils/trace.py`` (sim JSONL), ``utils/logging.py``
(sockets EventLog), ``parallel/commviz.py`` (HLO traffic classifier) — with
no shared schema. This registry is the one telemetry plane both backends
report through: the sockets path (per-peer bytes, handle-latency histograms,
reconnects, phi suspicion), the sim path (run summaries bridged post-transfer,
compile wall-time via jax.monitoring, injected failures), and the parallel
diagnostics (ICI/DCN byte budgets from compiled HLO).

Deliberately zero-dependency (stdlib only — the sockets backend must work
without jax installed) and thread-safe: sockets metrics update from asyncio
loops on several node threads while exporters snapshot from scrape or test
threads. Exporters live in :mod:`p2pnetwork_tpu.telemetry.export` and
:mod:`p2pnetwork_tpu.telemetry.httpd`; the in-process snapshot API for tests
is :meth:`Registry.snapshot` / :meth:`Registry.value`.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from p2pnetwork_tpu import concurrency

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "default_registry", "set_default_registry", "exponential_buckets",
    "DEFAULT_LATENCY_BUCKETS", "DEFAULT_SIZE_BUCKETS",
]

_METRIC_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def exponential_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` histogram upper bounds growing geometrically from ``start``
    (the +Inf bucket is implicit — every histogram always has it)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


#: Message-latency style buckets: 100 µs .. ~3.3 s, factor 2.
DEFAULT_LATENCY_BUCKETS = exponential_buckets(1e-4, 2.0, 16)
#: Payload-size style buckets: 64 B .. 2 MiB, factor 4.
DEFAULT_SIZE_BUCKETS = exponential_buckets(64.0, 4.0, 9)


class _Child:
    """One labeled sample of a metric. Updates take the parent's lock —
    Python's ``+=`` on a float is not atomic across bytecode boundaries,
    and these update from several node event-loop threads at once."""

    __slots__ = ("_metric", "labels")

    def __init__(self, metric: "_Metric", labels: Tuple[str, ...]):
        self._metric = metric
        self.labels = labels


class _CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, metric, labels):
        super().__init__(metric, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._metric._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._metric._lock:
            return self._value


class _GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, metric, labels):
        super().__init__(metric, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._metric._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._metric._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._metric._lock:
            return self._value


class _HistogramChild(_Child):
    __slots__ = ("counts", "_sum", "_count")

    def __init__(self, metric, labels):
        super().__init__(metric, labels)
        self.counts = [0] * (len(metric.buckets) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        buckets = self._metric.buckets
        i = 0
        while i < len(buckets) and value > buckets[i]:
            i += 1
        with self._metric._lock:
            self.counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def sum(self) -> float:
        with self._metric._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._metric._lock:
            return self._count

    def cumulative(self) -> List[Tuple[float, int]]:
        """``[(upper_bound, cumulative_count)]`` including the +Inf bucket —
        the Prometheus ``_bucket{le=...}`` series."""
        with self._metric._lock:
            counts = list(self.counts)
        out, running = [], 0
        for ub, c in zip(tuple(self._metric.buckets) + (math.inf,), counts):
            running += c
            out.append((ub, running))
        return out


class _Metric:
    """A named metric family: fixed label names, one child per label-value
    tuple. Calling update methods directly on an unlabeled metric routes to
    its single anonymous child."""

    kind = "untyped"
    _child_cls = _Child

    def __init__(self, name: str, help: str, labelnames: Sequence[str]):
        if not name or not set(name) <= _METRIC_NAME_OK or name[0].isdigit():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = concurrency.lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def labels(self, *values, **kv) -> _Child:
        if values and kv:
            raise ValueError("pass label values positionally or by name, not both")
        if kv:
            try:
                values = tuple(str(kv.pop(n)) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(f"{self.name}: missing label {e}") from None
            if kv:
                raise ValueError(f"{self.name}: unknown labels {sorted(kv)}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {values}")
        with self._lock:
            child = self._children.get(values)
        if child is None:
            # Construct OUTSIDE the lock (a subclass child __init__ is
            # foreign code — open-call discipline); setdefault re-checks,
            # so two racing creators agree on one child and the loser's
            # never-published candidate is garbage.
            candidate = self._child_cls(self, values)
            with self._lock:
                child = self._children.setdefault(values, candidate)
        return child

    def _anon(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call .labels() first")
        return self.labels()

    def remove(self, *values, **kv) -> None:
        """Drop one labeled child (same addressing as :meth:`labels`).

        Per-peer children otherwise live for the process lifetime — a
        long-lived node under churn should prune point-in-time gauges for
        departed peers (phi.py does). Counters are usually KEPT so totals
        survive reconnects; prune them only when the label value can never
        recur. No-op if the child does not exist."""
        if kv:
            try:
                values = tuple(str(kv[n]) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(f"{self.name}: missing label {e}") from None
        else:
            values = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(values, None)

    def children(self) -> List[_Child]:
        with self._lock:
            return list(self._children.values())


class Counter(_Metric):
    """Monotonically increasing value (events, bytes, errors)."""

    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._anon().inc(amount)

    @property
    def value(self) -> float:
        return self._anon().value


class Gauge(_Metric):
    """Point-in-time value that can go both ways (connections, suspicion,
    budget bytes)."""

    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._anon().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._anon().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._anon().dec(amount)

    @property
    def value(self) -> float:
        return self._anon().value


class Histogram(_Metric):
    """Distribution over fixed exponential buckets (latencies, frame sizes)."""

    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name, help, labelnames,
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in
                          (DEFAULT_LATENCY_BUCKETS if buckets is None else buckets)))
        if not bs:
            raise ValueError("histogram needs at least one finite bucket")
        if bs and math.isinf(bs[-1]):
            bs = bs[:-1]  # +Inf is implicit
        self.buckets = bs

    def observe(self, value: float) -> None:
        self._anon().observe(value)

    @property
    def sum(self) -> float:
        return self._anon().sum

    @property
    def count(self) -> int:
        return self._anon().count


class Registry:
    """Thread-safe collection of metric families; get-or-create semantics so
    instrumentation sites never race over "who registers first"."""

    def __init__(self):
        self._lock = concurrency.lock()
        self._metrics: Dict[str, _Metric] = {}
        self.created_at = time.time()

    # ----------------------------------------------------------- factories

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
        if m is None:
            # Construct outside the lock — metric __init__ validates and
            # allocates (open-call discipline) — then commit atomically;
            # a racing registrant's candidate loses to setdefault and the
            # shared checks below validate against the winner.
            candidate = cls(name, help, labelnames, **kw)
            with self._lock:
                m = self._metrics.setdefault(name, candidate)
        if not isinstance(m, cls):
            raise ValueError(
                f"{name} already registered as a {m.kind}, not a {cls.kind}")
        if m.labelnames != labelnames:
            raise ValueError(
                f"{name} already registered with labels {m.labelnames}, "
                f"not {labelnames}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # ------------------------------------------------------------ queries

    def collect(self) -> List[_Metric]:
        """All metric families, registration-ordered (dicts preserve it)."""
        with self._lock:
            return list(self._metrics.values())

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, **labels) -> float:
        """Read one sample's current value — the one-liner tests and quick
        checks want. 0.0 for anything that does not resolve to a touched
        child: unknown family, missing/partial/unknown label sets included
        (a typo'd label is an untouched sample, not a crash)."""
        m = self.get(name)
        if m is None:
            return 0.0
        try:
            key = tuple(str(labels[n]) for n in m.labelnames)
        except KeyError:
            return 0.0
        with m._lock:
            child = m._children.get(key)
        if child is None:
            return 0.0
        return child.count if isinstance(child, _HistogramChild) else child.value

    def snapshot(self) -> Dict[str, dict]:
        """Plain-dict view of every family — the in-process API examples and
        tests consume, and the JSON the exporters serialize.

        ``{name: {"type", "help", "labelnames", "samples": [
        {"labels": {...}, "value": ...} |
        {"labels": {...}, "sum": ..., "count": ..., "buckets": {le: n}}]}}``
        """
        out: Dict[str, dict] = {}
        for m in self.collect():
            samples = []
            for child in m.children():
                labels = dict(zip(m.labelnames, child.labels))
                if isinstance(child, _HistogramChild):
                    samples.append({
                        "labels": labels,
                        "sum": child.sum,
                        "count": child.count,
                        "buckets": {("+Inf" if math.isinf(ub) else repr(ub)): c
                                    for ub, c in child.cumulative()},
                    })
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "labelnames": list(m.labelnames), "samples": samples}
        return out

    def clear(self) -> None:
        """Drop every registered family (test isolation)."""
        with self._lock:
            self._metrics.clear()


_default = Registry()
_default_lock = concurrency.lock()


def default_registry() -> Registry:
    """The process-wide registry every instrumentation site reports to
    unless handed an explicit one."""
    # Read under the same lock that guards the swap: a torn read is not
    # actually possible for one reference, but the asymmetric discipline
    # (guarded write, bare read) is exactly what rots under refactoring —
    # and what graftlint's lock-guard rule flags.
    with _default_lock:
        return _default


def set_default_registry(registry: Registry) -> Registry:
    """Swap the process-wide registry, returning the previous one (tests
    isolate by swapping in a fresh Registry and restoring after)."""
    global _default
    with _default_lock:
        prev, _default = _default, registry
    return prev
