"""graftsight SLO engine: declarative objectives, rolling windows,
multi-window burn-rate alerts.

ROADMAP item 2 names a p99 submit->completion SLO; this module is the
instrument that measures one. An :class:`Objective` declares what
"good" means for one observation stream (``completion_rounds <= 24``,
``shed == 0``, ...) and what fraction of observations must be good
(``goal=0.99`` is a p99 objective: 99% of completions within target).
The :class:`SLOEngine` is fed raw observations (:meth:`SLOEngine.record`
— the serve driver feeds per-ticket completion rounds/wall and
per-submission shed flags, per-tick heal flags) and evaluated once per
driver tick (:meth:`SLOEngine.evaluate`).

Burn rate is the standard SRE quantity: the fraction of the error
budget (``1 - goal``) consumed per unit, so ``burn == 1.0`` means
"exactly on budget" and ``burn == 10`` means "burning budget 10x too
fast". Alerts are MULTI-WINDOW: an objective fires only when both the
fast window (responsive, flappy alone) and the slow window (stable,
laggy alone) burn at or above ``burn_threshold`` — the classic
two-window page condition. Transitions (fire/resolve) are emitted as
structured :class:`~p2pnetwork_tpu.utils.logging.EventLog` records
(the shared JSONL schema via ``to_jsonl``) and counted in
``slo_alerts_total``; the current burn rides the ``slo_burn_rate``
gauge per (objective, window) so the history ring and ``/dashboard``
can plot it.

Windows are counted in OBSERVATIONS, not wall seconds: evaluation is a
pure function of the fed values, so a seeded serve run evaluates
identically every replay — which is what lets AIMD admission consume a
firing objective (``admission_signal=True``) as an explicit,
deterministic backpressure signal (serve/service.py) without breaking
the serving plane's bit-identity contract. Wall-clock objectives
(``completion_wall_s``) are observability-only and must keep
``admission_signal=False``.

Stdlib-only, like the rest of the telemetry plane.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.telemetry.registry import Registry, default_registry
from p2pnetwork_tpu.utils.logging import EventLog

__all__ = ["Objective", "SLOEngine", "serve_objectives"]


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative service-level objective.

    ``metric`` names the observation stream this objective judges;
    an observation is GOOD when ``value <= target`` (``mode="le"``) or
    ``value >= target`` (``mode="ge"``). ``goal`` is the required good
    fraction (0.99 = p99). ``fast_window``/``slow_window`` are rolling
    window lengths in observations; the alert condition is burn >=
    ``burn_threshold`` in BOTH windows at once. ``admission_signal``
    marks the objective as safe for AIMD admission to act on — only
    set it on objectives whose observations are deterministic under
    seeded replay (rounds, shed flags), never wall-clock ones."""

    name: str
    metric: str
    target: float
    mode: str = "le"
    goal: float = 0.99
    fast_window: int = 16
    slow_window: int = 64
    burn_threshold: float = 2.0
    admission_signal: bool = False

    def __post_init__(self):
        if self.mode not in ("le", "ge"):
            raise ValueError(f"mode must be 'le' or 'ge', got {self.mode!r}")
        if not 0.0 < self.goal < 1.0:
            raise ValueError(f"goal must be in (0, 1), got {self.goal}")
        if self.fast_window < 1 or self.slow_window < self.fast_window:
            raise ValueError(
                f"need 1 <= fast_window <= slow_window, got "
                f"{self.fast_window}/{self.slow_window}")
        if self.burn_threshold <= 0.0:
            raise ValueError(
                f"burn_threshold must be > 0, got {self.burn_threshold}")

    def good(self, value: float) -> bool:
        return value <= self.target if self.mode == "le" \
            else value >= self.target

    def spec(self) -> dict:
        """The declaration as a plain dict (what /dashboard embeds)."""
        return dataclasses.asdict(self)


def serve_objectives(slo_rounds: float, wall_s: Optional[float] = None,
                     shed_goal: float = 0.95,
                     heal_goal: float = 0.90,
                     durability_goal: Optional[float] = None,
                     ) -> Tuple[Objective, ...]:
    """The default graftserve objective set: p99 completion rounds
    (deterministic — the one AIMD may act on), optional p99 completion
    wall latency (observability-only), shed rate, heal rate.

    ``durability_goal`` (opt-in, graftdur) appends a ``durability``
    objective over the service's per-tick durability stream (1.0 while
    the journal is failed / the service sheds ``DurabilityLost``, else
    0.0): a goal of e.g. 0.999 alerts when more than 0.1% of recent
    ticks ran without a working write-ahead journal. Deterministic
    (tick-derived), but observability-only by default — degraded
    durability should page an operator, not throttle admission of the
    work that IS still journalable."""
    objs = [
        Objective("completion_p99_rounds", metric="completion_rounds",
                  target=float(slo_rounds), mode="le", goal=0.99,
                  admission_signal=True),
        Objective("shed_rate", metric="shed", target=0.0, mode="le",
                  goal=shed_goal),
        Objective("heal_rate", metric="heal", target=0.0, mode="le",
                  goal=heal_goal),
    ]
    if wall_s is not None:
        objs.insert(1, Objective("completion_p99_wall_s",
                                 metric="completion_wall_s",
                                 target=float(wall_s), mode="le", goal=0.99))
    if durability_goal is not None:
        objs.append(Objective("durability", metric="durability",
                              target=0.0, mode="le",
                              goal=float(durability_goal)))
    return tuple(objs)


class SLOEngine:
    """Evaluate a set of :class:`Objective`\\ s over rolling windows.

    Thread-safe: :meth:`record` may be called from submitter threads
    while the driver calls :meth:`evaluate`; observation rings and
    firing state serialize on one lock, and gauge writes happen outside
    it (open-call discipline). Alert records land in ``self.log`` (an
    :class:`EventLog`; pass one in to share a stream) as
    ``slo_alert`` events with the full burn context in ``data``."""

    def __init__(self, objectives: Iterable[Objective],
                 registry: Optional[Registry] = None,
                 log: Optional[EventLog] = None):
        self.objectives: Tuple[Objective, ...] = tuple(objectives)
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        self.log = log if log is not None else EventLog()
        reg = registry if registry is not None else default_registry()
        self._g_burn = reg.gauge(
            "slo_burn_rate",
            "error-budget burn rate per objective and window "
            "(1.0 = exactly on budget)", ("objective", "window"))
        self._g_firing = reg.gauge(
            "slo_firing", "1 while the objective's multi-window burn "
            "alert is firing, else 0", ("objective",))
        self._c_alerts = reg.counter(
            "slo_alerts_total", "burn-rate alert transitions",
            ("objective", "transition"))
        self._lock = concurrency.lock()
        # One bounded ring per observation stream, sized by the widest
        # window that judges it.
        window_by_metric: Dict[str, int] = {}
        for o in self.objectives:
            window_by_metric[o.metric] = max(
                window_by_metric.get(o.metric, 0), o.slow_window)
        self._obs: Dict[str, collections.deque] = {
            m: collections.deque(maxlen=w)
            for m, w in window_by_metric.items()}
        self._firing: Dict[str, bool] = {o.name: False
                                         for o in self.objectives}
        self._last: Dict[str, dict] = {}

    # ------------------------------------------------------------- feeding

    def record(self, metric: str, value: float) -> None:
        """Feed one observation. Streams no objective judges are
        dropped (instrumentation may feed generously)."""
        with self._lock:
            ring = self._obs.get(metric)
            if ring is not None:
                ring.append(float(value))

    # ---------------------------------------------------------- evaluating

    @staticmethod
    def _burn(values: Sequence[float], obj: Objective) -> float:
        if not values:
            return 0.0
        bad = sum(0 if obj.good(v) else 1 for v in values)
        return (bad / len(values)) / (1.0 - obj.goal)

    def evaluate(self, tick: int = -1) -> Dict[str, dict]:
        """Evaluate every objective against its current windows; update
        the gauges; emit fire/resolve transitions. Returns (and caches,
        for :meth:`snapshot`) per-objective state dicts. Pure in the
        fed observations — identical feeds give identical verdicts."""
        states: Dict[str, dict] = {}
        transitions: List[Tuple[Objective, bool, dict]] = []
        # Copy the observation rings under the lock, judge them outside
        # it (open-call discipline: ``Objective.good`` is app-providable
        # code and must not run inside the engine's critical section).
        with self._lock:
            obs = {m: list(ring) for m, ring in self._obs.items()}
        for obj in self.objectives:
            values = obs.get(obj.metric, [])
            slow = values[-obj.slow_window:]
            fast = values[-obj.fast_window:]
            burn_fast = self._burn(fast, obj)
            burn_slow = self._burn(slow, obj)
            good = sum(1 for v in slow if obj.good(v))
            # No verdict before one full fast window: a single bad
            # first observation must not page.
            warmed = len(values) >= obj.fast_window
            firing = bool(warmed
                          and burn_fast >= obj.burn_threshold
                          and burn_slow >= obj.burn_threshold)
            states[obj.name] = {
                "metric": obj.metric,
                "target": obj.target,
                "mode": obj.mode,
                "goal": obj.goal,
                "burn_fast": burn_fast,
                "burn_slow": burn_slow,
                "burn_threshold": obj.burn_threshold,
                "good_ratio": (good / len(slow)) if slow else 1.0,
                "samples": len(slow),
                "firing": firing,
                "admission_signal": obj.admission_signal,
                "tick": tick,
            }
        with self._lock:
            for obj in self.objectives:
                state = states[obj.name]
                if state["firing"] != self._firing[obj.name]:
                    self._firing[obj.name] = state["firing"]
                    transitions.append((obj, state["firing"], dict(state)))
            self._last = states
        # Metric writes and EventLog records outside the engine lock
        # (both take their own locks).
        for obj in self.objectives:
            st = states[obj.name]
            self._g_burn.labels(obj.name, "fast").set(st["burn_fast"])
            self._g_burn.labels(obj.name, "slow").set(st["burn_slow"])
            self._g_firing.labels(obj.name).set(1.0 if st["firing"] else 0.0)
        for obj, firing, state in transitions:
            kind = "fire" if firing else "resolve"
            self._c_alerts.labels(obj.name, kind).inc()
            self.log.record("slo_alert", None,
                            {"objective": obj.name, "transition": kind,
                             **state})
        return states

    # ------------------------------------------------------------- reading

    def firing(self, admission_only: bool = False) -> List[str]:
        """Names of currently-firing objectives (as of the last
        :meth:`evaluate`); ``admission_only`` keeps just the ones AIMD
        admission is allowed to act on."""
        with self._lock:
            last = dict(self._last)
        by_name = {o.name: o for o in self.objectives}
        return [n for n, st in last.items()
                if st["firing"] and (not admission_only
                                     or by_name[n].admission_signal)]

    def snapshot(self) -> dict:
        """JSON-able engine state for ``/dashboard``: every objective's
        declaration + last evaluation, plus recent alert records."""
        with self._lock:
            last = {n: dict(st) for n, st in self._last.items()}
        objectives = {}
        for obj in self.objectives:
            st = last.get(obj.name, {
                "burn_fast": 0.0, "burn_slow": 0.0, "good_ratio": 1.0,
                "samples": 0, "firing": False, "tick": -1})
            objectives[obj.name] = {**obj.spec(), **st}
        alerts = [{"event": r.event, "timestamp": r.timestamp,
                   "data": r.data}
                  for r in self.log.snapshot() if r.event == "slo_alert"]
        return {"objectives": objectives, "alerts": alerts[-32:]}
