"""Dijkstra–Scholten termination detection over the sockets backend.

"Has the computation I started actually FINISHED everywhere?" — the
question every diffusing protocol (flood, query fan-out, recursive
lookup) built on the reference's ``node_message`` cannot answer: silence
is indistinguishable from in-flight work [ref: p2pnetwork/node.py:334 —
fire-and-forget delivery, no acknowledgements anywhere]. The classic
answer for diffusing computations is Dijkstra–Scholten (1980): grow a
spanning tree of "engagements" as the work spreads, retire leaves as
they go quiet, and when the tree has collapsed back into the root the
root KNOWS the whole computation — every message included — is done.

:class:`TerminationNode` runs the accounting under an app-defined
computation:

- the root calls :meth:`start_diffusing` (becoming its own engager);
- work moves with :meth:`send_work` (inside :meth:`work_message`
  handlers or from the root) — each send adds to the sender's deficit;
- an idle node's first work message ENGAGES it (that sender becomes its
  parent in the detection tree); any other work message is acknowledged
  immediately;
- a node acknowledges its ENGAGER only once it is passive (its
  ``work_message`` handler returned) with zero deficit (all its own
  sends acknowledged) — detaching from the tree;
- when the ROOT's deficit reaches zero, :meth:`computation_terminated`
  fires: a true global claim, not a timeout heuristic.

The handler-scoped activity model keeps the bookkeeping deterministic:
a node is active exactly while its ``work_message`` handler runs on the
event loop, so "passive" needs no app signal — long-lived local work
should re-enter through self-addressed messages rather than blocking
the loop. Multiple concurrent computations are tracked per root id.

Honest limits: like the algorithm, this assumes reliable channels —
a peer crashing mid-computation orphans its subtree's acknowledgements
and the root waits forever (``deficit()`` exposes the stuck count;
pair with the reconnect machinery or a SnapshotNode cut to diagnose).
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, Optional

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.nodeconnection import NodeConnection

WORK_KEY = "_ds_work"  # envelope: {_ds_work: comp_id, payload: ...}
ACK_KEY = "_ds_ack"  # envelope: {_ds_ack: comp_id}


class _Comp:
    """Per-computation detection state on one node."""

    __slots__ = ("engager", "deficit", "is_root")

    def __init__(self, engager: Optional[NodeConnection], is_root: bool):
        self.engager = engager  # None for the root
        self.deficit = 0  # our sends not yet acknowledged
        self.is_root = is_root


class TerminationNode(Node):
    """A :class:`Node` that detects termination of diffusing computations."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Mutated only on the event loop.
        self._comps: Dict[str, _Comp] = {}
        self._active_comp: Optional[str] = None  # set while handler runs
        # Root-side id ledger, reserved SYNCHRONOUSLY in start_diffusing:
        # checking _comps alone races the posted closure that creates the
        # entry (a second start_diffusing can sneak in before the loop
        # runs the first), so the reservation must happen caller-side.
        self._cids_used: set = set()
        self._cid_lock = concurrency.lock()
        # Local-completion events, creatable from ANY thread (setdefault
        # under the GIL): wait_terminated must work even before the
        # posted start_diffusing closure has created the comp entry.
        self._term_events: Dict[str, threading.Event] = {}

    # ------------------------------------------------------------ app API

    def work_message(self, node: Optional[NodeConnection], comp_id: str,
                     data) -> None:
        """Application work arrived (or, at the root, the computation
        starts — then ``node`` is None). Override me; the node is ACTIVE
        for this computation exactly while this handler runs, and
        :meth:`send_work` calls made here are charged to it."""
        self.debug_print(f"work_message: {comp_id}: {data!r}")
        self._dispatch("work_message", node, {"comp_id": comp_id,
                                              "data": data})

    def computation_terminated(self, comp_id: str) -> None:
        """The ROOT's detection fired: every work message of ``comp_id``
        has been processed and acknowledged, globally."""
        self.debug_print(f"computation_terminated: {comp_id}")
        self._dispatch("computation_terminated", None, {"comp_id": comp_id})

    def start_diffusing(self, data, comp_id: Optional[str] = None) -> str:
        """Become the root of a new diffusing computation: run
        :meth:`work_message` locally (whose sends seed the spread).
        Thread-safe; returns the computation id."""
        cid = comp_id if comp_id is not None else uuid.uuid4().hex
        loop = self._loop
        if loop is None or loop.is_closed():
            raise RuntimeError("node is not running — call start() first")

        # Eager, caller-visible rejection: raised inside the posted
        # closure it would vanish into asyncio's exception handler and
        # the caller would mistake the OLD run's completion for this
        # one's. The reservation is synchronous (lock-guarded ledger) so
        # two back-to-back calls cannot both pass before the loop runs.
        with self._cid_lock:
            # All three ledgers matter: _cids_used catches root-side
            # reuse racing the posted closure; _comps catches an id this
            # node is currently ENGAGED in as a non-root (rooting it too
            # would clobber the engagement and orphan the real root's
            # ack); _term_events catches an id we already detached from
            # (its set event would make wait_terminated lie about the
            # new run).
            if (cid in self._cids_used or cid in self._comps
                    or cid in self._term_events):
                raise ValueError(f"computation id {cid!r} already used")
            self._cids_used.add(cid)

        def _do():
            if cid in self._comps:
                return  # engaged via marker since the reservation — the
                #         engagement wins; rooting would clobber it
            self._comps[cid] = _Comp(engager=None, is_root=True)
            self._run_handler(None, cid, data)

        loop.call_soon_threadsafe(_do)
        return cid

    def send_work(self, n: NodeConnection, data,
                  comp_id: Optional[str] = None) -> None:
        """Send one unit of work to peer ``n`` under a computation. Inside
        a :meth:`work_message` handler the computation is implied;
        ``comp_id`` is for other EVENT-LOOP code (another handler, a
        scheduled callback). Must run on the node's loop — a foreign
        thread bumping ``deficit`` would race ``_maybe_detach`` and could
        fire a FALSE termination while its message is still in flight,
        so the root seeds the spread from its own ``work_message``."""
        if threading.current_thread() is not self:
            raise RuntimeError(
                "send_work must run on the node's event loop (e.g. inside "
                "a work_message handler)")
        cid = comp_id if comp_id is not None else self._active_comp
        if cid is None:
            raise RuntimeError("send_work outside a work_message handler "
                               "needs an explicit comp_id")
        comp = self._comps.get(cid)
        if comp is None:
            raise RuntimeError(f"unknown computation {cid!r}")
        comp.deficit += 1
        self.send_to_node(n, {WORK_KEY: cid, "payload": data})

    def deficit(self, comp_id: str) -> int:
        """Outstanding unacknowledged sends for a computation (0 after
        local detach; at the root, 0 means terminated)."""
        comp = self._comps.get(comp_id)
        return 0 if comp is None else comp.deficit

    def wait_terminated(self, comp_id: str,
                        timeout: Optional[float] = None) -> bool:
        """Block until this node DETACHES from ``comp_id`` — at the root,
        that is global termination — or ``timeout`` elapses (False).

        Completed ids stay on record; a long-lived node launching
        unbounded computations should :meth:`forget_computation` ids it
        is done asking about (that also releases them for reuse)."""
        return self._term_events.setdefault(
            comp_id, concurrency.event()).wait(timeout)

    def forget_computation(self, comp_id: str) -> None:
        """Release the completion record of a finished computation (and
        allow the id's reuse). No-op while it is still running."""
        if comp_id not in self._comps:
            self._term_events.pop(comp_id, None)
            with self._cid_lock:
                self._cids_used.discard(comp_id)

    # ------------------------------------------------------ the machinery

    def _run_handler(self, node: Optional[NodeConnection], cid: str,
                     data) -> None:
        prev, self._active_comp = self._active_comp, cid
        try:
            self.work_message(node, cid, data)
        finally:
            self._active_comp = prev
        self._maybe_detach(cid)

    def _maybe_detach(self, cid: str) -> None:
        comp = self._comps.get(cid)
        if comp is None or comp.deficit > 0:
            return
        # Passive (no handler running for cid here — we only get called
        # after handlers return or acks arrive) with zero deficit.
        if comp.is_root:
            del self._comps[cid]
            self._term_events.setdefault(cid, concurrency.event()).set()
            self.computation_terminated(cid)
        elif comp.engager is not None:
            engager, comp.engager = comp.engager, None
            del self._comps[cid]
            self._term_events.setdefault(cid, concurrency.event()).set()
            self.send_to_node(engager, {ACK_KEY: cid})

    def _on_work(self, node: NodeConnection, cid: str, payload) -> None:
        comp = self._comps.get(cid)
        if comp is None:
            # First contact: this sender engages us into the tree. Its
            # ack is deferred until we detach.
            self._comps[cid] = _Comp(engager=node, is_root=False)
            self._run_handler(node, cid, payload)
        else:
            # Already engaged: process, then ack this message right away.
            self._run_handler(node, cid, payload)
            self.send_to_node(node, {ACK_KEY: cid})

    def _on_ack(self, node: NodeConnection, cid: str) -> None:
        comp = self._comps.get(cid)
        if comp is None or comp.deficit <= 0:
            return  # stray ack (e.g. from a computation we detached)
        comp.deficit -= 1
        self._maybe_detach(cid)

    # ------------------------------------------------------ interception

    def node_message(self, node: NodeConnection, data) -> None:
        if isinstance(data, dict):
            if WORK_KEY in data:
                self._on_work(node, data[WORK_KEY], data.get("payload"))
                return
            if ACK_KEY in data:
                self._on_ack(node, data[ACK_KEY])
                return
        super().node_message(node, data)
