"""Hop-distance (BFS layers) from a source node.

The discovery measurement behind every overlay-health question reference
users answer by hand-instrumenting ``node_message`` hops [ref:
README.md:20]: how many forwarding steps does a message need to reach each
peer? One synchronous round is the same masked frontier-OR as flooding
(``propagate_or``, the batched form of the reference's per-edge send loop
[ref: p2pnetwork/node.py:110-112]); nodes record the round number at which
the wave first reaches them. The final state is the exact BFS hop count
per node (-1 for unreachable), so eccentricity / diameter / reachability
drop out as device-side reductions.

Deterministic — no RNG consumed; exposes ``coverage`` + ``messages`` stats,
so :func:`p2pnetwork_tpu.sim.engine.run_until_coverage` runs it to any
reach fraction with the device-side early-exit loop.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HopDistanceState:
    dist: jax.Array  # i32[N_pad] — BFS hops from source, -1 = not reached
    frontier: jax.Array  # bool[N_pad] — nodes first reached last round
    round: jax.Array  # i32[] — rounds executed so far


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class HopDistance:
    """Single-source BFS hop counts. ``source`` is the seed node index."""

    source: int = 0
    method: str = "auto"  # aggregation lowering, see ops/segment.py

    def init(self, graph: Graph, key: jax.Array) -> HopDistanceState:
        base.validate_source(graph, self.source)
        seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[self.source].set(True)
        seed = seed & graph.node_mask
        dist = jnp.where(seed, 0, -1).astype(jnp.int32)
        return HopDistanceState(dist=dist, frontier=seed,
                                round=jnp.int32(0))

    def coverage(self, graph: Graph, state: HopDistanceState) -> jax.Array:
        """Reached fraction of live nodes (run_until_coverage resume seed)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum((state.dist >= 0) & graph.node_mask) / n_real

    def step(self, graph: Graph, state: HopDistanceState, key: jax.Array):
        delivered = segment.propagate_or(graph, state.frontier, self.method)
        new = delivered & (state.dist < 0) & graph.node_mask
        rnd = state.round + 1
        dist = jnp.where(new, rnd, state.dist)
        reached = (dist >= 0) & graph.node_mask
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": segment.frontier_messages(graph, state.frontier),
            "coverage": jnp.sum(reached) / n_real,
            "frontier": jnp.sum(new),
            # Farthest hop seen so far — the source's eccentricity once the
            # wave dies out (frontier == 0).
            "max_dist": jnp.max(dist),
        }
        return HopDistanceState(dist=dist, frontier=new, round=rnd), stats
