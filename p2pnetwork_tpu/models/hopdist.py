"""Hop-distance (BFS layers) from a source node.

The discovery measurement behind every overlay-health question reference
users answer by hand-instrumenting ``node_message`` hops [ref:
README.md:20]: how many forwarding steps does a message need to reach each
peer? One synchronous round is the same masked frontier-OR as flooding
(``propagate_or``, the batched form of the reference's per-edge send loop
[ref: p2pnetwork/node.py:110-112]); nodes record the round number at which
the wave first reaches them. The final state is the exact BFS hop count
per node (-1 for unreachable), so eccentricity / diameter / reachability
drop out as device-side reductions.

Deterministic — no RNG consumed; exposes ``coverage`` + ``messages`` stats,
so :func:`p2pnetwork_tpu.sim.engine.run_until_coverage` runs it to any
reach fraction with the device-side early-exit loop.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HopDistanceState:
    dist: jax.Array  # i32[N_pad] — BFS hops from source, -1 = not reached
    frontier: jax.Array  # bool[N_pad] — nodes first reached last round
    round: jax.Array  # i32[] — rounds executed so far


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class HopDistance:
    """Single-source BFS hop counts. ``source`` is the seed node index."""

    source: int = 0
    method: str = "auto"  # aggregation lowering, see ops/segment.py

    def init(self, graph: Graph, key: jax.Array) -> HopDistanceState:
        base.validate_source(graph, self.source)
        seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[self.source].set(True)
        seed = seed & graph.node_mask
        dist = jnp.where(seed, 0, -1).astype(jnp.int32)
        return HopDistanceState(dist=dist, frontier=seed,
                                round=jnp.int32(0))

    def coverage(self, graph: Graph, state: HopDistanceState) -> jax.Array:
        """Reached fraction of live nodes (run_until_coverage resume seed)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum((state.dist >= 0) & graph.node_mask) / n_real

    def step(self, graph: Graph, state: HopDistanceState, key: jax.Array):
        delivered = segment.propagate_or(graph, state.frontier, self.method)
        new = delivered & (state.dist < 0) & graph.node_mask
        rnd = state.round + 1
        dist = jnp.where(new, rnd, state.dist)
        reached = (dist >= 0) & graph.node_mask
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": segment.frontier_messages(graph, state.frontier),
            "coverage": jnp.sum(reached) / n_real,
            "frontier": jnp.sum(new),
            # Farthest hop seen so far — the source's eccentricity once the
            # wave dies out (frontier == 0).
            "max_dist": jnp.max(dist),
        }
        return HopDistanceState(dist=dist, frontier=new, round=rnd), stats


@functools.partial(jax.jit, static_argnames=("method",))
def bfs_distances(graph: Graph, src, method: str = "auto") -> jax.Array:
    """Single-source BFS distance field ``i32[N_pad]`` (-1 unreached),
    run as one device-side ``while_loop`` — THE masked wave shared by
    :func:`eccentricities` and models/centrality.py's closeness (one
    implementation, so a masking fix lands on all of them)."""
    n_pad = graph.n_nodes_padded
    seed = jnp.zeros(n_pad, dtype=bool).at[src].set(True)
    seed = seed & graph.node_mask
    dist0 = jnp.where(seed, 0, -1).astype(jnp.int32)

    def cond(carry):
        _, frontier, _ = carry
        return jnp.any(frontier)

    def body(carry):
        dist, frontier, rnd = carry
        delivered = segment.propagate_or(graph, frontier, method)
        new = delivered & (dist < 0) & graph.node_mask
        return jnp.where(new, rnd + 1, dist), new, rnd + 1

    dist, _, _ = jax.lax.while_loop(cond, body, (dist0, seed, jnp.int32(0)))
    return dist


@functools.partial(jax.jit, static_argnames=("method",))
def eccentricities(graph: Graph, sources: jax.Array,
                   method: str = "auto"):
    """Batched exact eccentricities: one full BFS per source, run as
    ``lax.map`` over sequential device-side ``while_loop``s (one XLA
    program, no host round trips, peak memory one wave).

    Returns ``(ecc, reached)``, both ``i32[S]``: the farthest hop from
    each source within its component, and how many live nodes its wave
    touched (``ecc`` is -1 for a dead source). The batched form of
    reading ``stats["max_dist"]`` off a finished :class:`HopDistance`
    run, for the multi-source sweeps diameter estimation wants.
    """
    sources = jnp.asarray(sources, dtype=jnp.int32)

    def one(src):
        dist = bfs_distances(graph, src, method)
        reached = (dist >= 0) & graph.node_mask
        return jnp.max(dist), jnp.sum(reached, dtype=jnp.int32)

    return jax.lax.map(one, sources)


def diameter_bounds(graph: Graph, key: jax.Array, samples: int = 16,
                    method: str = "auto"):
    """Classical sampled diameter bracket: from any vertex ``v``,
    ``ecc(v) <= diameter <= 2 * ecc(v)`` (triangle inequality through
    ``v``), so over a sample the tightest bracket is
    ``[max ecc, 2 * min ecc]``.

    Returns ``dict(lower, upper, radius_upper, connected)`` as Python
    scalars — ``radius_upper`` is the smallest sampled eccentricity and
    ``connected`` whether every sampled wave reached all live nodes (the
    bracket only brackets the sampled component's diameter otherwise).
    Sources are drawn uniformly from live nodes.
    """
    import numpy as np

    alive = np.flatnonzero(np.asarray(graph.node_mask))
    if alive.size == 0:
        return {"lower": 0, "upper": 0, "radius_upper": 0, "connected": False}
    picks = jax.random.choice(key, jnp.asarray(alive, dtype=jnp.int32),
                              shape=(min(samples, alive.size),),
                              replace=False)
    ecc, reached = eccentricities(graph, picks, method)
    ecc = np.asarray(ecc)
    reached = np.asarray(reached)
    return {
        "lower": int(ecc.max()),
        "upper": int(2 * ecc.min()),
        "radius_upper": int(ecc.min()),
        "connected": bool((reached == alive.size).all()),
    }
