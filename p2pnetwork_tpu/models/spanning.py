"""Spanning-tree construction over the flood wave.

Reference users who outgrow naive flooding build a broadcast TREE on the
hooks: remember who you first heard a message from, forward only down-tree
afterwards [ref: README.md:20 — protocols are the user's job]. This
protocol is that construction, batched: the BFS wave expands exactly like
models/flood.py, and every newly reached node records a PARENT — the
highest-id frontier neighbor that delivered this round (deterministic,
no RNG). The result is a rooted spanning tree of the source's reachable
component: ``parent[source] == source``, every other reached node's
parent sits one hop closer to the source.

The parent choice rides :func:`ops.segment.propagate_max` over the
frontier's ids — one masked neighbor-max per round, no gather of edge
endpoints, no atomics; exactly the aggregation the leader election uses,
pointed at a different question.

Stats contract: ``messages`` (flood accounting), ``coverage`` (reached
fraction of live nodes — run_until_coverage works), ``frontier``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SpanningTreeState:
    parent: jax.Array  # i32[N_pad] — -1 until reached; parent[source]=source
    frontier: jax.Array  # bool[N_pad] — reached last round
    dist: jax.Array  # i32[N_pad] — hops from source, -1 until reached
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class SpanningTree:
    """BFS spanning tree from ``source``; parents picked as the highest-id
    delivering neighbor. ``method`` as in ops/segment.propagate_max
    (``"segment"``/``"gather"``/``"auto"``)."""

    source: int = 0
    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> SpanningTreeState:
        base.validate_source(graph, self.source)
        seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[
            self.source].set(True)
        seed = seed & graph.node_mask
        parent = jnp.where(seed, self.source, -1).astype(jnp.int32)
        return SpanningTreeState(
            parent=parent, frontier=seed,
            dist=jnp.where(seed, 0, -1).astype(jnp.int32),
            round=jnp.int32(0),
        )

    def coverage(self, graph: Graph, state: SpanningTreeState) -> jax.Array:
        """Reached fraction of live nodes."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum((state.parent >= 0) & graph.node_mask) / n_real

    def step(self, graph: Graph, state: SpanningTreeState, key: jax.Array):
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        neutral = segment.neutral_min(jnp.int32)
        # Frontier nodes offer their id; each unreached receiver adopts
        # the highest offer as its parent — one neighbor-max per round.
        offer = jnp.where(state.frontier & graph.node_mask, ids, neutral)
        best = segment.propagate_max(graph, offer, self.method)
        newly = (best >= 0) & (state.parent < 0) & graph.node_mask
        rnd = state.round + 1
        parent = jnp.where(newly, best, state.parent)
        dist = jnp.where(newly, rnd, state.dist)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": segment.frontier_messages(
                graph, state.frontier & graph.node_mask),
            "coverage": jnp.sum((parent >= 0) & graph.node_mask) / n_real,
            "frontier": jnp.sum(newly),
        }
        return SpanningTreeState(parent=parent, frontier=newly, dist=dist,
                                 round=rnd), stats
