"""Push-sum (weighted) average consensus.

The mass-conserving cousin of :mod:`p2pnetwork_tpu.models.gossip` — the
other classic aggregate protocol reference users hand-roll on
``node_message`` [ref: README.md:20]: every node holds a value and wants
the network-wide mean without any coordinator. Unlike pairwise gossip,
push-sum (Kempe–Dobra–Gehrke) keeps TWO channels, a value mass ``s`` and a
weight mass ``w``; each round every node splits both masses equally over
itself and its out-neighbors and broadcasts the shares. ``s/w`` converges
to the true mean on any strongly-connected graph, and the invariants

    sum(s) == sum(initial values)        sum(w) == N

hold EXACTLY at every round — the deterministic, testable replacement for
the reference's "eventually everyone knows" socket choreography.

One synchronous round of the whole population is two ``propagate_sum``
calls over the edge set (the same batched aggregation that replaces the
reference's per-edge send loop [ref: p2pnetwork/node.py:110-112]); there is
no per-node randomness, so a run is a pure function of (graph, init key).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PushSumState:
    s: jax.Array  # f32[N_pad] — value mass
    w: jax.Array  # f32[N_pad] — weight mass


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class PushSum:
    """Mass-splitting average consensus. The per-node estimate is ``s/w``."""

    method: str = "auto"  # aggregation lowering, see ops/segment.py

    def init(self, graph: Graph, key: jax.Array) -> PushSumState:
        values = jax.random.normal(key, (graph.n_nodes_padded,),
                                   dtype=jnp.float32)
        mask = graph.node_mask
        return PushSumState(s=values * mask, w=mask.astype(jnp.float32))

    def estimate(self, graph: Graph, state: PushSumState) -> jax.Array:
        """Per-node mean estimate ``s/w`` (0 on dead/padded nodes)."""
        return jnp.where(state.w > 0, state.s / jnp.maximum(state.w, 1e-30), 0.0)

    def step(self, graph: Graph, state: PushSumState, key: jax.Array):
        mask_f = graph.node_mask.astype(jnp.float32)
        # Each node splits its mass into (out_degree + 1) equal shares: one
        # kept, one sent along every outgoing edge. Sinks (out_degree 0 —
        # isolated or all-links-failed nodes) keep everything.
        shares = 1.0 / (graph.out_degree.astype(jnp.float32) + 1.0)
        s_share = state.s * shares
        w_share = state.w * shares
        s = (s_share + segment.propagate_sum(graph, s_share, self.method)) * mask_f
        w = (w_share + segment.propagate_sum(graph, w_share, self.method)) * mask_f

        est = jnp.where(w > 0, s / jnp.maximum(w, 1e-30), 0.0)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        mean = jnp.sum(est * mask_f) / n_real
        var = jnp.sum(jnp.where(graph.node_mask, (est - mean) ** 2, 0.0)) / n_real
        stats = {
            # One share sent per outgoing edge of every live node — the
            # message-count parity metric [ref: node.py:110-116].
            "messages": segment.frontier_messages(graph, graph.node_mask),
            # Conservation observables (exact up to f32 rounding): the sum
            # of s never moves, the sum of w stays N.
            "s_total": jnp.sum(s),
            "w_total": jnp.sum(w),
            "variance": var,
            "mean": mean,
        }
        return PushSumState(s=s, w=w), stats
