"""Luby's maximal-independent-set protocol (randomized symmetry breaking).

The classic building block for decentralized scheduling/clustering that
reference users would hand-write on the event hooks [ref: README.md:20]:
each undecided node draws a random priority and broadcasts it; a node
whose draw strictly beats every undecided neighbor's joins the set and
announces; the announcers' neighbors drop out of contention. Expected
O(log n) rounds to decide everyone (Luby, SIAM J. Comput. 1986).

One protocol round = one batched draw (`jax.random.randint` from the
engine's per-round key) + one `propagate_max` of priorities over the
undecided subgraph + one `propagate_or` of the join announcements. Ties
(identical int32 draws between neighbors) leave both undecided for the
round — correctness is unaffected, the pair re-draws next round.

Independence of the result assumes the overlay is symmetric (every
builder in sim/graph.py produces undirected edge sets): a strictly
one-way edge lets the tail join without the head ever hearing it. The
tests pin independence + maximality on the symmetric family.

Run with ``engine.run_until_converged(..., stat="undecided",
threshold=1)``; at quiescence ``state.in_mis`` is the set.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LubyMISState:
    in_mis: jax.Array  # bool[N_pad] — decided: member of the set
    undecided: jax.Array  # bool[N_pad] — still in contention


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class LubyMIS:
    """Randomized MIS. ``method`` picks the max-aggregation lowering
    (``"auto"``/``"segment"``/``"gather"`` — ops/segment.propagate_max);
    ``or_method`` the announcement lowering (propagate_or's choices)."""

    method: str = "auto"
    or_method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> LubyMISState:
        dead = jnp.zeros(graph.n_nodes_padded, dtype=bool)
        return LubyMISState(in_mis=dead, undecided=graph.node_mask)

    def step(self, graph: Graph, state: LubyMISState, key: jax.Array):
        undecided = state.undecided
        # Per-round priorities; decided/dead nodes carry the max-identity
        # so they never outrank anyone.
        draws = jax.random.randint(key, undecided.shape, 0,
                                   jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
        neutral = segment.neutral_min(draws.dtype)
        prio = jnp.where(undecided, draws, neutral)
        heard = segment.propagate_max(graph, prio, self.method)
        join = undecided & (prio > heard)
        # Winners announce; their neighbors leave contention.
        lost = segment.propagate_or(graph, join, self.or_method)
        in_mis = state.in_mis | join
        undecided = undecided & ~join & ~lost
        # Wire accounting: every contender broadcast its draw, every winner
        # its announcement [ref: node.py:110-116 send_to_nodes fan-out].
        msgs = (segment.frontier_messages(graph, state.undecided)
                + segment.frontier_messages(graph, join))
        new_state = LubyMISState(in_mis=in_mis, undecided=undecided)
        stats = {
            "messages": msgs,
            "undecided": jnp.sum(undecided),
            "mis_size": jnp.sum(in_mis),
        }
        return new_state, stats
