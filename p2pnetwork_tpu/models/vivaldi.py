"""Vivaldi network coordinates — decentralized latency embedding, batched.

The classic P2P answer (Dabek et al. 2004; shipped in Vuze/Azureus and
the Serf/Consul memberlist) to "which replica is CLOSEST to me?"
without O(N²) pings: every node keeps a Euclidean coordinate plus a
non-Euclidean *height* (its access-link penalty), and each observed RTT
acts as a spring pulling the pair toward coordinates whose predicted
distance ``|xi − xj| + hi + hj`` matches the measurement. Reference
users would hand-roll this over ``node_message`` ping/ack pairs
[ref: README.md:20]; here one round is the whole population springing
at once:

- each live node draws one neighbor from its table (the shared
  :func:`~p2pnetwork_tpu.models.base.draw_neighbor_slot` sampler — the
  same draw Gossip and the failure detector use);
- the "measured" RTT is the graph's edge weight for that link (build
  latencies with ``from_edges(weights=...)``; unweighted graphs embed
  hop distance), optionally jittered by ``noise`` to model measurement
  error;
- the adaptive-timestep rule from the paper: confidence weight
  ``w = ei/(ei+ej)``, relative error of the sample, an EWMA of each
  node's error estimate (``ce``), and step ``δ = cc·w`` scaling the
  spring displacement — with the height update pulling both ends'
  access penalties toward the residual.

Deterministic given the PRNG key; dead nodes hold position (their error
stays at the 1.0 ceiling, matching a peer that answers no pings).
``stats['rmse']`` tracks embedding quality over the SAMPLED springs per
round; converge with ``engine.run_until_converged(..., stat="rmse",
threshold=...)`` sized to the latency scale, or run fixed rounds like
the real systems do (they never stop springing).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class VivaldiState:
    coord: jax.Array  # f32[N_pad, dim] — Euclidean part
    height: jax.Array  # f32[N_pad] — access-link penalty (>= 0)
    ce: jax.Array  # f32[N_pad] — local error estimate in [0, 1]
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Vivaldi:
    """Height-vector Vivaldi over the neighbor table.

    ``dim``: Euclidean dimensions (the paper found 2-3 + height ample);
    ``cc``/``ce_gain``: the paper's c_c and c_e gains; ``noise``:
    multiplicative RTT jitter amplitude (0 = exact measurements);
    ``height_min``: the positive height floor (Serf's HeightMin) — the
    height update scales by the current height, so an exact zero would
    be absorbing and the access-link term could never learn; the floor
    keeps it live. Size it well below the latency scale."""

    dim: int = 2
    cc: float = 0.25
    ce_gain: float = 0.25
    noise: float = 0.0
    height_min: float = 1e-3

    def init(self, graph: Graph, key: jax.Array) -> VivaldiState:
        if graph.neighbors is None or not graph.neighbors_complete:
            raise ValueError(
                "Vivaldi needs the complete neighbor table "
                "(build with from_edges(build_neighbor_table=True))")
        n_pad = graph.n_nodes_padded
        # Tiny random spread instead of the all-at-origin cold start (the
        # paper's zero-start needs the random unit-vector escape hatch
        # every round; a seeded spread reaches the same embeddings with
        # one fewer special case in the batched update).
        coord = 1e-3 * jax.random.normal(key, (n_pad, self.dim),
                                         dtype=jnp.float32)
        return VivaldiState(
            coord=coord * graph.node_mask[:, None],
            height=jnp.full(n_pad, self.height_min, dtype=jnp.float32),
            ce=jnp.ones(n_pad, dtype=jnp.float32),
            round=jnp.int32(0),
        )

    def predicted(self, state: VivaldiState, i, j) -> jax.Array:
        """Predicted latency between node index arrays ``i`` and ``j``."""
        d = jnp.linalg.norm(state.coord[i] - state.coord[j], axis=-1)
        return d + state.height[i] + state.height[j]

    def step(self, graph: Graph, state: VivaldiState, key: jax.Array):
        k_pick, k_noise = jax.random.split(key)
        slot, partner, has = base.draw_neighbor_slot(graph, k_pick)
        active = has & graph.node_mask & graph.node_mask[partner]

        # The sampled spring's measured RTT: the stored link weight
        # (aligned neighbor_weight view), hop cost 1 when unweighted.
        if graph.neighbor_weight is not None:
            rtt = jnp.take_along_axis(graph.neighbor_weight,
                                      slot[:, None], axis=1)[:, 0]
        else:
            rtt = jnp.ones(graph.n_nodes_padded, dtype=jnp.float32)
        if self.noise > 0.0:
            jitter = 1.0 + self.noise * jax.random.uniform(
                k_noise, rtt.shape, minval=-1.0, maxval=1.0)
            rtt = rtt * jitter

        xi, xj = state.coord, state.coord[partner]
        hi, hj = state.height, state.height[partner]
        dvec = xi - xj
        dist = jnp.linalg.norm(dvec, axis=-1)
        pred = dist + hi + hj
        # Unit vector; coincident points separate along a random axis is
        # the paper's rule — the seeded init makes coincidence measure
        # zero, so a safe-denominator is all that is needed.
        unit = dvec / jnp.maximum(dist, 1e-9)[:, None]

        w = state.ce / jnp.maximum(state.ce + state.ce[partner], 1e-9)
        err = pred - rtt  # positive: we predict too far -> pull closer
        rel_err = jnp.abs(err) / jnp.maximum(rtt, 1e-9)
        delta = self.cc * w

        # Spring displacement splits between the Euclidean part and the
        # height (the height-vector force of the paper: both ends'
        # penalties absorb a share of the residual).
        move = (-delta * err)[:, None] * unit
        coord = jnp.where(active[:, None],
                          xi + move, xi)
        height = jnp.where(
            active,
            jnp.maximum(hi - delta * err * (hi / jnp.maximum(pred, 1e-9)),
                        self.height_min),
            hi)
        ce = jnp.where(
            active,
            jnp.clip(rel_err * (self.ce_gain * w)
                     + state.ce * (1.0 - self.ce_gain * w), 0.0, 1.0),
            state.ce)

        new_state = VivaldiState(coord=coord, height=height, ce=ce,
                                 round=state.round + 1)
        n_act = jnp.maximum(jnp.sum(active), 1)
        stats = {
            "messages": jnp.sum(active),  # one ping/ack per sampled spring
            "rmse": jnp.sqrt(jnp.sum(jnp.where(active, err * err, 0.0))
                             / n_act),
            "mean_rel_err": jnp.sum(jnp.where(active, rel_err, 0.0)) / n_act,
            "mean_ce": jnp.sum(jnp.where(graph.node_mask, ce, 0.0))
            / jnp.maximum(jnp.sum(graph.node_mask), 1),
        }
        return new_state, stats
