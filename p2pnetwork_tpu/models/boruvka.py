"""Borůvka minimum spanning forest — the synchronous core of GHS.

THE classic distributed-MST question a P2P overlay asks: *which links
form the cheapest backbone connecting every reachable peer?* Reference
users would hand-roll this on the event hooks (the library "does not
implement any protocol" [ref: README.md:20]); the canonical distributed
answer is Gallager–Humblet–Spira, whose synchronous skeleton is exactly
Borůvka: every fragment picks its minimum-weight outgoing edge, merges
along it, repeat — O(log N) phases. One phase maps to one ``step`` here,
with each Borůvka primitive batched over the whole population:

- *fragment min-edge search* — lexicographic scatter-min over the COO
  edges, keyed ``(weight, lo, hi)`` where ``lo/hi`` are the sorted
  endpoints. Direction-INDEPENDENT tie-breaking is load-bearing: keyed
  by directed edge id, two fragments can rank the same equal-weight
  edge pair differently and hook into a length-3 cycle the merge step
  cannot absorb; the undirected key makes every hook cycle a 2-cycle
  (the standard proof: the strictly-minimal edge of any would-be cycle
  is picked from BOTH sides).
- *merge* — hook each fragment to its pick's far fragment, break the
  2-cycles by keeping the lower representative id as root, then
  pointer-jump (``lax.while_loop`` doubling) to the new roots.
- *edge commitment* — every NON-root fragment commits its picked edge,
  so a merge of k fragments adds exactly k−1 edges: acyclicity holds by
  counting even when two fragments picked distinct equal-weight edges
  between the same pair.

Runs on ``graph.edge_weight`` (unit costs when unweighted — then this
is a deterministic spanning forest, the weighted sibling of
models/spanning.py's BFS tree). **Weights must be symmetric** —
``w(u, v) == w(v, u)``, i.e. a function of the undirected edge, which is
what "minimum spanning" means; build them from the sorted endpoint pair
(``min(s, r)``, ``max(s, r)``) as the tests do. Asymmetric weights void
the minimality argument (two fragments then disagree on the same edge's
cost); the phase count stays bounded — the merge loop is a fixed
doubling schedule, see ``step`` — but the output is not an MSF of
anything. Dead nodes/edges are excluded via the
usual masks; the dynamic runtime-link region is NOT a candidate until a
consolidation rebuild folds it into the weighted edge set (weights
attach at build [graph.py ``with_weights``], matching DistanceVector's
treatment of unconsolidated links as provisional).

Quiescence: a phase that merges nothing (``changed == 0``) means no
outgoing edges remain anywhere — run with
``engine.run_until_converged(..., stat="changed", threshold=1)``. At
that point ``state.mst_edge`` marks one directed COO slot per forest
edge, ``state.comp`` labels nodes by forest component, and
``mst_edges == live_nodes − components`` (the forest invariant the
tests assert). Deterministic — no RNG consumed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BoruvkaState:
    comp: jax.Array  # i32[N_pad] — fragment representative id; -1 on dead
    mst_edge: jax.Array  # bool[E_pad] — COO slots committed to the forest
    mst_weight: jax.Array  # f32[] — cumulative committed weight
    round: jax.Array  # i32[] — phases executed


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Boruvka:
    """Minimum spanning forest by synchronous fragment merging.

    Pure COO scatter/gather — no aggregation-method knob: the min-edge
    search is keyed by fragment label, which changes every phase, so
    none of the static layouts (blocked/hybrid/neighbor-table) apply.
    """

    def init(self, graph: Graph, key: jax.Array) -> BoruvkaState:
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        comp = jnp.where(graph.node_mask, ids, -1)
        return BoruvkaState(
            comp=comp,
            mst_edge=jnp.zeros(graph.n_edges_padded, dtype=bool),
            mst_weight=jnp.float32(0.0),
            round=jnp.int32(0),
        )

    def components(self, graph: Graph, state: BoruvkaState) -> jax.Array:
        """Live nodes still representing themselves — the forest
        component count once ``changed`` hits 0."""
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        return jnp.sum((state.comp == ids) & graph.node_mask)

    def step(self, graph: Graph, state: BoruvkaState, key: jax.Array):
        n_pad = graph.n_nodes_padded
        e_pad = graph.n_edges_padded
        ids = jnp.arange(n_pad, dtype=jnp.int32)
        s, r = graph.senders, graph.receivers
        w = (graph.edge_weight if graph.edge_weight is not None
             else jnp.ones(e_pad, dtype=jnp.float32))
        comp = state.comp

        alive = graph.edge_mask & graph.node_mask[s] & graph.node_mask[r]
        cu = jnp.where(alive, comp[s], 0)
        cv = jnp.where(alive, comp[r], 0)
        cross = alive & (cu != cv)
        # Scatter target per edge: the sender's fragment (dropped when not
        # a cross edge). Both directions of an undirected edge are stored,
        # so each fragment sees every incident edge through its own
        # outgoing copies.
        tgt = jnp.where(cross, cu, n_pad)

        # Lexicographic (weight, lo, hi) scatter-min, one component at a
        # time, narrowing the candidate set after each.
        lo = jnp.minimum(s, r)
        hi = jnp.maximum(s, r)
        inf = jnp.float32(jnp.inf)
        big = jnp.int32(2**31 - 1)
        best_w = jnp.full(n_pad, inf).at[tgt].min(
            jnp.where(cross, w, inf), mode="drop")
        cand = cross & (w == best_w[jnp.where(cross, cu, 0)])
        best_lo = jnp.full(n_pad, big).at[jnp.where(cand, cu, n_pad)].min(
            jnp.where(cand, lo, big), mode="drop")
        cand &= lo == best_lo[jnp.where(cand, cu, 0)]
        best_hi = jnp.full(n_pad, big).at[jnp.where(cand, cu, n_pad)].min(
            jnp.where(cand, hi, big), mode="drop")
        cand &= hi == best_hi[jnp.where(cand, cu, 0)]
        # Same undirected key can still be stored twice between the same
        # endpoints (parallel duplicates) — a final edge-id min makes the
        # committed slot unique.
        eids = jnp.arange(e_pad, dtype=jnp.int32)
        best_e = jnp.full(n_pad, big).at[jnp.where(cand, cu, n_pad)].min(
            jnp.where(cand, eids, big), mode="drop")

        is_rep = (comp == ids) & graph.node_mask
        has_pick = is_rep & (best_e < big)
        pick = jnp.where(has_pick, best_e, 0)
        # Hook each picking fragment to the far endpoint's fragment.
        far = jnp.where(has_pick, cv[pick], ids)
        parent = jnp.where(is_rep, far, ids)
        # Break the 2-cycles: mutual hooks keep the lower id as root.
        mutual = (parent[parent] == ids) & (parent != ids)
        parent = jnp.where(mutual & (ids < parent), ids, parent)

        # Non-root fragments commit their picked edge: k-way merges add
        # exactly k-1 edges.
        commits = has_pick & (parent != ids)
        slot = jnp.where(commits, pick, e_pad)
        mst_edge = state.mst_edge.at[slot].set(True, mode="drop")
        added_w = jnp.sum(jnp.where(commits, w[pick], 0.0))

        # Pointer-jump the hook forest to its roots. The iteration count is
        # STATIC: ceil(log2(n_pad)) + 1 doublings collapse any forest (depth
        # <= fragment count <= n_pad). A convergence-tested while_loop here
        # once hung forever on ASYMMETRIC edge weights — direction-dependent
        # costs break the total-order argument that limits hook cycles to
        # mutual pairs, and a 3-cycle never reaches a fixpoint. Bounded
        # doubling cannot hang; symmetric weights (the documented contract)
        # are exact either way.
        n_iter = max(1, (n_pad - 1).bit_length() + 1)
        parent = jax.lax.fori_loop(0, n_iter, lambda i, p: p[p], parent)
        comp = jnp.where(graph.node_mask, parent[jnp.where(comp >= 0, comp, 0)],
                         -1)

        new_state = BoruvkaState(
            comp=comp,
            mst_edge=mst_edge,
            mst_weight=state.mst_weight + added_w,
            round=state.round + 1,
        )
        merges = jnp.sum(commits)
        stats = {
            "messages": jnp.sum(cross),
            "changed": merges,
            "components": self.components(graph, new_state),
            "mst_edges": jnp.sum(mst_edge),
            "mst_weight": new_state.mst_weight,
        }
        return new_state, stats
