"""Probabilistic failure detection: randomized ping/ack with suspicion.

The reference detects dead peers passively — a recv timeout or EOF fires
``node_disconnected`` and the socket is dropped [ref:
p2pnetwork/nodeconnection.py:196-236, node.py events]. Real deployments
layer an ACTIVE detector on top (SWIM-style: ping a random member each
tick, suspect on silence, confirm after repeated misses) because a TCP
session can sit half-open for minutes. Batched TPU form: every
responsive node pings one uniformly drawn neighbor-table slot per round
(the same k-th-set-bit draw as Gossip); a ping answered resets that
slot's suspicion, silence increments it, and ``threshold`` consecutive
misses latch the slot as declared-dead. Message loss (``loss_prob``
per direction, independently) makes the detector properly
probabilistic: false suspicions happen and the threshold is the
precision/latency dial — exactly the SWIM trade-off, now measurable
over a whole population in one compiled loop.

Run against :func:`p2pnetwork_tpu.sim.failures.mark_unresponsive` (NOT
``fail_nodes``): the detector's whole premise is that survivors still
hold the silent peer in their tables and must discover the silence —
``fail_nodes`` would re-mask the table and hide the corpse from the
pinger. Converge with ``engine.run_until_converged(...,
stat="undetected", threshold=1)``: at that point every dead watched
slot is declared.

State is ``[N_pad, max_degree]`` — per (watcher, watched-slot) — so
memory matches the neighbor table the watchers already hold.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FailureDetectorState:
    suspicion: jax.Array  # i32[N_pad, d] — consecutive unanswered pings
    declared: jax.Array  # bool[N_pad, d] — latched declarations
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class FailureDetector:
    """SWIM-style randomized ping/ack over the neighbor table."""

    #: Consecutive misses before a slot is declared dead.
    threshold: int = 3
    #: Per-direction message-loss probability (ping and ack drawn
    #: independently) — 0 makes the detector exact.
    loss_prob: float = 0.0

    def init(self, graph: Graph, key: jax.Array) -> FailureDetectorState:
        if graph.neighbors is None:
            raise ValueError(
                "FailureDetector requires a graph with a neighbor table")
        shape = graph.neighbors.shape
        return FailureDetectorState(
            suspicion=jnp.zeros(shape, dtype=jnp.int32),
            declared=jnp.zeros(shape, dtype=bool),
            round=jnp.int32(0),
        )

    def _dead_watched(self, graph: Graph) -> jax.Array:
        """bool[N_pad, d]: watched slots whose target is unresponsive,
        seen from a responsive watcher (the detector's ground truth)."""
        return (graph.neighbor_mask
                & ~graph.node_mask[graph.neighbors]
                & graph.node_mask[:, None])

    def step(self, graph: Graph, state: FailureDetectorState, key: jax.Array):
        from p2pnetwork_tpu.models.base import draw_neighbor_slot

        n_pad = graph.n_nodes_padded
        mask = graph.neighbor_mask
        k1, k2, k3 = jax.random.split(key, 3)
        # Uniform slot among the watched (valid) table slots — the shared
        # k-th-set-bit draw, over the build-time rows mark_unresponsive
        # deliberately leaves intact.
        slot, target, has_slot = draw_neighbor_slot(graph, k1)
        pinger = has_slot & graph.node_mask
        responsive = graph.node_mask[target]
        ping_ok = jax.random.uniform(k2, (n_pad,)) >= self.loss_prob
        ack_ok = jax.random.uniform(k3, (n_pad,)) >= self.loss_prob
        acked = responsive & ping_ok & ack_ok

        probed = ((jnp.arange(mask.shape[1])[None, :] == slot[:, None])
                  & mask & pinger[:, None])
        suspicion = jnp.where(
            probed,
            jnp.where(acked[:, None], 0, state.suspicion + 1),
            state.suspicion,
        )
        declared = state.declared | (suspicion >= self.threshold)

        dead = self._dead_watched(graph)
        n_dead = jnp.sum(dead)
        detected = jnp.sum(declared & dead)
        false_pos = jnp.sum(declared & mask & ~dead
                            & graph.node_mask[:, None])
        stats = {
            # One ping per prober + one ack per delivered ping to a
            # responsive target — the reference's send/recv counters.
            "messages": (jnp.sum(pinger)
                         + jnp.sum(pinger & responsive & ping_ok)),
            "undetected": n_dead - detected,
            "detected": detected,
            "dead_slots": n_dead,
            "false_positives": false_pos,
        }
        new_state = FailureDetectorState(suspicion=suspicion,
                                         declared=declared,
                                         round=state.round + 1)
        return new_state, stats
