"""PageRank power iteration over the peer graph.

The "who matters in this overlay" analysis reference users would run
offline on a dump of ``all_nodes`` [ref: p2pnetwork/node.py:75-78]; here it
is just another protocol behind the models/base.py seam — per-round state
is the rank vector, and one synchronous round is one ``propagate_sum`` of
``rank / out_degree`` over the edge set (the batched replacement for the
reference's per-edge send loop [ref: p2pnetwork/node.py:110-112]).

Damped formulation with dangling-mass redistribution over LIVE nodes:

    r'[v] = (1-d)/N + d * ( sum_{u->v} r[u]/deg_out[u]  +  dangling/N )

where ``dangling`` is the rank mass held by live nodes with no outgoing
edges (isolated nodes, or nodes whose every link failed — sim/failures.py).
``sum(r) == 1`` holds at every round, and the iteration is a deterministic
pure function of the graph — no RNG consumed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PageRankState:
    ranks: jax.Array  # f32[N_pad] — sums to 1 over live nodes
    residual: jax.Array  # f32[] — L1 change of the last round


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class PageRank:
    damping: float = 0.85
    method: str = "auto"  # aggregation lowering, see ops/segment.py

    def init(self, graph: Graph, key: jax.Array) -> PageRankState:
        mask_f = graph.node_mask.astype(jnp.float32)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1).astype(jnp.float32)
        return PageRankState(ranks=mask_f / n_real,
                             residual=jnp.float32(jnp.inf))

    def step(self, graph: Graph, state: PageRankState, key: jax.Array):
        mask = graph.node_mask
        mask_f = mask.astype(jnp.float32)
        n_real = jnp.maximum(jnp.sum(mask), 1).astype(jnp.float32)
        deg = graph.out_degree.astype(jnp.float32)
        contrib = jnp.where(mask & (graph.out_degree > 0),
                            state.ranks / jnp.maximum(deg, 1.0), 0.0)
        pulled = segment.propagate_sum(graph, contrib, self.method)
        dangling = jnp.sum(jnp.where(mask & (graph.out_degree == 0),
                                     state.ranks, 0.0))
        ranks = ((1.0 - self.damping) / n_real
                 + self.damping * (pulled + dangling / n_real)) * mask_f
        residual = jnp.sum(jnp.abs(ranks - state.ranks))
        stats = {
            # Every live node with outgoing links ships one share per edge.
            "messages": segment.frontier_messages(graph, mask),
            "residual": residual,
            "rank_total": jnp.sum(ranks),
            "rank_max": jnp.max(ranks),
        }
        return PageRankState(ranks=ranks, residual=residual), stats
