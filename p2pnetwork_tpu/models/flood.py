"""Flooding broadcast with seen-set dedup.

The canonical protocol the reference tells users to write themselves
[ref: README.md:20]: a node that receives a message for the first time
re-broadcasts it to all its peers; a seen-set suppresses re-sends. In the
reference this is per-node Python in ``node_message`` overrides fanned out
over O(peers) sequential socket sends [ref: node.py:110-112]; here one round
of the entire population is a single masked neighbor-OR (ops/segment.py) —
the BASELINE.json north-star workload.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FloodState:
    """Population state: who has the message, who got it last round."""

    seen: jax.Array  # bool[N_pad]
    frontier: jax.Array  # bool[N_pad] — nodes that first saw it last round


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Flood:
    """Single-source flood. ``source`` is the seed node index."""

    source: int = 0
    method: str = "auto"  # aggregation lowering, see ops/segment.py

    def init(self, graph: Graph, key: jax.Array) -> FloodState:
        base.validate_source(graph, self.source)
        seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[self.source].set(True)
        seed = seed & graph.node_mask
        return FloodState(seen=seed, frontier=seed)

    def coverage(self, graph: Graph, state: FloodState) -> jax.Array:
        """Fraction of live nodes holding the message (resume seeding for
        engine.run_until_coverage_from).

        The numerator is masked: after mid-run node failures
        (sim/failures.py) ``seen`` can hold dead nodes, and counting them
        would report coverage > 1 and spuriously stop run-to-coverage."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum(state.seen & graph.node_mask) / n_real

    def step(self, graph: Graph, state: FloodState, key: jax.Array):
        """One synchronous round: frontier nodes broadcast; receivers that
        had not seen the message join the next frontier."""
        delivered = segment.propagate_or(graph, state.frontier, self.method)
        new = delivered & ~state.seen & graph.node_mask
        seen = state.seen | new
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": segment.frontier_messages(graph, state.frontier),
            # Masked numerator: dead-but-seen nodes (mid-run failures) must
            # not push coverage past 1.
            "coverage": jnp.sum(seen & graph.node_mask) / n_real,
            "frontier": jnp.sum(new),
        }
        return FloodState(seen=seen, frontier=new), stats
