"""Flooding broadcast with seen-set dedup.

The canonical protocol the reference tells users to write themselves
[ref: README.md:20]: a node that receives a message for the first time
re-broadcasts it to all its peers; a seen-set suppresses re-sends. In the
reference this is per-node Python in ``node_message`` overrides fanned out
over O(peers) sequential socket sends [ref: node.py:110-112]; here one round
of the entire population is a single masked neighbor-OR (ops/segment.py) —
the BASELINE.json north-star workload.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import bitset, frontier, segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FloodState:
    """Population state: who has the message, who got it last round."""

    seen: jax.Array  # bool[N_pad]
    frontier: jax.Array  # bool[N_pad] — nodes that first saw it last round


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FloodBitState:
    """FloodState bit-packed: 32 nodes per uint32 word (ops/bitset.py) —
    the scan/while loop carries 32x less predicate state in HBM."""

    seen: jax.Array  # u32[N_pad // 32]
    frontier: jax.Array  # u32[N_pad // 32]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Flood:
    """Single-source flood. ``source`` is the seed node index.

    ``bitset=True`` carries the seen/frontier predicates bit-packed
    (:class:`FloodBitState`); the round's set algebra (dedup, union,
    coverage count) then runs word-level (AND-NOT / OR / popcount), and
    only the propagate's input unpacks transiently. Results are
    bit-identical to the bool-state path — same seen sets, same stats
    (tests/test_frontier.py pins this).

    ``frontier_crossover`` overrides ``method="frontier"``'s sparse
    budget (float = fraction of padded nodes, int = node budget; None =
    the auto constant) — apply a value re-fit from bench.py's
    per-round occupancy attribution here."""

    source: int = 0
    method: str = "auto"  # aggregation lowering, see ops/segment.py
    bitset: bool = False  # pack carried state into uint32 words
    frontier_crossover: object = None  # ops/frontier.py budget override

    def init(self, graph: Graph, key: jax.Array):
        base.validate_source(graph, self.source)
        seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[self.source].set(True)
        seed = seed & graph.node_mask
        if self.bitset:
            packed = bitset.pack_bits(seed)
            return FloodBitState(seen=packed, frontier=packed)
        return FloodState(seen=seed, frontier=seed)

    def coverage(self, graph: Graph, state) -> jax.Array:
        """Fraction of live nodes holding the message (resume seeding for
        engine.run_until_coverage_from).

        The numerator is masked: after mid-run node failures
        (sim/failures.py) ``seen`` can hold dead nodes, and counting them
        would report coverage > 1 and spuriously stop run-to-coverage."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        if isinstance(state, FloodBitState):
            node_bits = bitset.pack_bits(graph.node_mask)
            return bitset.popcount(state.seen & node_bits) / n_real
        return jnp.sum(state.seen & graph.node_mask) / n_real

    def step(self, graph: Graph, state, key: jax.Array):
        """One synchronous round: frontier nodes broadcast; receivers that
        had not seen the message join the next frontier."""
        if isinstance(state, FloodBitState):
            return self._step_bits(graph, state)
        delivered = segment.propagate_or(
            graph, state.frontier, self.method,
            frontier_crossover=self.frontier_crossover)
        new = delivered & ~state.seen & graph.node_mask
        seen = state.seen | new
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": segment.frontier_messages(graph, state.frontier),
            # Masked numerator: dead-but-seen nodes (mid-run failures) must
            # not push coverage past 1.
            "coverage": jnp.sum(seen & graph.node_mask) / n_real,
            "frontier": jnp.sum(new),
            # The canonical definition (ops/frontier.py) — the same ints
            # the crossover budget is measured against.
            "frontier_occupancy": frontier.occupancy(graph, new),
        }
        return FloodState(seen=seen, frontier=new), stats

    def _step_bits(self, graph: Graph, state: FloodBitState):
        """The packed round: identical per-node logic, word-level algebra.
        ``new = delivered & ~seen & alive`` and the coverage/frontier
        counts are AND-NOT/OR/popcount over uint32 words; pack/unpack are
        exact, so every count and every bit matches the bool path."""
        n_pad = graph.n_nodes_padded
        frontier = bitset.unpack_bits(state.frontier, n_pad)
        delivered = segment.propagate_or(
            graph, frontier, self.method,
            frontier_crossover=self.frontier_crossover)
        node_bits = bitset.pack_bits(graph.node_mask)
        new = bitset.pack_bits(delivered) & ~state.seen & node_bits
        seen = state.seen | new
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        n_new = bitset.popcount(new)
        stats = {
            "messages": segment.frontier_messages(graph, frontier),
            "coverage": bitset.popcount(seen & node_bits) / n_real,
            "frontier": n_new,
            "frontier_occupancy": n_new / n_real,
        }
        return FloodBitState(seen=seen, frontier=new), stats
