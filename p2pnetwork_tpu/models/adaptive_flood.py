"""Frontier-adaptive flood: sparse rounds when the wave is small.

Direction-optimized traversal, TPU-style. The dense flood round
(models/flood.py) costs the same whether one node broadcast or half the
population did — its remainder gather touches every edge slot at XLA's
~8 cycles/element floor (BENCH.md "gather floor"). But a flood's life is
asymmetric: the first rounds move a handful of messages, the last rounds a
trickle, and only the middle saturates the graph. The reference pays this
shape in its own coin — one Python ``send`` per edge per 10 ms poll tick
[ref: p2pnetwork/node.py:110-112, nodeconnection.py:220]; here we pay it
in wasted gather cycles.

``AdaptiveFlood`` keeps TWO round implementations behind one
``lax.cond``, chosen per round by the live frontier count:

- **sparse** (``count <= k``): the frontier lives as an index list
  ``[k]``. One round gathers the ≤ ``k * max_out_span`` out-edge slots
  through the graph's source-CSR view (graph.py ``src_eid``/
  ``src_offsets``), re-checks runtime edge liveness through
  ``edge_mask``, folds in the dynamic (runtime-connected) edge region,
  dedups new receivers with a scatter-min claim pass, and scatter-marks
  them seen — O(k·W) work instead of O(E).
- **dense** (``count > k``): exactly models/flood.py's masked OR round
  (same ``method`` lowerings). When the wave shrinks back under ``k``,
  the branch pays one ``nonzero`` compaction to re-enter sparse mode.

State is a strict superset of FloodState (``seen``/``frontier`` bools
plus the index list and its count). Results are
bit-identical to ``Flood`` — same seen sets, same per-round message and
coverage stats (tests/test_adaptive_flood.py asserts this through dense,
sparse, and both transition directions, under failures and runtime
connects).

Requires a graph built with ``source_csr=True`` (or
``with_source_csr()``). Degree-skewed graphs bound the slot width by
their largest out-degree: a Barabási–Albert hub makes ``k * max_out_span``
rival the edge count, so this protocol targets the quasi-regular
topologies (WS lattices, rings, ER) where the benchmark family lives.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AdaptiveFloodState:
    seen: jax.Array  # bool[N_pad]
    frontier: jax.Array  # bool[N_pad] — nodes that first saw it last round
    fidx: jax.Array  # i32[k] — frontier as indices (valid iff fcount <= k)
    fcount: jax.Array  # i32[] — live frontier size (always exact)


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class AdaptiveFlood:
    """Single-source flood with frontier-sparse small rounds.

    ``k`` is the sparse-mode capacity (index-list width, a compile-time
    shape); ``method`` picks the dense round's aggregation lowering."""

    source: int = 0
    method: str = "auto"
    k: int = 1024

    def init(self, graph: Graph, key: jax.Array) -> AdaptiveFloodState:
        seed, fidx, count = _wave_seed(graph, self.source, self.k,
                                       "AdaptiveFlood")
        return AdaptiveFloodState(seen=seed, frontier=seed, fidx=fidx,
                                  fcount=count)

    def coverage(self, graph: Graph, state: AdaptiveFloodState) -> jax.Array:
        """Live-node coverage (Flood.coverage parity)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum(state.seen & graph.node_mask) / n_real

    def step(self, graph: Graph, state: AdaptiveFloodState, key: jax.Array):
        seen, frontier, fidx, fcount, msgs = _wave_step(
            graph, self.k, self.method,
            state.seen, state.frontier, state.fidx, state.fcount,
        )
        new_state = AdaptiveFloodState(seen=seen, frontier=frontier,
                                       fidx=fidx, fcount=fcount)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": msgs,
            # Masked recompute, not an incremental counter — a fused AND +
            # reduce is nearly free, and it stays exact across mid-run
            # node failures (models/flood.py parity).
            "coverage": jnp.sum(seen & graph.node_mask) / n_real,
            "frontier": fcount,
        }
        return new_state, stats


# --------------------------------------------------- shared wave rounds


def _sparse_wave_round(graph: Graph, k: int, seen, frontier, fidx, fcount):
    """One frontier-sparse wave round: O(k·max_out_span) work via the
    source-CSR view. Returns ``(seen, frontier, fidx, new_count, msgs)``."""
    w = max(graph.max_out_span, 1)
    n_pad = graph.n_nodes_padded
    pad_node = n_pad - 1

    fvalid = jnp.arange(k) < fcount
    f = jnp.where(fvalid, fidx, pad_node)
    base_off = graph.src_offsets[f]  # [k]
    row_len = graph.src_offsets[f + 1] - base_off  # [k] build-time extent
    slot = base_off[:, None] + jnp.arange(w)[None, :]  # [k, w]
    svalid = (jnp.arange(w)[None, :] < row_len[:, None]) & fvalid[:, None]
    eid = graph.src_eid[jnp.where(svalid, slot, graph.n_edges_padded - 1)]
    # Runtime liveness re-check: failed edges (sim/failures.py) stay in
    # the build-time CSR rows but are masked here.
    evalid = svalid & graph.edge_mask[eid]
    cand = jnp.where(evalid, graph.receivers[eid], pad_node).reshape(-1)
    fresh = evalid.reshape(-1) & ~seen[cand] & graph.node_mask[cand]

    # Dynamic (runtime-connected) out-edges ride along: the region is a
    # small unsorted COO block, scanned whole.
    if graph.dyn_senders is not None:
        dsend = frontier[graph.dyn_senders] & graph.dyn_mask
        dcand = jnp.where(dsend, graph.dyn_receivers, pad_node)
        dfresh = dsend & ~seen[dcand] & graph.node_mask[dcand]
        cand = jnp.concatenate([cand, dcand])
        fresh = jnp.concatenate([fresh, dfresh])

    # First-claim dedup: every fresh slot claims its candidate with its
    # position; winners are the slots that hold the minimum claim, so
    # each newly-seen node appears in the next frontier exactly once.
    order = jnp.arange(cand.shape[0], dtype=jnp.int32)
    big = jnp.int32(2**31 - 1)
    claim = jnp.where(fresh, order, big)
    scratch = jnp.full(n_pad, big, dtype=jnp.int32).at[cand].min(
        claim, mode="drop"
    )
    winner = fresh & (scratch[cand] == order)
    new_count = jnp.sum(winner).astype(jnp.int32)

    seen = seen.at[jnp.where(fresh, cand, n_pad)].set(True, mode="drop")
    new_frontier = (
        jnp.zeros(n_pad, dtype=bool)
        .at[jnp.where(winner, cand, n_pad)].set(True, mode="drop")
    )
    # Next index list: compact the winners (O(k·w) cumsum, not O(N)).
    # Overflow past k only happens when new_count > k — dense mode
    # takes over and the truncated list is never read.
    pos = jnp.nonzero(winner, size=k, fill_value=cand.shape[0] - 1)[0]
    fidx = jnp.where(jnp.arange(k) < new_count, cand[pos], pad_node)

    msgs = jnp.sum(jnp.where(fvalid, graph.out_degree[f], 0))
    return seen, new_frontier, fidx, new_count, msgs


def _dense_wave_round(graph: Graph, k: int, method: str, seen, frontier,
                      fidx):
    """One dense wave round (models/flood.py's masked OR), maintaining the
    sparse index list on the crossing back under ``k``."""
    delivered = segment.propagate_or(graph, frontier, method)
    new = delivered & ~seen & graph.node_mask
    seen = seen | new
    new_count = jnp.sum(new).astype(jnp.int32)

    # Re-enter sparse mode: pay the O(N) compaction only on the round
    # that crosses back under k (lax.cond executes one branch).
    def compact(n):
        return jnp.nonzero(
            n, size=k, fill_value=graph.n_nodes_padded - 1
        )[0].astype(jnp.int32)

    fidx = jax.lax.cond(new_count <= k, compact, lambda n: fidx, new)
    msgs = segment.frontier_messages(graph, frontier)
    return seen, new, fidx, new_count, msgs


def _wave_seed(graph: Graph, source: int, k: int, proto_name: str):
    """Validated seed shared by the adaptive protocols: the source's
    one-hot (masked by liveness), the fidx sentinel list, and the count."""
    base.validate_source(graph, source)
    if graph.src_eid is None:
        raise ValueError(
            f"{proto_name} requires a source-CSR graph — build with "
            f"from_edges(source_csr=True) or graph.with_source_csr()"
        )
    seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[source].set(True)
    seed = seed & graph.node_mask
    fidx = jnp.full(k, graph.n_nodes_padded - 1, dtype=jnp.int32)
    fidx = fidx.at[0].set(source)
    return seed, fidx, jnp.sum(seed).astype(jnp.int32)


def _wave_step(graph: Graph, k: int, method: str, seen, frontier, fidx,
               fcount):
    """Adaptive wave round: lax.cond picks sparse vs dense by the live
    frontier count. Shared by AdaptiveFlood and AdaptiveHopDistance."""
    return jax.lax.cond(
        fcount <= k,
        lambda s, f, i: _sparse_wave_round(graph, k, s, f, i, fcount),
        lambda s, f, i: _dense_wave_round(graph, k, method, s, f, i),
        seen, frontier, fidx,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AdaptiveHopDistanceState:
    dist: jax.Array  # i32[N_pad] — BFS hops from source, -1 = not reached
    frontier: jax.Array  # bool[N_pad]
    fidx: jax.Array  # i32[k]
    fcount: jax.Array  # i32[]
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class AdaptiveHopDistance:
    """BFS hop distances with frontier-sparse small rounds — the adaptive
    twin of models/hopdist.py (the wave IS the flood wave; nodes record the
    first round that reaches them), bit-identical to it round for round."""

    source: int = 0
    method: str = "auto"
    k: int = 1024

    def init(self, graph: Graph, key: jax.Array) -> AdaptiveHopDistanceState:
        seed, fidx, count = _wave_seed(graph, self.source, self.k,
                                       "AdaptiveHopDistance")
        return AdaptiveHopDistanceState(
            dist=jnp.where(seed, 0, -1).astype(jnp.int32), frontier=seed,
            fidx=fidx, fcount=count, round=jnp.int32(0),
        )

    def coverage(self, graph: Graph, state) -> jax.Array:
        """Reached fraction of live nodes (hopdist.py parity)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum((state.dist >= 0) & graph.node_mask) / n_real

    def step(self, graph: Graph, state: AdaptiveHopDistanceState,
             key: jax.Array):
        seen = state.dist >= 0
        seen2, frontier, fidx, fcount, msgs = _wave_step(
            graph, self.k, self.method,
            seen, state.frontier, state.fidx, state.fcount,
        )
        rnd = state.round + 1
        dist = jnp.where(frontier, rnd, state.dist)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        reached = (dist >= 0) & graph.node_mask
        stats = {
            "messages": msgs,
            "coverage": jnp.sum(reached) / n_real,
            "frontier": fcount,
            "max_dist": jnp.max(dist),
        }
        return AdaptiveHopDistanceState(dist=dist, frontier=frontier,
                                        fidx=fidx, fcount=fcount,
                                        round=rnd), stats
