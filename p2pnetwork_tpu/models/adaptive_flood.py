"""Frontier-adaptive flood: sparse rounds when the wave is small.

Direction-optimized traversal, TPU-style. The dense flood round
(models/flood.py) costs the same whether one node broadcast or half the
population did — its remainder gather touches every edge slot at XLA's
~8 cycles/element floor (BENCH.md "gather floor"). But a flood's life is
asymmetric: the first rounds move a handful of messages, the last rounds a
trickle, and only the middle saturates the graph. The reference pays this
shape in its own coin — one Python ``send`` per edge per 10 ms poll tick
[ref: p2pnetwork/node.py:110-112, nodeconnection.py:220]; here we pay it
in wasted gather cycles.

``AdaptiveFlood`` keeps TWO round implementations behind one
``lax.cond``, chosen per round by the live frontier count:

- **sparse** (item count ``<= k``): the frontier lives as a list of ``k``
  fixed-width WORK ITEMS, each a ``(node, slice)`` pair naming one
  ``W``-wide slice of that node's out-edge row in the graph's source-CSR
  view (graph.py ``src_eid``/``src_offsets``). A quasi-regular node is one
  item; a hub with out-degree ``d`` chunks into ``ceil(d/W)`` items, so
  the round's gather is always exactly ``k·W`` slots — independent of the
  largest degree. One round gathers those slots, re-checks runtime edge
  liveness through ``edge_mask``, folds in the dynamic
  (runtime-connected) edge region, dedups new receivers with a
  scatter-min claim pass, scatter-marks them seen, and expands the
  winners back into work items (cumsum + searchsorted, O(k log k)).
- **dense** (item count ``> k``): exactly models/flood.py's masked OR
  round (same ``method`` lowerings). When the wave's out-edge mass
  shrinks back under ``k`` items, the branch pays one ``nonzero``
  compaction to re-enter sparse mode.

Because the sparse/dense switch budgets by the frontier's out-edge MASS
(in ``W``-slice units), not its node count, a single hub waking up is
charged for its whole row and tips the round dense when that is cheaper —
degree-skewed (Barabási–Albert) graphs get the same adaptive win as the
quasi-regular families instead of being excluded.

State is a strict superset of FloodState (``seen``/``frontier`` bools
plus the work-item lists and the item count). Results are
bit-identical to ``Flood`` — same seen sets, same per-round message and
coverage stats (tests/test_adaptive_flood.py asserts this through dense,
sparse, and both transition directions, under failures, runtime
connects, and on hub-skewed graphs).

Requires a graph built with ``source_csr=True`` (or
``with_source_csr()``). ``slice_width`` pins ``W`` explicitly; the
default 0 picks ``min(max_out_span, 128)`` — on quasi-regular graphs
(WS, ring, ER) that is one item per node, the pre-chunking layout.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import bitset, segment
from p2pnetwork_tpu.ops import frontier as frontier_ops
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AdaptiveFloodState:
    seen: jax.Array  # bool[N_pad]
    frontier: jax.Array  # bool[N_pad] — nodes that first saw it last round
    fidx: jax.Array  # i32[k] — work-item node ids (valid iff fcount <= k)
    fslice: jax.Array  # i32[k] — work-item slice index within the node's row
    fcount: jax.Array  # i32[] — frontier out-edge mass in W-slice work items


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AdaptiveFloodBitState:
    """AdaptiveFloodState with the bool predicates bit-packed
    (ops/bitset.py): the while-loop carry holds 32x less seen/frontier
    state in HBM; the wave rounds unpack transiently."""

    seen: jax.Array  # u32[N_pad // 32]
    frontier: jax.Array  # u32[N_pad // 32]
    fidx: jax.Array  # i32[k]
    fslice: jax.Array  # i32[k]
    fcount: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class AdaptiveFlood:
    """Single-source flood with frontier-sparse small rounds.

    ``k`` is the sparse-mode capacity in work items (a compile-time
    shape); ``method`` picks the dense round's aggregation lowering;
    ``slice_width`` is the per-item row-slice width W (0 = auto:
    ``min(max_out_span, 128)``); ``bitset=True`` packs the carried
    seen/frontier predicates into uint32 words — bit-identical results
    (tests/test_frontier.py pins the parity)."""

    source: int = 0
    method: str = "auto"
    k: int = 1024
    slice_width: int = 0
    bitset: bool = False

    def init(self, graph: Graph, key: jax.Array):
        seed, fidx, fslice, count = _wave_seed(
            graph, self.source, self.k, self.slice_width, "AdaptiveFlood")
        if self.bitset:
            packed = bitset.pack_bits(seed)
            return AdaptiveFloodBitState(seen=packed, frontier=packed,
                                         fidx=fidx, fslice=fslice,
                                         fcount=count)
        return AdaptiveFloodState(seen=seed, frontier=seed, fidx=fidx,
                                  fslice=fslice, fcount=count)

    def coverage(self, graph: Graph, state) -> jax.Array:
        """Live-node coverage (Flood.coverage parity)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        if isinstance(state, AdaptiveFloodBitState):
            node_bits = bitset.pack_bits(graph.node_mask)
            return bitset.popcount(state.seen & node_bits) / n_real
        return jnp.sum(state.seen & graph.node_mask) / n_real

    def step(self, graph: Graph, state, key: jax.Array):
        packed = isinstance(state, AdaptiveFloodBitState)
        n_pad = graph.n_nodes_padded
        seen0 = bitset.unpack_bits(state.seen, n_pad) if packed else state.seen
        frontier0 = (bitset.unpack_bits(state.frontier, n_pad)
                     if packed else state.frontier)
        seen, frontier, fidx, fslice, fcount, ncount, msgs = _wave_step(
            graph, self.k, self.slice_width, self.method,
            seen0, frontier0, state.fidx, state.fslice, state.fcount,
        )
        if packed:
            new_state = AdaptiveFloodBitState(
                seen=bitset.pack_bits(seen),
                frontier=bitset.pack_bits(frontier),
                fidx=fidx, fslice=fslice, fcount=fcount)
        else:
            new_state = AdaptiveFloodState(seen=seen, frontier=frontier,
                                           fidx=fidx, fslice=fslice,
                                           fcount=fcount)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": msgs,
            # Masked recompute, not an incremental counter — a fused AND +
            # reduce is nearly free, and it stays exact across mid-run
            # node failures (models/flood.py parity).
            "coverage": jnp.sum(seen & graph.node_mask) / n_real,
            "frontier": ncount,
            # ops/frontier.py's canonical definition; the new frontier
            # holds exactly the ncount winner nodes (live by
            # construction), so the ints — and the f32 division — match.
            "frontier_occupancy": frontier_ops.occupancy(graph, frontier),
        }
        return new_state, stats


# --------------------------------------------------- shared wave rounds


def _slice_width(graph: Graph, slice_width: int) -> int:
    """Resolve W, the per-work-item row-slice width. Auto (0) keeps one
    item per node on quasi-regular graphs and chunks anything wider than
    128 — a hub then costs ceil(d/W) items instead of widening every
    item's gather to the hub's degree."""
    if slice_width > 0:
        return slice_width
    return max(1, min(graph.max_out_span, 128))


def _one_item_per_node(graph: Graph, w: int) -> bool:
    """STATIC (trace-time) predicate for the quasi-regular fast path:
    when no build-time row is wider than ``w`` — every graph under the
    auto slice width — each row is exactly one work item, so item
    expansion is the identity and item mass equals node count. Both
    specializations (``_expand_items``, ``_dense_wave_round``) key on
    THIS predicate so they cannot desynchronize."""
    return graph.max_out_span <= w


def _row_items(graph: Graph, w: int, nodes) -> jax.Array:
    """Work items per node: its build-time CSR row in W-wide slices.
    Empty rows still cost one (empty) item so every frontier node owns a
    slice-0 item — message accounting reads out_degree through those."""
    row_len = graph.src_offsets[nodes + 1] - graph.src_offsets[nodes]
    return jnp.maximum((row_len + w - 1) // w, 1).astype(jnp.int32)


def _expand_items(graph: Graph, w: int, k: int, wnode, node_count):
    """Expand ``node_count`` frontier nodes (``wnode``, width-k list) into
    ``(fidx, fslice, icount)`` work items: per-node counts -> cumsum ->
    searchsorted assigns each of the k item slots its owning node and
    slice index. O(k log k); never touches N or E. An ``icount > k``
    result truncates silently — dense mode takes over and the lists are
    never read (same overflow contract as the node lists had).

    STATIC specialization: ``max_out_span <= w`` (every quasi-regular
    graph under the auto slice width) makes every row exactly one item —
    the expansion is the identity, so the compiled program skips the
    cumsum/searchsorted entirely and this path costs what round 3's
    node-list layout did. Both operands are trace-time Python ints."""
    if _one_item_per_node(graph, w):
        return wnode, jnp.zeros(k, dtype=jnp.int32), node_count
    pad_node = graph.n_nodes_padded - 1
    items_per = jnp.where(jnp.arange(k) < node_count,
                          _row_items(graph, w, wnode), 0)
    offs = jnp.cumsum(items_per)
    icount = offs[-1].astype(jnp.int32)
    starts = offs - items_per
    p = jnp.arange(k, dtype=jnp.int32)
    j = jnp.clip(jnp.searchsorted(offs, p, side="right"), 0, k - 1)
    valid = p < icount
    fidx = jnp.where(valid, wnode[j], pad_node)
    fslice = jnp.where(valid, p - starts[j], 0).astype(jnp.int32)
    return fidx, fslice, icount


def _sparse_wave_round(graph: Graph, w: int, k: int, seen, frontier, fidx,
                       fslice, fcount):
    """One frontier-sparse wave round: exactly k·W gathered slots via the
    source-CSR view, whatever the degree distribution. Returns
    ``(seen, frontier, fidx, fslice, icount, node_count, msgs)``."""
    n_pad = graph.n_nodes_padded
    pad_node = n_pad - 1

    fvalid = jnp.arange(k) < fcount
    f = jnp.where(fvalid, fidx, pad_node)
    # Each frontier node owns exactly one slice-0 item (empty rows
    # included, _row_items), so counting out_degree through those matches
    # frontier_messages' dense accounting send for send. Must read the
    # INCOMING lists — fidx/fslice are rebuilt for the next round below.
    msgs = jnp.sum(jnp.where(fvalid & (fslice == 0), graph.out_degree[f], 0))
    eid, in_row = graph.gather_row_slots(
        graph.src_offsets[f] + fslice * w,  # [k] slice start
        graph.src_offsets[f + 1], w,  # [k] build-time row end
    )
    svalid = in_row & fvalid[:, None]
    # Runtime liveness re-check: failed edges (sim/failures.py) stay in
    # the build-time CSR rows but are masked here.
    evalid = svalid & graph.edge_mask[eid]
    cand = jnp.where(evalid, graph.receivers[eid], pad_node).reshape(-1)
    fresh = evalid.reshape(-1) & ~seen[cand] & graph.node_mask[cand]

    # Dynamic (runtime-connected) out-edges ride along: the region is a
    # small unsorted COO block, scanned whole.
    if graph.dyn_senders is not None:
        dsend = frontier[graph.dyn_senders] & graph.dyn_mask
        dcand = jnp.where(dsend, graph.dyn_receivers, pad_node)
        dfresh = dsend & ~seen[dcand] & graph.node_mask[dcand]
        cand = jnp.concatenate([cand, dcand])
        fresh = jnp.concatenate([fresh, dfresh])

    # First-claim dedup: every fresh slot claims its candidate with its
    # position; winners are the slots that hold the minimum claim, so
    # each newly-seen node appears in the next frontier exactly once.
    order = jnp.arange(cand.shape[0], dtype=jnp.int32)
    big = jnp.int32(2**31 - 1)
    claim = jnp.where(fresh, order, big)
    scratch = jnp.full(n_pad, big, dtype=jnp.int32).at[cand].min(
        claim, mode="drop"
    )
    winner = fresh & (scratch[cand] == order)
    node_count = jnp.sum(winner).astype(jnp.int32)

    seen = seen.at[jnp.where(fresh, cand, n_pad)].set(True, mode="drop")
    new_frontier = (
        jnp.zeros(n_pad, dtype=bool)
        .at[jnp.where(winner, cand, n_pad)].set(True, mode="drop")
    )
    # Next work-item lists: compact the winner nodes (O(k·w) nonzero over
    # the candidate slots, not O(N)), then expand into W-slices. A
    # node_count > k frontier truncates — but then icount > k too, dense
    # mode takes over, and the truncated lists are never read.
    pos = jnp.nonzero(winner, size=k, fill_value=cand.shape[0] - 1)[0]
    wnode = jnp.where(jnp.arange(k) < node_count, cand[pos], pad_node)
    fidx, fslice, icount = _expand_items(graph, w, k, wnode, node_count)
    # Guard the truncation case: cand[pos] repeats the fill slot when
    # node_count > k, which could alias a real node's row and undercount
    # icount back under k. Saturate instead so dense mode takes over.
    icount = jnp.where(node_count > k, jnp.int32(k + 1), icount)
    return seen, new_frontier, fidx, fslice, icount, node_count, msgs


def _dense_wave_round(graph: Graph, w: int, k: int, method: str, seen,
                      frontier, fidx, fslice):
    """One dense wave round (models/flood.py's masked OR), maintaining the
    sparse work-item lists on the crossing back under ``k`` items."""
    delivered = segment.propagate_or(graph, frontier, method)
    new = delivered & ~seen & graph.node_mask
    seen = seen | new
    node_count = jnp.sum(new).astype(jnp.int32)
    # Frontier out-edge mass in W-slice items — decides sparse re-entry:
    # a frontier of few-but-hub nodes stays dense. One item per node when
    # no row chunks (static, trace-time — the quasi-regular fast path);
    # otherwise an O(N) row-length pass, still small next to the propagate.
    if _one_item_per_node(graph, w):
        icount = node_count
    else:
        items_all = _row_items(graph, w, jnp.arange(graph.n_nodes_padded))
        icount = jnp.sum(jnp.where(new, items_all, 0)).astype(jnp.int32)

    # Re-enter sparse mode: pay the O(N) compaction only on the round
    # that crosses back under k items (lax.cond executes one branch).
    def compact(n):
        wnode = jnp.nonzero(
            n, size=k, fill_value=graph.n_nodes_padded - 1
        )[0].astype(jnp.int32)
        out_fidx, out_fslice, _ = _expand_items(graph, w, k, wnode,
                                                node_count)
        return out_fidx, out_fslice

    fidx, fslice = jax.lax.cond(
        icount <= k, compact, lambda n: (fidx, fslice), new)
    msgs = segment.frontier_messages(graph, frontier)
    return seen, new, fidx, fslice, icount, node_count, msgs


def _wave_seed(graph: Graph, source: int, k: int, slice_width: int,
               proto_name: str):
    """Validated seed shared by the adaptive protocols: the source's
    one-hot (masked by liveness), its work-item lists, and the item
    count."""
    base.validate_source(graph, source)
    if graph.src_eid is None:
        raise ValueError(
            f"{proto_name} requires a source-CSR graph — build with "
            f"from_edges(source_csr=True) or graph.with_source_csr()"
        )
    w = _slice_width(graph, slice_width)
    seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[source].set(True)
    seed = seed & graph.node_mask
    wnode = jnp.full(k, graph.n_nodes_padded - 1, dtype=jnp.int32)
    wnode = wnode.at[0].set(source)
    node_count = jnp.sum(seed).astype(jnp.int32)
    fidx, fslice, icount = _expand_items(graph, w, k, wnode, node_count)
    return seed, fidx, fslice, icount


def _wave_step(graph: Graph, k: int, slice_width: int, method: str, seen,
               frontier, fidx, fslice, fcount):
    """Adaptive wave round: lax.cond picks sparse vs dense by the live
    frontier's out-edge mass in work items. Shared by AdaptiveFlood and
    AdaptiveHopDistance."""
    w = _slice_width(graph, slice_width)
    return jax.lax.cond(
        fcount <= k,
        lambda s, f, i, sl: _sparse_wave_round(graph, w, k, s, f, i, sl,
                                               fcount),
        lambda s, f, i, sl: _dense_wave_round(graph, w, k, method, s, f,
                                              i, sl),
        seen, frontier, fidx, fslice,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AdaptiveHopDistanceState:
    dist: jax.Array  # i32[N_pad] — BFS hops from source, -1 = not reached
    frontier: jax.Array  # bool[N_pad]
    fidx: jax.Array  # i32[k]
    fslice: jax.Array  # i32[k]
    fcount: jax.Array  # i32[] — item count (W-slice out-edge mass)
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class AdaptiveHopDistance:
    """BFS hop distances with frontier-sparse small rounds — the adaptive
    twin of models/hopdist.py (the wave IS the flood wave; nodes record the
    first round that reaches them), bit-identical to it round for round."""

    source: int = 0
    method: str = "auto"
    k: int = 1024
    slice_width: int = 0

    def init(self, graph: Graph, key: jax.Array) -> AdaptiveHopDistanceState:
        seed, fidx, fslice, count = _wave_seed(
            graph, self.source, self.k, self.slice_width,
            "AdaptiveHopDistance")
        return AdaptiveHopDistanceState(
            dist=jnp.where(seed, 0, -1).astype(jnp.int32), frontier=seed,
            fidx=fidx, fslice=fslice, fcount=count, round=jnp.int32(0),
        )

    def coverage(self, graph: Graph, state) -> jax.Array:
        """Reached fraction of live nodes (hopdist.py parity)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum((state.dist >= 0) & graph.node_mask) / n_real

    def step(self, graph: Graph, state: AdaptiveHopDistanceState,
             key: jax.Array):
        seen = state.dist >= 0
        seen2, frontier, fidx, fslice, fcount, ncount, msgs = _wave_step(
            graph, self.k, self.slice_width, self.method,
            seen, state.frontier, state.fidx, state.fslice, state.fcount,
        )
        rnd = state.round + 1
        dist = jnp.where(frontier, rnd, state.dist)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        reached = (dist >= 0) & graph.node_mask
        stats = {
            "messages": msgs,
            "coverage": jnp.sum(reached) / n_real,
            "frontier": ncount,
            "frontier_occupancy": frontier_ops.occupancy(graph, frontier),
            "max_dist": jnp.max(dist),
        }
        return AdaptiveHopDistanceState(dist=dist, frontier=frontier,
                                        fidx=fidx, fslice=fslice,
                                        fcount=fcount, round=rnd), stats
