"""Bracha reliable broadcast — Byzantine-tolerant delivery, batched.

The reference's trust model is "every peer is honest": one spoofed
message is delivered like any other [ref: p2pnetwork/nodeconnection.py:216
— no authentication or voting anywhere; the handshake is self-described
"not secure", p2pnetwork/node.py:148]. The canonical repair in the
distributed-systems literature is Bracha's reliable broadcast (1987):
with ``n >= 3f + 1`` nodes of which at most ``f`` are Byzantine, every
honest node delivers the SAME value (agreement) and if the broadcaster
is honest that value is the broadcaster's (validity), despite
equivocation. The three-message-type state machine, batched per round:

- round 1, INITIAL: the broadcaster's value reaches its out-neighbors;
- on INITIAL(v): send ECHO(v) — at most one value, ever;
- on ``2f+1`` ECHO(v) or ``f+1`` READY(v): send READY(v) — at most one;
- on ``2f+1`` READY(v): deliver v.

The value domain is binary ({0, 1}), which is where equivocation lives;
each threshold check is one ``propagate_sum`` per value over the graph
(ops/segment.py — indicator sums, exact in every lowering).

**The adversary is part of the model.** ``byzantine`` is a static tuple
of node ids running a deterministic worst-case-flavored strategy: from
round 1 on, every Byzantine node sends ECHO(r % 2) and READY(r % 2) to
each neighbor r — maximal equivocation, splitting the population by id
parity; a Byzantine BROADCASTER likewise sends INITIAL(r % 2). Because
the strategy factorizes by receiver, its contribution to r's count for
value v is ``(r % 2 == v) * |byzantine in-neighbors of r|`` — one
propagate_sum of the Byzantine mask, paid at ``init`` and carried in
the state (``byz_in``, like the broadcaster's reach ``from_src``).
Byzantine nodes never deliver (their state is not meaningful).

Guarantees hold on the complete topology Bracha assumes
(sim/graph.complete); the protocol runs on any graph, where sparse
connectivity weakens it exactly as it would a real deployment (the
quorum-connectivity literature's territory, not modeled here).

Quiescence: ``engine.run_until_converged(..., stat="changed",
threshold=1)``; ``coverage`` (honest delivered fraction) also supports
``run_until_coverage``. Deterministic — no RNG consumed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BrachaState:
    echo_sent: jax.Array  # bool[N_pad, 2] — ECHO(v) broadcast (honest: <=1 col)
    ready_sent: jax.Array  # bool[N_pad, 2] — READY(v) broadcast (<=1 col)
    value: jax.Array  # i32[N_pad] — delivered value; -1 undelivered/Byzantine
    round: jax.Array  # i32[]
    # Round-invariant propagations, paid once at init instead of per step:
    byz_in: jax.Array  # f32[N_pad] — Byzantine in-neighbor count
    from_src: jax.Array  # bool[N_pad] — broadcaster reaches this node (+self)


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Bracha:
    """Byzantine reliable broadcast with a parity-equivocating adversary.

    ``f`` sets the quorum thresholds (2f+1 / f+1); it is the TOLERANCE
    the deployment is sized for, independent of how many ids are actually
    listed in ``byzantine`` (listing more than f voids the guarantees,
    as it must)."""

    source: int = 0
    source_value: int = 1
    f: int = 1
    byzantine: tuple = ()
    method: str = "auto"

    def __post_init__(self):
        if self.source_value not in (0, 1):
            raise ValueError("source_value must be 0 or 1")
        if self.f < 0:
            raise ValueError("f must be >= 0")

    def _byz_mask(self, graph: Graph) -> jax.Array:
        m = jnp.zeros(graph.n_nodes_padded, dtype=bool)
        if self.byzantine:
            ids = jnp.asarray(self.byzantine, dtype=jnp.int32)
            m = m.at[ids].set(True)
        return m & graph.node_mask

    def init(self, graph: Graph, key: jax.Array) -> BrachaState:
        base.validate_source(graph, self.source)
        for b in self.byzantine:
            if not 0 <= b < graph.n_nodes_padded:
                # Same silent-clamp hazard validate_source exists for: an
                # out-of-range id would scatter to a masked padded slot and
                # the adversary would quietly not exist.
                raise ValueError(
                    f"byzantine id {b} out of range for padded id space "
                    f"[0, {graph.n_nodes_padded})")
        n_pad = graph.n_nodes_padded
        byz = self._byz_mask(graph)
        src_hot = jnp.zeros(n_pad, dtype=bool).at[self.source].set(True)
        src_hot = src_hot & graph.node_mask
        one = lambda sig: segment.propagate_sum(  # noqa: E731
            graph, sig.astype(jnp.float32), self.method)
        return BrachaState(
            echo_sent=jnp.zeros((n_pad, 2), dtype=bool),
            ready_sent=jnp.zeros((n_pad, 2), dtype=bool),
            value=jnp.full(n_pad, -1, dtype=jnp.int32),
            round=jnp.int32(0),
            byz_in=one(byz),
            # Everyone "sends to itself" too (standard quorum counting —
            # the arithmetic at n = 3f+1 exactly needs the node's own
            # vote): the source receives its own INITIAL, and own
            # ECHO/READY count in step().
            from_src=(one(src_hot) > 0) | src_hot,
        )

    def coverage(self, graph: Graph, state: BrachaState) -> jax.Array:
        """Delivered fraction of live HONEST nodes."""
        honest = graph.node_mask & ~self._byz_mask(graph)
        n = jnp.maximum(jnp.sum(honest), 1)
        return jnp.sum((state.value >= 0) & honest) / n

    def step(self, graph: Graph, state: BrachaState, key: jax.Array):
        n_pad = graph.n_nodes_padded
        ids = jnp.arange(n_pad, dtype=jnp.int32)
        parity = ids % 2
        byz = self._byz_mask(graph)
        honest = graph.node_mask & ~byz
        rnd = state.round + 1

        one = lambda sig: segment.propagate_sum(  # noqa: E731
            graph, sig.astype(jnp.float32), self.method)
        # Byzantine in-neighbor count per receiver (state.byz_in, computed
        # once at init): their ECHO/READY for value v lands exactly on
        # receivers with parity v, every round.
        byz_for = jnp.stack([jnp.where(parity == 0, state.byz_in, 0.0),
                             jnp.where(parity == 1, state.byz_in, 0.0)],
                            axis=1)

        # INITIAL: round 1 only. Honest source sends source_value to all
        # out-neighbors; a Byzantine source equivocates by parity (its
        # byz_for share already counts its ECHO/READY, but INITIAL is a
        # separate message type). Reachability is state.from_src from init.
        src_is_byz = byz[self.source]
        init_val = jnp.where(src_is_byz, parity,
                             jnp.int32(self.source_value))
        got_initial = state.from_src & (rnd == 1)
        initial = jnp.stack([got_initial & (init_val == 0),
                             got_initial & (init_val == 1)], axis=1)

        def counted(sent):
            own = (sent & honest[:, None]).astype(jnp.float32)
            return jnp.stack([one(sent[:, 0] & honest),
                              one(sent[:, 1] & honest)],
                             axis=1) + byz_for + own

        echo_cnt = counted(state.echo_sent)
        ready_cnt = counted(state.ready_sent)

        q_echo = jnp.float32(2 * self.f + 1)
        q_amp = jnp.float32(self.f + 1)
        q_deliver = jnp.float32(2 * self.f + 1)

        # ECHO: on INITIAL(v), if never echoed (honest discipline).
        never_echoed = ~jnp.any(state.echo_sent, axis=1)
        new_echo = initial & never_echoed[:, None] & honest[:, None]
        echo_sent = state.echo_sent | new_echo

        # READY: quorum of ECHOs or amplification quorum of READYs, at
        # most one value ever; simultaneous crossings break toward the
        # larger count, then value 0.
        ready_ok = (echo_cnt >= q_echo) | (ready_cnt >= q_amp)
        never_ready = ~jnp.any(state.ready_sent, axis=1)
        pick1 = ready_ok[:, 1] & (~ready_ok[:, 0]
                                  | (ready_cnt[:, 1] > ready_cnt[:, 0]))
        pick = jnp.stack([ready_ok[:, 0] & ~pick1, pick1], axis=1)
        new_ready = pick & never_ready[:, None] & honest[:, None]
        ready_sent = state.ready_sent | new_ready

        # DELIVER: 2f+1 READYs; an honest node delivers once. Both values
        # crossing at once means the Byzantine count exceeded f — pick 0
        # deterministically rather than hide it.
        deliver = (ready_cnt >= q_deliver) & (state.value == -1)[:, None] \
            & honest[:, None]
        value = jnp.where(deliver[:, 0], 0,
                          jnp.where(deliver[:, 1], 1, state.value))

        new_state = BrachaState(echo_sent=echo_sent, ready_sent=ready_sent,
                                value=value, round=rnd,
                                byz_in=state.byz_in, from_src=state.from_src)
        any0 = jnp.any((value == 0) & honest)
        any1 = jnp.any((value == 1) & honest)
        changed = (jnp.sum(new_echo) + jnp.sum(new_ready)
                   + jnp.sum(value != state.value))
        out_deg = graph.out_degree.astype(jnp.float32)
        stats = {
            "messages": (jnp.sum(jnp.any(new_echo, axis=1) * out_deg)
                         + jnp.sum(jnp.any(new_ready, axis=1) * out_deg)
                         + jnp.where(rnd == 1, out_deg[self.source], 0.0)
                         + jnp.sum(jnp.where(byz, out_deg, 0.0))),
            "changed": changed,
            "delivered": jnp.sum((value >= 0) & honest),
            "coverage": self.coverage(graph, new_state),
            "agreement": (~(any0 & any1)).astype(jnp.int32),
        }
        return new_state, stats
