"""Betweenness centrality — batched Brandes on device.

*Which peers does the traffic actually flow through?* — the question
behind relay selection, hub hardening, and attack-surface analysis of
an overlay; on the reference, users could only answer it by exporting
their topology to an external tool (the library computes nothing,
README.md:20). Brandes' algorithm (2001) computes exact betweenness in
O(S·E) for S sources: per source, a BFS forward pass counting shortest
paths (``sigma``), then a reverse layer sweep accumulating pair
dependencies ``delta[v] = Σ_succ sigma[v]/sigma[w]·(1+delta[w])``.

TPU form: both passes are per-layer ``propagate_sum`` calls inside
device-side ``while_loop``s — the forward wave is the HopDistance BFS
with a path-count payload, and the reverse sweep reuses the SAME
propagation direction by flipping the layer filter (on the symmetric
edge sets the builders produce, ``w`` is a BFS-successor of ``v`` iff
the stored edge ``w→v`` has ``d[w] == d[v]+1`` — so "pull from my
successors" is an ordinary in-edge sum with a sender-side layer mask,
no reverse-CSR needed). Sources accumulate through a ``lax.scan``, so
peak memory is O(N) regardless of sample size.

Exact when ``sources`` is every live node; for large graphs pass a
uniform sample — the classic Brandes–Pich estimator: dependencies are
summed over sampled sources only, and ``normalized=True`` rescales by
``n_live / S`` into an unbiased estimate of the full directed-sum
betweenness. (On undirected graphs the directed sum counts each
unordered pair twice — halve to match conventions that don't,
e.g. networkx's unnormalized undirected values.)

Works on any aggregation lowering; requires symmetric edges (the
undirected contract the builders satisfy), documented rather than
checked — asymmetric edge sets yield a directed-graph forward pass with
a wrong reverse sweep.

Numeric bound: path counts accumulate in f32, so ``sigma`` is exact
only up to 2^24 paths and overflows to inf near 3.4e38 — lattice-like
graphs reach astronomical shortest-path multiplicities at modest
diameter (a grid has C(2k, k) paths at distance 2k), and past the
overflow the reverse sweep turns inf into NaN. Small-world / scale-free
overlays (this library's domain) have low multiplicity and are fine;
for grid-like topologies check ``jnp.isfinite`` on the result.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


def closeness_sample(graph: Graph, sources, method: str = "auto",
                     harmonic: bool = True,
                     normalized: bool = False) -> jax.Array:
    """Closeness centrality ``f32[N_pad]`` from BFS waves over
    ``sources`` — *which peers are nearest to everyone?* (replica
    placement's other half, beside :func:`betweenness_sample`'s relay
    question).

    On the symmetric edge sets the builders produce, ``d(s, v) =
    d(v, s)``, so accumulating each sampled source's distance field
    gives every node's distances TO the sample. ``harmonic=True``
    (default) sums ``1/d`` — Boldi–Vigna harmonic centrality, finite
    and meaningful on disconnected graphs where classic closeness
    degenerates; ``harmonic=False`` returns ``reached / sum(d)`` over
    the sampled sources (classic closeness restricted to reached
    pairs). ``normalized=True`` rescales the harmonic sum by
    ``n_live / S_live`` (live sources only in the divisor, like
    :func:`betweenness_sample`) — the unbiased full-graph estimate.
    Exact when ``sources`` is every live node. Deterministic."""
    if normalized and not harmonic:
        raise ValueError(
            "normalized=True is defined for the harmonic estimator only "
            "(classic closeness has no unbiased sampled rescale here)")
    from p2pnetwork_tpu.models.hopdist import bfs_distances

    sources = jnp.asarray(sources, dtype=jnp.int32)
    n_pad = graph.n_nodes_padded

    def one_source(carry, src):
        inv_sum, d_sum, reach = carry
        alive_src = graph.node_mask[src]
        d = bfs_distances(graph, src, method)
        hit = (d > 0) & alive_src  # excludes the source itself
        df = d.astype(jnp.float32)
        inv_sum = inv_sum + jnp.where(hit, 1.0 / jnp.maximum(df, 1.0), 0.0)
        d_sum = d_sum + jnp.where(hit, df, 0.0)
        reach = reach + hit.astype(jnp.float32)
        return (inv_sum, d_sum, reach), None

    zeros = jnp.zeros(n_pad, jnp.float32)
    (inv_sum, d_sum, reach), _ = jax.lax.scan(
        one_source, (zeros, zeros, zeros), sources)
    if harmonic:
        out = inv_sum
        if normalized:
            n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
            s_live = jnp.maximum(jnp.sum(graph.node_mask[sources]), 1)
            out = out * (n_live.astype(jnp.float32)
                         / s_live.astype(jnp.float32))
    else:
        out = jnp.where(d_sum > 0, reach / jnp.maximum(d_sum, 1.0), 0.0)
    return out * graph.node_mask


def betweenness_sample(graph: Graph, sources, method: str = "auto",
                       normalized: bool = False) -> jax.Array:
    """Accumulated Brandes dependencies ``f32[N_pad]`` over ``sources``.

    ``normalized=True`` rescales the sampled sum by ``n_live / S_live``
    where ``S_live`` counts the LIVE sources in the sample (dead sources
    contribute no dependencies, so counting them in the divisor would
    deflate the estimate on churned graphs) — the unbiased full-graph
    estimate under uniform sampling of either frame."""
    sources = jnp.asarray(sources, dtype=jnp.int32)
    n_pad = graph.n_nodes_padded

    def one_source(bc, src):
        alive_src = graph.node_mask[src]
        seed = jnp.zeros(n_pad, dtype=bool).at[src].set(True)
        seed = seed & graph.node_mask
        d0 = jnp.where(seed, 0, -1).astype(jnp.int32)
        sigma0 = jnp.where(seed, 1.0, 0.0).astype(jnp.float32)

        # Forward: BFS layers with path counting. sigma[v] = sum of
        # sigma over frontier in-neighbors, assigned the round v is
        # first reached.
        def fcond(carry):
            _, _, frontier, _ = carry
            return jnp.any(frontier)

        def fbody(carry):
            d, sigma, frontier, layer = carry
            contrib = segment.propagate_sum(
                graph, sigma * frontier.astype(jnp.float32), method)
            # contrib > 0 IS delivery: every frontier node carries
            # sigma >= 1 (by induction from the seed), and f32 sums of
            # >= 1 terms can't vanish — no second edge sweep needed.
            new = (contrib > 0) & (d < 0) & graph.node_mask
            d = jnp.where(new, layer + 1, d)
            sigma = sigma + jnp.where(new, contrib, 0.0)
            return d, sigma, new, layer + 1

        d, sigma, _, maxlayer = jax.lax.while_loop(
            fcond, fbody, (d0, sigma0, seed, jnp.int32(0)))

        # Reverse: dependency accumulation, deepest layer first. The
        # sender-side mask picks BFS-successors (d == L); the
        # receiver-side mask lands the sum on their predecessors
        # (d == L - 1) — edges inside one layer satisfy neither.
        def bcond(carry):
            _, L = carry
            return L >= 1

        def bbody(carry):
            delta, L = carry
            coef = jnp.where((d == L) & (sigma > 0),
                             (1.0 + delta) / jnp.maximum(sigma, 1.0),
                             0.0)
            acc = segment.propagate_sum(graph, coef, method)
            delta = delta + jnp.where(d == L - 1, sigma * acc, 0.0)
            return delta, L - 1

        delta, _ = jax.lax.while_loop(
            bcond, bbody, (jnp.zeros(n_pad, jnp.float32), maxlayer))
        delta = jnp.where(seed, 0.0, delta)  # bc sums over v != source
        return bc + jnp.where(alive_src, delta, 0.0), None

    bc, _ = jax.lax.scan(one_source, jnp.zeros(n_pad, jnp.float32), sources)
    if normalized:
        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        s_live = jnp.maximum(jnp.sum(graph.node_mask[sources]), 1)
        bc = bc * (n_live.astype(jnp.float32) / s_live.astype(jnp.float32))
    return bc
