"""Protocol models for the simulation backend.

The reference ships no protocols — users implement flooding/gossip/etc. in
``node_message`` overrides [ref: README.md:20]. These are the batched,
TPU-native forms of the protocols its users write by hand, all behind one
``Protocol`` seam (models/base.py)."""

from p2pnetwork_tpu.models.adaptive_flood import (
    AdaptiveFlood,
    AdaptiveFloodState,
    AdaptiveHopDistance,
    AdaptiveHopDistanceState,
)
from p2pnetwork_tpu.models.antientropy import AntiEntropy, AntiEntropyState
from p2pnetwork_tpu.models.base import Protocol
from p2pnetwork_tpu.models.bipartite import BipartiteCheck, BipartiteCheckState
from p2pnetwork_tpu.models.boruvka import Boruvka, BoruvkaState
from p2pnetwork_tpu.models.bracha import Bracha, BrachaState
from p2pnetwork_tpu.models.centrality import (
    betweenness_sample,
    closeness_sample,
)
from p2pnetwork_tpu.models.coloring import color_via_mis
from p2pnetwork_tpu.models.detector import (
    FailureDetector,
    FailureDetectorState,
)
from p2pnetwork_tpu.models.components import (
    ConnectedComponents,
    ConnectedComponentsState,
)
from p2pnetwork_tpu.models.flood import Flood, FloodState
from p2pnetwork_tpu.models.messagebatch import (
    BatchFlood,
    LaneExhausted,
    MessageBatch,
    free_lane_count,
    lane_frontier,
    lane_messages,
    lane_seen,
)
from p2pnetwork_tpu.models.querybatch import (
    DhtLookups,
    LaneBudgetExceeded,
    MinPlusQueries,
    PushSumQueries,
    QueryBatch,
    free_query_lanes,
    lane_dist,
)
from p2pnetwork_tpu.models.gossip import Gossip, GossipState
from p2pnetwork_tpu.models.hits import HITS, HITSState
from p2pnetwork_tpu.models.hopdist import (
    HopDistance,
    HopDistanceState,
    diameter_bounds,
    eccentricities,
)
from p2pnetwork_tpu.models.kcore import KCore, KCoreState
from p2pnetwork_tpu.models.labelprop import (
    LabelPropagation,
    LabelPropagationState,
)
from p2pnetwork_tpu.models.leader import LeaderElection, LeaderElectionState
from p2pnetwork_tpu.models.mis import LubyMIS, LubyMISState
from p2pnetwork_tpu.models.pagerank import PageRank, PageRankState
from p2pnetwork_tpu.models.plumtree import Plumtree, PlumtreeState
from p2pnetwork_tpu.models.pushsum import PushSum, PushSumState
from p2pnetwork_tpu.models.routing import DistanceVector, DistanceVectorState
from p2pnetwork_tpu.models.sir import SIR, SIRState
from p2pnetwork_tpu.models.spanning import SpanningTree, SpanningTreeState
from p2pnetwork_tpu.models.triangles import (
    count_triangles,
    local_clustering,
    transitivity,
    transitivity_sample,
    triangles_per_node,
)
from p2pnetwork_tpu.models.vivaldi import Vivaldi, VivaldiState
from p2pnetwork_tpu.models.walk import RandomWalks, RandomWalksState

__all__ = [
    "Protocol",
    "betweenness_sample",
    "closeness_sample",
    "color_via_mis",
    "count_triangles",
    "diameter_bounds",
    "eccentricities",
    "local_clustering",
    "transitivity",
    "transitivity_sample",
    "triangles_per_node",
    "free_lane_count",
    "free_query_lanes",
    "lane_dist",
    "lane_frontier",
    "lane_messages",
    "lane_seen",
    "AdaptiveFlood",
    "AdaptiveFloodState",
    "AntiEntropy",
    "AntiEntropyState",
    "BatchFlood",
    "DhtLookups",
    "LaneBudgetExceeded",
    "LaneExhausted",
    "MessageBatch",
    "MinPlusQueries",
    "PushSumQueries",
    "QueryBatch",
    "AdaptiveHopDistance",
    "AdaptiveHopDistanceState",
    "BipartiteCheck",
    "BipartiteCheckState",
    "Boruvka",
    "BoruvkaState",
    "Bracha",
    "BrachaState",
    "ConnectedComponents",
    "ConnectedComponentsState",
    "DistanceVector",
    "DistanceVectorState",
    "FailureDetector",
    "FailureDetectorState",
    "Flood",
    "FloodState",
    "Gossip",
    "GossipState",
    "HITS",
    "HITSState",
    "HopDistance",
    "HopDistanceState",
    "KCore",
    "KCoreState",
    "LabelPropagation",
    "LabelPropagationState",
    "LeaderElection",
    "LeaderElectionState",
    "LubyMIS",
    "LubyMISState",
    "PageRank",
    "PageRankState",
    "Plumtree",
    "PlumtreeState",
    "PushSum",
    "PushSumState",
    "RandomWalks",
    "RandomWalksState",
    "SIR",
    "SIRState",
    "SpanningTree",
    "SpanningTreeState",
    "Vivaldi",
    "VivaldiState",
]
