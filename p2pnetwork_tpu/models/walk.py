"""Batched random walks: the peer-sampling / discovery protocol family.

Discovery is the canonical protocol the reference tells users to build
themselves [ref: README.md:20, GETTING_STARTED.md:9 — "no protocol"]:
Cyclon/Brahms-style services walk the overlay to collect uniform peer
samples; crawlers walk it to map membership. A reference node forwards a
walk by picking one neighbor in ``node_message`` and calling
``send_to_node``; here a whole cohort of ``n_walkers`` walkers advances
in one batched step — gather each walker's out-edge row through the
source-CSR view, draw a uniform LIVE edge per walker, move.

Semantics per round, per walker:

- uniform choice among the walker's currently-live out-edges (runtime
  edge liveness via ``edge_mask``; dead receivers excluded — churn
  needs no rebuild, mirroring the adaptive flood's liveness re-check);
- a walker whose node has no live out-edge STAYS PUT (a crawler stuck in
  a sink keeps retrying — matching the reference node whose sends all
  failed [ref: nodeconnection.py:123-126 close-on-error]);
- with probability ``restart_p`` the walker teleports back to its start
  node instead (PPR-style restart — turns the cohort into a
  personalized sampler around its seeds).

``visited`` accumulates every node any walker has stood on, so
``coverage`` is discovery progress and ``engine.run_until_coverage``
answers "how many rounds until the cohort has mapped 99% of the
overlay". ``messages`` counts one send per moving walker per round (a
stay-put walker sends nothing).

The per-round gather is ``[n_walkers, max_out_span]`` — the row-width
cost of quasi-regular graphs is a handful of slots; on degree-skewed
families a hub widens every walker's row slice, the same skew tax the
flood lowerings pay (BENCH.md "auto" waste bound), so size cohorts
accordingly there.

Requires a graph built with ``source_csr=True`` (or
``with_source_csr()``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.utils.edgehash import edge_uniform


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RandomWalksState:
    pos: jax.Array  # i32[W] — each walker's current node
    start: jax.Array  # i32[W] — restart target (initial position)
    visited: jax.Array  # bool[N_pad] — any walker has stood here


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class RandomWalks:
    """``n_walkers`` uniform random walkers with optional restart.

    ``init`` seeds walkers on distinct live nodes (evenly spread when
    more live nodes than walkers exist; wrapping otherwise)."""

    n_walkers: int = 1024
    restart_p: float = 0.0

    def __post_init__(self):
        if self.n_walkers < 1:
            raise ValueError(f"n_walkers must be >= 1, got {self.n_walkers}")
        if not 0.0 <= self.restart_p <= 1.0:
            raise ValueError(f"restart_p must be in [0, 1], got {self.restart_p}")

    def _require_csr(self, graph: Graph) -> None:
        if graph.src_eid is None:
            raise ValueError(
                "RandomWalks requires a source-CSR graph — build with "
                "from_edges(source_csr=True) or graph.with_source_csr()"
            )

    def init(self, graph: Graph, key: jax.Array) -> RandomWalksState:
        self._require_csr(graph)
        # Evenly spread over the live nodes: stride walker w to the
        # (w * stride mod n_live)-th live id — deterministic, wraps when
        # W exceeds the live population, and stays in int32 (w * stride
        # <= n_live * W / W; a w*n_live/W spread would overflow at 10M
        # nodes x 1K walkers).
        live_ids = jnp.nonzero(
            graph.node_mask, size=graph.n_nodes_padded, fill_value=0
        )[0]
        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stride = jnp.maximum(n_live // self.n_walkers, 1)
        w = jnp.arange(self.n_walkers)
        pos = live_ids[(w * stride) % n_live].astype(jnp.int32)
        visited = (
            jnp.zeros(graph.n_nodes_padded, dtype=bool)
            .at[pos].set(True)
            & graph.node_mask
        )
        return RandomWalksState(pos=pos, start=pos, visited=visited)

    def coverage(self, graph: Graph, state: RandomWalksState) -> jax.Array:
        """Fraction of live nodes some walker has visited."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum(state.visited & graph.node_mask) / n_real

    def step(self, graph: Graph, state: RandomWalksState, key: jax.Array):
        self._require_csr(graph)
        w = max(graph.max_out_span, 1)
        k_edge, k_restart = jax.random.split(key)

        # Each walker's out-edge row, liveness-masked [W, w].
        eid, svalid = graph.gather_row_slots(
            graph.src_offsets[state.pos],
            graph.src_offsets[state.pos + 1], w,
        )
        rcv = graph.receivers[eid]
        live = svalid & graph.edge_mask[eid] & graph.node_mask[rcv]

        # Dynamic (runtime-connected) out-edges ride along: the region is
        # a small unsorted COO block, membership-tested per walker
        # ([W, D] compare — size cohorts to the reserved capacity), so a
        # runtime bridge is walkable the round it appears.
        if graph.dyn_senders is not None:
            dmember = (
                (graph.dyn_senders[None, :] == state.pos[:, None])
                & graph.dyn_mask[None, :]
                & graph.node_mask[graph.dyn_receivers][None, :]
            )
            rcv = jnp.concatenate(
                [rcv, jnp.broadcast_to(graph.dyn_receivers[None, :],
                                       dmember.shape)], axis=1)
            live = jnp.concatenate([live, dmember], axis=1)

        # Uniform live choice by max-u, where each candidate's u is keyed
        # by the EDGE IDENTITY (round key, walker, sender, receiver —
        # utils/edgehash.py), not its slot: any party naming the same
        # edge draws the same number, which is what lets the sharded ring
        # (parallel/sharded.py walk) reproduce this choice bit-for-bit
        # with the edges scattered across shards. Equal-u ties (2^-24)
        # break on the higher receiver id — deterministic on every
        # layout. Dead pos rows gather only dead slots, so live is all
        # False there and the walker stays put.
        walkers = jnp.arange(self.n_walkers, dtype=jnp.int32)
        u = edge_uniform(k_edge, walkers[:, None], state.pos[:, None], rcv)
        u = jnp.where(live, u, -1.0)
        m = jnp.max(u, axis=1)
        can_move = m >= 0.0
        best_rcv = jnp.max(
            jnp.where(live & (u == m[:, None]), rcv, -1), axis=1
        )
        dest = jnp.where(can_move, best_rcv, state.pos)

        if self.restart_p > 0.0:
            # Restart wins over the edge move; a dead start (churn) falls
            # back to the edge move so walkers never stand on dead nodes.
            restart = (
                (jax.random.uniform(k_restart, (self.n_walkers,))
                 < self.restart_p)
                & graph.node_mask[state.start]
            )
            dest = jnp.where(restart, state.start, dest)
            moved = (restart | can_move) & (dest != state.pos)
        else:
            moved = can_move & (dest != state.pos)

        visited = state.visited.at[dest].set(True) & graph.node_mask
        new_state = RandomWalksState(pos=dest, start=state.start,
                                     visited=visited)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            # One send per walker that actually moved [ref: node.py:116
            # message_count_send — the reference counts sends, and a
            # stuck walker sends nothing].
            "messages": jnp.sum(moved),
            "coverage": jnp.sum(visited & graph.node_mask) / n_real,
            "stuck": jnp.sum(~can_move),
        }
        return new_state, stats
