"""Distance-vector routing: weighted shortest paths + next-hop tables.

THE routing question of a P2P overlay — *what is the cheapest path to
this peer, and which neighbor do I forward through* — which reference
users implement as RIP-style hand-rolled relays on ``node_message``
(re-broadcasting advertised costs and keeping the best [ref:
README.md:20, p2pnetwork/node.py:110-116]). Batched TPU form: the whole
population's Bellman-Ford relaxation is one ``propagate_min_plus`` per
round (ops/segment.py — the tropical-semiring sibling of the max flood),
with the frontier optimization every distance-vector protocol has
implicitly: only nodes whose cost improved last round advertise.

At quiescence (``engine.run_until_converged(..., stat="changed",
threshold=1)``) ``state.dist`` holds exact single-source shortest-path
costs over ``graph.edge_weight`` (unit costs when unweighted — then this
IS HopDistance, in f32), and ``state.parent`` a deterministic OPTIMAL
next hop: an in-neighbor achieving the optimum, i.e. where node v
forwards traffic TOWARD the source on the symmetric graphs the builders
produce (-1 at the source / unreached). ``state.parent`` breaks
equal-cost ties by lowest id among the advertisers of the round the
node last improved — an achiever that settles in a LATER round never
advertises an improvement, so it cannot win retroactively; for the
canonical globally-lowest-id-achiever table, :meth:`DistanceVector.
next_hops` recomputes the tie-break against the converged costs in one
O(E) pass. Negative weights
converge too while no negative cycle is reachable; ``max_rounds`` is the
guard, as everywhere.

Dynamic runtime links participate at ``segment.DYNAMIC_LINK_COST``
(unit) until consolidated. Deterministic — no RNG consumed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph

_I32_MAX = jnp.iinfo(jnp.int32).max


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DistanceVectorState:
    dist: jax.Array  # f32[N_pad] — best known cost from source; +inf unreached
    parent: jax.Array  # i32[N_pad] — an optimal neighbor (see module
    #                    docstring for the tie-break); -1 none
    frontier: jax.Array  # bool[N_pad] — improved last round (advertisers)
    round: jax.Array  # i32[] — rounds executed so far


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class DistanceVector:
    """Single-source Bellman-Ford with next-hop extraction. ``method``
    picks the aggregation lowering (see ops/segment.propagate_min_plus)."""

    source: int = 0
    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> DistanceVectorState:
        base.validate_source(graph, self.source)
        seed = jnp.zeros(graph.n_nodes_padded, dtype=bool).at[self.source].set(True)
        seed = seed & graph.node_mask
        dist = jnp.where(seed, 0.0, jnp.inf).astype(jnp.float32)
        parent = jnp.full(graph.n_nodes_padded, -1, dtype=jnp.int32)
        return DistanceVectorState(dist=dist, parent=parent, frontier=seed,
                                   round=jnp.int32(0))

    def coverage(self, graph: Graph, state: DistanceVectorState) -> jax.Array:
        """Reached fraction of live nodes (run_until_coverage seed)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum(jnp.isfinite(state.dist) & graph.node_mask) / n_real

    def next_hops(self, graph: Graph,
                  state: DistanceVectorState) -> jax.Array:
        """Canonical routing table from a converged state: per reached
        non-source node, the globally LOWEST-id in-neighbor achieving
        ``dist[u] + w(u, v) == dist[v]`` (the tie-break ``state.parent``
        cannot promise across rounds — an equal-cost achiever that
        settles later never advertises an improvement); -1 at the source
        and unreached nodes. One O(E) pass."""
        best = self._parents(graph, state.dist, state.dist)
        return jnp.where(best == _I32_MAX, -1, best)

    def _parents(self, graph: Graph, signal: jax.Array,
                 incoming: jax.Array) -> jax.Array:
        """Lowest-id sender whose relaxation achieves ``incoming`` — the
        same f32 add re-evaluated on the edge layout compares bitwise
        equal to the aggregation's pick, whichever lowering produced it."""
        w = graph.edge_weight if graph.edge_weight is not None else 1.0
        contrib = jnp.where(graph.edge_mask, signal[graph.senders] + w,
                            jnp.inf)
        hit = (contrib == incoming[graph.receivers]) & jnp.isfinite(contrib)
        cand = jnp.where(hit, graph.senders, _I32_MAX)
        best = jax.ops.segment_min(
            cand, graph.receivers, num_segments=graph.n_nodes_padded,
            indices_are_sorted=True)
        if graph.dyn_senders is not None:
            dcontrib = jnp.where(
                graph.dyn_mask,
                signal[graph.dyn_senders] + segment.DYNAMIC_LINK_COST,
                jnp.inf)
            dhit = ((dcontrib == incoming[graph.dyn_receivers])
                    & jnp.isfinite(dcontrib))
            dcand = jnp.where(dhit, graph.dyn_senders, _I32_MAX)
            best = jnp.minimum(best, jax.ops.segment_min(
                dcand, graph.dyn_receivers,
                num_segments=graph.n_nodes_padded))
        return best

    def step(self, graph: Graph, state: DistanceVectorState, key: jax.Array):
        signal = jnp.where(state.frontier, state.dist, jnp.inf)
        incoming = segment.propagate_min_plus(graph, signal, self.method)
        improved = incoming < state.dist
        dist = jnp.where(improved, incoming, state.dist)
        parent = jnp.where(improved, self._parents(graph, signal, incoming),
                           state.parent)
        reached = jnp.isfinite(dist) & graph.node_mask
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            "messages": segment.frontier_messages(
                graph, state.frontier & graph.node_mask),
            "changed": jnp.sum(improved),
            "coverage": jnp.sum(reached) / n_real,
            "max_cost": jnp.max(jnp.where(reached, dist, -jnp.inf)),
        }
        new_state = DistanceVectorState(dist=dist, parent=parent,
                                        frontier=improved,
                                        round=state.round + 1)
        return new_state, stats
