"""Connected components / network-partition detection by label flooding.

A question reference users answer by hand-rolling discovery protocols on
the event hooks [ref: README.md:20 — the library "does not implement any
protocol"]: *is the overlay partitioned, and into how many pieces?* Every
node starts with its own id as its component label, repeatedly broadcasts
the highest live label it has heard, and adopts anything higher. At
quiescence each node holds the highest live id of its component, so the
number of distinct surviving labels — equivalently, the number of live
nodes still holding their own id — is the number of partitions.

This is the same propagation as :class:`~p2pnetwork_tpu.models.leader.
LeaderElection` (a leader election run *is* a partition labelling), but
the public contract differs: the stats expose ``components`` (current
count of label-maxima, i.e. partitions detected so far — monotonically
non-increasing as floods merge) and ``changed`` for the quiescence test.
Run with ``engine.run_until_converged(..., stat="changed", threshold=1)``;
at that point ``state.label`` is the exact component labelling and
``components`` the partition count.

Directed-graph semantics: labels flow along edge direction, so the
fixpoint groups nodes by "highest live id that can reach me". On the
symmetric graphs the builders produce (watts_strogatz, erdos_renyi,
barabasi_albert build undirected edge sets) this is exactly connected
components; on an asymmetric overlay it is the max-ancestor relation —
the same caveat the numpy oracle in tests/test_leader.py encodes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models.leader import max_flood_step
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ConnectedComponentsState:
    label: jax.Array  # i32[N_pad] — highest live id heard; -1 on dead nodes
    frontier: jax.Array  # bool[N_pad] — adopted a new label last round


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class ConnectedComponents:
    """Max-label flooding to a per-component fixpoint. ``method`` picks the
    aggregation lowering (``"auto"``/``"segment"``/``"gather"`` — see
    ops/segment.propagate_max)."""

    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> ConnectedComponentsState:
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        label = jnp.where(graph.node_mask, ids, -1)
        return ConnectedComponentsState(label=label, frontier=graph.node_mask)

    def components(self, graph: Graph,
                   state: ConnectedComponentsState) -> jax.Array:
        """Number of live nodes still labelled with their own id — at
        quiescence, exactly the number of connected components."""
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        return jnp.sum((state.label == ids) & graph.node_mask)

    def step(self, graph: Graph, state: ConnectedComponentsState,
             key: jax.Array):
        label, changed, msgs = max_flood_step(
            graph, state.label, state.frontier, self.method)
        new_state = ConnectedComponentsState(label=label, frontier=changed)
        stats = {
            "messages": msgs,
            "changed": jnp.sum(changed),
            "components": self.components(graph, new_state),
        }
        return new_state, stats
