"""Bipartiteness / odd-cycle detection by rooted parity flooding.

Another overlay-structure question reference users would answer by
hand-rolling a probe protocol on the event hooks [ref: README.md:20 — the
library "does not implement any protocol"]: *is the overlay 2-colorable*
(e.g. does a request/response role split hold globally), equivalently
*does it contain an odd cycle?*

The classical distributed answer is a rooted BFS 2-coloring per component.
Batched TPU form: run the same max-label flood as
:class:`~p2pnetwork_tpu.models.components.ConnectedComponents` while
recording, per node, the round of its LAST label adoption. Synchronous
max-flooding delivers the component's maximum id to a node at exactly its
BFS distance from that maximum's holder (the wave travels one hop per
round and ids are unique, so the last strict increase IS the arrival of
the component max). At quiescence ``dist`` therefore holds exact BFS
layers from each component's root, with no second phase and no extra
propagation primitive: the labelling run and the layering run are the
same flood.

A graph is bipartite iff no edge joins two nodes in layers of equal
parity (BFS layers of adjacent nodes differ by at most one, so equal
parity means equal layer — the witness of an odd cycle through their
lowest common BFS ancestor). Run to quiescence with
``engine.run_until_converged(..., stat="changed", threshold=1)`` (like
ConnectedComponents), then read the verdict from the converged state:
``odd_edges(graph, state)`` counts the directed edge slots violating
parity (0 = bipartite) and ``component_bipartite`` maps it per
component — one O(E) scan each, deliberately NOT recomputed per round
(transient labels mid-merge would flag edges spuriously anyway).
Self-loops count as odd (a length-1 cycle), and
each undirected edge of the symmetric builder graphs occupies two
directed slots, so a single undirected odd edge reports as 2.

Deterministic — no RNG consumed. Dynamic runtime links
(sim/topology.py connect) participate in both the flood (via
ops/segment) and the parity scan.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models.leader import max_flood_step
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BipartiteCheckState:
    label: jax.Array  # i32[N_pad] — highest live id heard; -1 on dead nodes
    dist: jax.Array  # i32[N_pad] — round of last label adoption (BFS layer
    #                  from the component root at quiescence); -1 on dead
    frontier: jax.Array  # bool[N_pad] — adopted a new label last round
    round: jax.Array  # i32[] — rounds executed so far


def _odd_edge_slots(graph: Graph, label: jax.Array,
                    dist: jax.Array) -> jax.Array:
    """Count directed edge slots joining same-component endpoints whose BFS
    layers share parity (valid once the flood has quiesced)."""

    def scan(s, r, mask):
        ls, lr = label[s], label[r]
        same = mask & (ls >= 0) & (ls == lr)
        par = ((dist[s] ^ dist[r]) & 1) == 0
        return jnp.sum(same & par)

    count = scan(graph.senders, graph.receivers, graph.edge_mask)
    if graph.dyn_senders is not None:
        count = count + scan(graph.dyn_senders, graph.dyn_receivers,
                             graph.dyn_mask)
    return count


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class BipartiteCheck:
    """Rooted parity flood to a per-component fixpoint. ``method`` picks the
    aggregation lowering (``"auto"``/``"segment"``/``"gather"`` — see
    ops/segment.propagate_max)."""

    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> BipartiteCheckState:
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        label = jnp.where(graph.node_mask, ids, -1)
        dist = jnp.where(graph.node_mask, 0, -1).astype(jnp.int32)
        return BipartiteCheckState(label=label, dist=dist,
                                   frontier=graph.node_mask,
                                   round=jnp.int32(0))

    def odd_edges(self, graph: Graph,
                  state: BipartiteCheckState) -> jax.Array:
        """Directed edge slots violating 2-colorability (valid at
        quiescence; 0 means the whole live graph is bipartite)."""
        return _odd_edge_slots(graph, state.label, state.dist)

    def component_bipartite(self, graph: Graph,
                            state: BipartiteCheckState) -> jax.Array:
        """bool[N_pad]: does this node's component contain NO odd edge?
        (False on dead nodes; valid at quiescence.)"""

        bad = jnp.zeros(graph.n_nodes_padded, dtype=bool)

        def mark(bad, s, r, mask):
            ls, lr = state.label[s], state.label[r]
            same = mask & (ls >= 0) & (ls == lr)
            par = ((state.dist[s] ^ state.dist[r]) & 1) == 0
            odd = same & par
            # The component label is the root's own id — scatter the odd
            # flag there, then read it back through every member's label.
            return bad.at[jnp.where(odd, ls, 0)].max(odd)

        bad = mark(bad, graph.senders, graph.receivers, graph.edge_mask)
        if graph.dyn_senders is not None:
            bad = mark(bad, graph.dyn_senders, graph.dyn_receivers,
                       graph.dyn_mask)
        safe_label = jnp.maximum(state.label, 0)
        return graph.node_mask & ~bad[safe_label]

    def step(self, graph: Graph, state: BipartiteCheckState, key: jax.Array):
        label, changed, msgs = max_flood_step(
            graph, state.label, state.frontier, self.method)
        rnd = state.round + 1
        dist = jnp.where(changed, rnd, state.dist)
        new_state = BipartiteCheckState(label=label, dist=dist,
                                        frontier=changed, round=rnd)
        # No per-round parity scan: the verdict is only meaningful at
        # quiescence, and the O(E) edge scan would double every round's
        # edge traffic to produce transient values callers are told to
        # ignore — read it once from the converged state via odd_edges().
        stats = {
            "messages": msgs,
            "changed": jnp.sum(changed),
        }
        return new_state, stats
