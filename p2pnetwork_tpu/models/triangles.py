"""Triangle counting, clustering coefficients, and wedge-closure sampling.

The cohesion metrics of overlay analysis — *how clustered is the peer
graph* — which reference users could only approximate by crawling
neighbor-of-neighbor lists through ``node_message`` round trips [ref:
README.md:20, p2pnetwork/node.py:110-116]. Batched TPU forms:

- exact: every directed edge slot (s, r) intersects the two complete
  neighbor rows — a ``[B, d, d]`` masked equality per edge block,
  ``lax.map``-ed so peak memory is one block, summed device-side. Each
  triangle is seen once per (directed slot, third vertex) = 6 times.
  This is O(E * d^2) VPU work with no sorting, no hashing, and static
  shapes — the TPU trade for the CPU-classic sorted-adjacency merge,
  and exact on any degree-bounded graph (WS / ER / capped overlays).
- estimated: for degree-skewed graphs where d^2 explodes (BA hubs), a
  uniform wedge sample — centers drawn with probability proportional to
  d(d-1) through a cumulative-weight ``searchsorted``, two distinct
  out-slots through the source-CSR view, closure checked by the same
  windowed membership probe runtime connect uses
  (sim/topology.py ``static_edge_exists``). P(closed) = 3T / #wedges exactly,
  so transitivity estimates are unbiased with plain Monte Carlo error.

Undirected semantics: rows are in-neighbor lists, so counts are exact on
the symmetric graphs the builders produce (both directions present — the
reference's TCP-connection semantic). Graphs carrying a dynamic edge
region are rejected: the neighbor table does not see runtime links, and
a silently-static count would lie; fold links in first with
``topology.consolidate``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.ops.segment import _require_complete_table
from p2pnetwork_tpu.sim.graph import Graph

#: Target elements per [B, d, d] intersection block — bounds peak memory
#: (4 MiB of int32 compares at the default) while keeping blocks wide
#: enough to fill the VPU lanes.
_BLOCK_BUDGET = 1 << 20


def _require_static(graph: Graph, what: str) -> None:
    if graph.dyn_senders is not None:
        raise ValueError(
            f"{what} counts the static edge set only, but this graph "
            "carries a dynamic edge region (topology.with_capacity); "
            "fold runtime links into the static layout first with "
            "topology.consolidate"
        )


def _edge_block(graph: Graph) -> int:
    d = max(graph.max_degree, 1)
    return int(np.clip(_BLOCK_BUDGET // (d * d), 1, 4096))


@functools.partial(jax.jit, static_argnames=("edge_block",))
def _edge_common_counts(graph: Graph, edge_block: int) -> jax.Array:
    """i32[E_pad]: per directed edge slot, the number of live third
    vertices adjacent to both endpoints (0 on masked slots)."""
    e_pad = graph.n_edges_padded
    n_blocks = -(-e_pad // edge_block)
    pad = n_blocks * edge_block - e_pad
    senders = jnp.pad(graph.senders, (0, pad))
    receivers = jnp.pad(graph.receivers, (0, pad))
    emask = jnp.pad(graph.edge_mask, (0, pad))

    def one_block(args):
        s, r, em = args
        ns, ms = graph.neighbors[s], graph.neighbor_mask[s]
        nr, mr = graph.neighbors[r], graph.neighbor_mask[r]
        eq = (ns[:, :, None] == nr[:, None, :]) & ms[:, :, None] & mr[:, None, :]
        return jnp.sum(eq, axis=(1, 2), dtype=jnp.int32) * em

    cnt = jax.lax.map(one_block, (
        senders.reshape(n_blocks, edge_block),
        receivers.reshape(n_blocks, edge_block),
        emask.reshape(n_blocks, edge_block),
    ))
    return cnt.reshape(-1)[:e_pad]


def count_triangles(graph: Graph, *, edge_block: int | None = None) -> int:
    """Exact triangle count of the live undirected graph (Python int)."""
    _require_complete_table(graph)
    _require_static(graph, "count_triangles")
    cnt = _edge_common_counts(graph, edge_block or _edge_block(graph))
    total = int(np.asarray(cnt, dtype=np.int64).sum())
    assert total % 6 == 0, "directed slot closure must come in sixes"
    return total // 6


def triangles_per_node(graph: Graph, *,
                       edge_block: int | None = None) -> jax.Array:
    """i32[N_pad]: triangles through each node (exact, live graph)."""
    _require_complete_table(graph)
    _require_static(graph, "triangles_per_node")
    cnt = _edge_common_counts(graph, edge_block or _edge_block(graph))
    two_tri = jnp.zeros(graph.n_nodes_padded, jnp.int32).at[graph.senders].add(
        cnt, indices_are_sorted=False, unique_indices=False)
    return two_tri // 2


def local_clustering(graph: Graph, *,
                     edge_block: int | None = None) -> jax.Array:
    """f32[N_pad]: per-node local clustering coefficient
    ``2 * tri_v / (d_v * (d_v - 1))`` over live degrees (0 where d < 2)."""
    tri = triangles_per_node(graph, edge_block=edge_block)
    d = graph.in_degree  # == out_degree on the symmetric builder graphs
    denom = d * (d - 1)
    return jnp.where(denom > 0, 2.0 * tri / jnp.maximum(denom, 1), 0.0)


def transitivity(graph: Graph, *, edge_block: int | None = None) -> float:
    """Global clustering coefficient 3T / #wedges (0 for wedge-free)."""
    t = count_triangles(graph, edge_block=edge_block)
    d = np.asarray(graph.in_degree, dtype=np.int64)
    wedges = int((d * (d - 1)).sum()) // 2
    return 3.0 * t / wedges if wedges else 0.0


@functools.partial(jax.jit, static_argnames=("samples",))
def _sample_closed(graph: Graph, key: jax.Array, samples: int):
    d = graph.out_degree
    w = (d * (d - 1)).astype(jnp.int32)
    cum = jnp.cumsum(w)
    total = cum[-1]
    k1, k2, k3 = jax.random.split(key, 3)
    u = jax.random.randint(k1, (samples,), 0, jnp.maximum(total, 1))
    centers = jnp.searchsorted(cum, u, side="right").astype(jnp.int32)
    dc = d[centers]
    j1 = jax.random.randint(k2, (samples,), 0, jnp.maximum(dc, 1))
    j2 = jax.random.randint(k3, (samples,), 0, jnp.maximum(dc - 1, 1))
    j2 = jnp.where(j2 >= j1, j2 + 1, j2)  # distinct second slot
    row0 = graph.src_offsets[centers]
    e1 = graph.src_eid[jnp.minimum(row0 + j1, graph.n_edges_padded - 1)]
    e2 = graph.src_eid[jnp.minimum(row0 + j2, graph.n_edges_padded - 1)]
    a, b = graph.receivers[e1], graph.receivers[e2]
    valid = (dc >= 2) & graph.edge_mask[e1] & graph.edge_mask[e2]
    # The same windowed membership probe runtime connect's duplicate
    # guard uses (sim/topology.py), span-0 broadcast fallback included.
    from p2pnetwork_tpu.sim.topology import static_edge_exists

    closed = static_edge_exists(graph, a, b) & valid
    return jnp.sum(closed), jnp.sum(valid)


def transitivity_sample(graph: Graph, key: jax.Array,
                        samples: int = 65536) -> float:
    """Unbiased global-clustering estimate by uniform wedge sampling —
    the hub-tolerant path (O(samples * max_in_span), degree-free).

    Exact-uniform over the wedges of the BUILT graph; under node/edge
    failures, samples touching dead edges are rejected, which is a
    re-weighting (close to uniform when failures are light), not the
    exact live-wedge distribution — use the exact counter when failures
    matter and degrees allow."""
    _require_static(graph, "transitivity_sample")
    if graph.src_eid is None:
        raise ValueError(
            "transitivity_sample needs the source-CSR view: build with "
            "from_edges(source_csr=True) or graph.with_source_csr()"
        )
    d = np.asarray(graph.out_degree, dtype=np.int64)
    if int((d * (d - 1)).sum()) >= 2**31:
        raise ValueError("wedge count exceeds int32 sampling range")
    closed, valid = _sample_closed(graph, key, samples)
    closed, valid = int(closed), int(valid)
    return closed / valid if valid else 0.0
