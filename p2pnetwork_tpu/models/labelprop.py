"""Label-propagation community detection (Raghavan et al. 2007), batched.

*Which peers cluster together?* — the overlay-analytics sibling of
:class:`~p2pnetwork_tpu.models.components.ConnectedComponents`: where
component labelling finds the partition structure the graph FORCES,
label propagation finds the community structure it SUGGESTS. Every node
starts as its own community and repeatedly adopts the most frequent
label among its neighbors; dense regions agree in a few rounds and the
surviving labels are the communities. Reference users would build this
on ``node_message`` like any other protocol [ref: README.md:20].

TPU form of the per-node mode (most-frequent neighbor label): gather
the neighbor-table labels ``[N, D+1]`` (own label appended — the
standard self-vote that stabilizes singletons), sort each row, and read
run lengths off the sorted row with two vmapped ``searchsorted`` calls —
O(D log D) per node, static shapes, no per-label histograms. Ties break
toward the SMALLEST label (argmax hits the first maximal run of the
ascending sort), making the whole protocol deterministic — no RNG.

Synchronous LPA famously oscillates two-colorable neighborhoods (the
bipartite "label swap" cycle); the standard fix, deterministic here, is
a parity schedule: even ids update on even rounds, odd ids on odd
rounds. Quiescence therefore needs a STABLE PAIR of rounds, exposed as
the ``unsettled`` stat — the adopter count summed over the last two
rounds, 0 only when BOTH halves just held still: run with
``engine.run_until_converged(..., stat="unsettled", threshold=1)``.
(``changed_prev`` seeds to 1, not 0, so the very first round can never
read as settled before the odd half has had a turn.)

Uses the gather (neighbor-table) layout only — the mode is not a
semiring reduction, so the segment/MXU lowerings don't apply; the table
must be complete (from_edges' default). Dead nodes hold label -1 and
dead neighbors don't vote (the mask re-applied by sim/failures.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph

_SENTINEL = jnp.int32(2**31 - 1)


def _row_mode(row: jax.Array) -> jax.Array:
    """Most frequent value of an ascending-sorted row, ignoring
    ``_SENTINEL`` padding; ties -> smallest value. Returns the value
    (``_SENTINEL`` when the row is all padding)."""
    left = jnp.searchsorted(row, row, side="left")
    right = jnp.searchsorted(row, row, side="right")
    count = jnp.where(row == _SENTINEL, 0, right - left)
    return row[jnp.argmax(count)]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LabelPropagationState:
    label: jax.Array  # i32[N_pad] — community label; -1 on dead nodes
    changed_prev: jax.Array  # i32[] — adopters in the previous round
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class LabelPropagation:
    """Community detection by iterated neighborhood-majority voting."""

    def init(self, graph: Graph, key: jax.Array) -> LabelPropagationState:
        if graph.neighbors is None or not graph.neighbors_complete:
            raise ValueError(
                "LabelPropagation needs the complete neighbor table "
                "(build with from_edges(build_neighbor_table=True))")
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        label = jnp.where(graph.node_mask, ids, -1)
        # changed_prev = 1: "the other half hasn't moved yet" — a 0 seed
        # lets round 1 report unsettled == 0 and stop the convergence loop
        # before the odd parity class has ever updated.
        return LabelPropagationState(label=label,
                                     changed_prev=jnp.int32(1),
                                     round=jnp.int32(0))

    def communities(self, graph: Graph,
                    state: LabelPropagationState) -> jax.Array:
        """Distinct labels currently held by live nodes."""
        used = jnp.zeros(graph.n_nodes_padded, dtype=bool)
        lab = jnp.where(graph.node_mask, state.label, 0)
        used = used.at[lab].max(graph.node_mask)
        return jnp.sum(used)

    def step(self, graph: Graph, state: LabelPropagationState,
             key: jax.Array):
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        live_vote = graph.neighbor_mask & graph.node_mask[graph.neighbors]
        votes = jnp.where(live_vote, state.label[graph.neighbors],
                          _SENTINEL)
        own = jnp.where(graph.node_mask, state.label, _SENTINEL)
        votes = jnp.concatenate([votes, own[:, None]], axis=1)
        mode = jax.vmap(_row_mode)(jnp.sort(votes, axis=1))
        # Parity schedule: half the population holds still each round.
        turn = (ids % 2) == (state.round % 2)
        adopt = turn & graph.node_mask & (mode != _SENTINEL)
        label = jnp.where(adopt, mode, state.label)

        changed = jnp.sum(label != state.label)
        new_state = LabelPropagationState(label=label,
                                          changed_prev=changed,
                                          round=state.round + 1)
        stats = {
            "messages": jnp.sum(live_vote),
            "changed": changed,
            "unsettled": changed + state.changed_prev,
            "communities": self.communities(graph, new_state),
        }
        return new_state, stats
