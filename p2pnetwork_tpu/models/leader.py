"""Leader election by highest-id flooding.

The classic decentralized coordination protocol reference users build on
the event hooks [ref: README.md:20 — the library "does not implement any
protocol", users write discovery/election themselves]: every node starts
by nominating itself, repeatedly broadcasts the highest live node id it
has heard, and adopts anything higher that arrives. When no node learns
anything new, every connected component has agreed on its highest live
member — the leader. On the reference this is per-peer Python in
``node_message`` overrides; here one round of the whole population is a
single masked neighbor-max (ops/segment.propagate_max).

Message accounting mirrors the flood family: a node re-broadcasts only in
the round after it learned a better candidate (the reference node would
``send_to_nodes`` from inside its handler), so ``messages`` counts what a
gossip implementation actually sends, not N·degree every round.

Convergence is a stats contract: ``changed`` (number of nodes that
adopted a new candidate this round) reaches 0 exactly when election is
done — run it with ``engine.run_until_converged(..., stat="changed",
threshold=1)``. ``coverage`` is the fraction of live nodes already
agreeing with the globally highest live id, so ``run_until_coverage``
works too (note: per disconnected component, minority components never
reach the global winner — coverage plateaus below 1 there, by design).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LeaderElectionState:
    known: jax.Array  # i32[N_pad] — highest live id heard; -1 on dead nodes
    frontier: jax.Array  # bool[N_pad] — learned something last round


def max_flood_step(graph: Graph, known: jax.Array, frontier: jax.Array,
                   method: str):
    """One frontier-masked max-flood round, shared by LeaderElection and
    ConnectedComponents (a leader election run *is* a partition labelling).

    Only last round's learners re-broadcast; masking the signal to the
    frontier keeps max-propagation identical (a non-frontier node's value
    was already delivered in an earlier round). Returns
    ``(known', frontier', messages)`` where ``frontier'`` is the changed
    mask and ``messages`` the fan-out the reference's per-edge
    ``send_to_nodes`` loop would have performed [ref: node.py:110-116].
    """
    neutral = segment.neutral_min(known.dtype)
    signal = jnp.where(frontier, known, neutral)
    heard = segment.propagate_max(graph, signal, method)
    new_known = jnp.where(graph.node_mask, jnp.maximum(known, heard), -1)
    changed = (new_known != known) & graph.node_mask
    msgs = segment.frontier_messages(graph, frontier & graph.node_mask)
    return new_known, changed, msgs


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class LeaderElection:
    """Highest-live-id election. ``method`` picks the aggregation lowering
    (``"auto"``/``"segment"``/``"gather"`` — see ops/segment.propagate_max)."""

    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> LeaderElectionState:
        ids = jnp.arange(graph.n_nodes_padded, dtype=jnp.int32)
        known = jnp.where(graph.node_mask, ids, -1)
        return LeaderElectionState(known=known, frontier=graph.node_mask)

    def coverage(self, graph: Graph, state: LeaderElectionState) -> jax.Array:
        """Fraction of live nodes already holding the global winner."""
        winner = jnp.max(jnp.where(graph.node_mask, state.known, -1))
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        agreed = jnp.sum((state.known == winner) & graph.node_mask)
        return agreed / n_real

    def step(self, graph: Graph, state: LeaderElectionState, key: jax.Array):
        known, changed, msgs = max_flood_step(
            graph, state.known, state.frontier, self.method)
        new_state = LeaderElectionState(known=known, frontier=changed)
        stats = {
            "messages": msgs,
            "changed": jnp.sum(changed),
            "coverage": self.coverage(graph, new_state),
        }
        return new_state, stats
