"""K-core decomposition by distributed peeling.

The overlay-robustness question behind ``max_connections`` tuning
[ref: node.py:71, node.py:239 — the reference caps peers but offers no
analysis]: which nodes survive when everyone with fewer than ``k`` live
neighbors drops out, recursively? The surviving subgraph (the k-core) is
the standard resilience skeleton of a P2P overlay — nodes outside it can
be cascaded offline by k-1 departures.

Distributed form reference users would write on the hooks: every node
counts its live in-core neighbors; a node seeing fewer than ``k`` leaves
and notifies its neighbors, whose counts shrink next round; repeat to a
fixpoint. One protocol round = one ``propagate_sum`` of the membership
indicator (which rides any aggregation lowering, MXU kernels included)
+ one mask update. At most N rounds; in practice a handful.

Run with ``engine.run_until_converged(..., stat="removed",
threshold=1)``; at quiescence ``state.in_core`` is the k-core.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class KCoreState:
    in_core: jax.Array  # bool[N_pad] — still a k-core candidate


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class KCore:
    """Iterative k-core peeling. ``method`` picks the sum-aggregation
    lowering (``"auto"``/``"segment"``/``"gather"``/``"blocked"``/
    ``"pallas"``/``"hybrid"`` — ops/segment.propagate_sum; the indicator
    is 0/1 so the single-pass bf16 MXU paths stay exact)."""

    k: int
    method: str = "auto"

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")

    def init(self, graph: Graph, key: jax.Array) -> KCoreState:
        return KCoreState(in_core=graph.node_mask)

    def step(self, graph: Graph, state: KCoreState, key: jax.Array):
        # exact=False: a 0/1 indicator is exactly representable in bf16, so
        # the MXU lowerings run single-pass at ~3x less work, bit-identical
        # (same contract SIR uses for its infection pressure).
        indicator = state.in_core.astype(jnp.int32)
        live_deg = segment.propagate_sum(graph, indicator, self.method,
                                         exact=False)
        in_core = state.in_core & (live_deg >= self.k)
        removed = state.in_core & ~in_core
        # Leavers notify each neighbor once — the batched equivalent of a
        # departing reference node's goodbye fan-out [ref: node.py:110-116].
        msgs = segment.frontier_messages(graph, removed)
        new_state = KCoreState(in_core=in_core)
        stats = {
            "messages": msgs,
            "removed": jnp.sum(removed),
            "core_size": jnp.sum(in_core),
        }
        return new_state, stats
