"""Distributed greedy graph coloring via iterated Luby MIS.

The classic decentralized resource-assignment pattern (TDMA slots,
gossip schedules, channel assignment) reference users would build on the
event hooks [ref: README.md:20]: color class c is a maximal independent
set of the graph with classes 0..c-1 removed, so adjacent nodes never
share a color and every node is colored after at most Δ+1 classes
(Δ = max degree; typically far fewer on sparse overlays).

This is a *utility on top of the protocol layer*, not a protocol itself:
each color class runs :class:`~p2pnetwork_tpu.models.mis.LubyMIS` to
quiescence through ``engine.run_until_converged`` (one compiled
device-side loop per class, cached across classes since the graph
structure is unchanged), then removes the class with
``failures.with_node_liveness`` — the same masking churn uses, so the
residual needs no rebuild.

Like the MIS it iterates, correctness of the coloring assumes a
symmetric overlay (every builder in sim/graph.py produces one).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models.mis import LubyMIS
from p2pnetwork_tpu.sim import engine, failures
from p2pnetwork_tpu.sim.graph import Graph


def color_via_mis(
    graph: Graph,
    key: jax.Array,
    *,
    max_colors: int = 256,
    max_rounds_per_color: int = 256,
    method: str = "auto",
) -> Tuple[jax.Array, int]:
    """Greedy-color ``graph``; returns ``(colors, n_colors)``.

    ``colors`` is i32[N_pad]: the color of every live node, ``-1`` on
    dead/padding nodes. ``n_colors`` is the number of classes used.
    Raises if ``max_colors`` classes leave nodes uncolored (raise the
    bound — Δ+1 always suffices) or a class fails to converge within
    ``max_rounds_per_color``.
    """
    proto = LubyMIS(method=method, or_method=method)
    colors = jnp.full(graph.n_nodes_padded, -1, dtype=jnp.int32)
    g = graph
    for c in range(max_colors):
        if int(jnp.sum(g.node_mask)) == 0:  # graftlint: ignore[host-sync-in-loop] -- the per-color host control loop IS the algorithm; bounded by max_colors
            return colors, c
        st, out = engine.run_until_converged(
            g, proto, jax.random.fold_in(key, c),
            stat="undecided", threshold=1,
            max_rounds=max_rounds_per_color,
        )
        if int(out["value"]) != 0:  # graftlint: ignore[host-sync-in-loop] -- summary already host-side after the run's own sync
            raise RuntimeError(
                f"color class {c} did not quiesce in "
                f"{max_rounds_per_color} rounds ({int(out['value'])} nodes "  # graftlint: ignore[host-sync-in-loop] -- error path
                f"undecided) — raise max_rounds_per_color"
            )
        colors = jnp.where(st.in_mis, c, colors)
        # Remove the class from contention; liveness masking IS removal
        # (edges at colored endpoints die with them).
        g = failures.with_node_liveness(g, g.node_mask & ~st.in_mis)
    if int(jnp.sum(g.node_mask)) != 0:
        raise RuntimeError(
            f"{int(jnp.sum(g.node_mask))} nodes uncolored after "
            f"{max_colors} classes — raise max_colors (Δ+1 always suffices)"
        )
    return colors, max_colors
