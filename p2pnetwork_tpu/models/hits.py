"""HITS — hubs and authorities, PageRank's directional companion.

*Who aggregates (hubs) and who is aggregated (authorities)?* Kleinberg's
mutual-reinforcement pair: a good hub points at good authorities, a good
authority is pointed at by good hubs. On a symmetric overlay the two
coincide with eigenvector centrality; the distinction earns its keep on
DIRECTED views — e.g. a ``from_edges`` graph of who-initiated-connection
-to-whom, where hubs are the active dialers and authorities the
well-known rendezvous peers. Another offline-dump analysis [ref:
p2pnetwork/node.py:75-78] turned into a protocol behind the
models/base.py seam.

One synchronous round is the textbook double power step with L2
normalization:

    a'[v] = Σ_{u→v} h[u]        (authority: in-edge sum of hub scores)
    h'[v] = Σ_{v→u} a'[u]       (hub: out-edge sum of new authorities)

The hub update sums over OUT-edges: ``h'[u] = Σ_e [s_e = u] a'[r_e]``
is a segment sum keyed by SENDER, which the receiver-sorted edge layout
does not directly provide. When the graph carries the source-CSR view
(``from_edges(source_csr=True)``), its sender-sorted edge permutation
turns the hub sum into the same sorted-segment reduction as the
authority side; otherwise an unsorted scatter-add does it — both exact,
the CSR path bandwidth-friendly. Runtime (dynamic-region) links fold
into both directions.

Converge with ``engine.run_until_converged(..., stat="residual",
threshold=...)``; deterministic, no RNG. Dead nodes hold score 0.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HITSState:
    hub: jax.Array  # f32[N_pad] — L2-normalized over live nodes
    authority: jax.Array  # f32[N_pad]
    residual: jax.Array  # f32[] — L1 change of both vectors last round


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class HITS:
    """Kleinberg's hubs/authorities by alternating power iteration."""

    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> HITSState:
        mask_f = graph.node_mask.astype(jnp.float32)
        n = jnp.maximum(jnp.sum(mask_f), 1.0)
        v = mask_f / jnp.sqrt(n)  # unit L2 over live nodes
        return HITSState(hub=v, authority=v,
                         residual=jnp.float32(jnp.inf))

    def _out_sum(self, graph: Graph, signal: jax.Array) -> jax.Array:
        """Per-node sum of ``signal`` over OUT-neighbors:
        ``out[u] = sum(signal[r_e], e: s_e = u)``."""
        s, r = graph.senders, graph.receivers
        live = graph.edge_mask & graph.node_mask[s] & graph.node_mask[r]
        vals = jnp.where(live, signal[r], 0.0)
        if graph.src_eid is not None:
            # Source-CSR: reorder edge slots sender-sorted, then the
            # same sorted-segment reduction as the receiver side. Slots
            # past src_offsets[-1] are PADDING whose sentinel (e_pad - 1)
            # can name a LIVE edge when the edge count is an exact pad
            # multiple (graph.py _build_source_csr docstring) — mask
            # them or a live edge's contribution double-counts.
            order = graph.src_eid
            slot_ok = (jnp.arange(order.shape[0], dtype=jnp.int32)
                       < graph.src_offsets[-1])
            out = jax.ops.segment_sum(
                jnp.where(slot_ok, vals[order], 0.0),
                jnp.where(slot_ok, s[order], graph.n_nodes_padded),
                num_segments=graph.n_nodes_padded,
                indices_are_sorted=True)
        else:
            out = (jnp.zeros(graph.n_nodes_padded, jnp.float32)
                   .at[jnp.where(live, s, graph.n_nodes_padded)]
                   .add(vals, mode="drop"))
        if graph.dyn_senders is not None:
            dlive = (graph.dyn_mask & graph.node_mask[graph.dyn_senders]
                     & graph.node_mask[graph.dyn_receivers])
            out = out.at[jnp.where(dlive, graph.dyn_senders,
                                   graph.n_nodes_padded)].add(
                jnp.where(dlive, signal[graph.dyn_receivers], 0.0),
                mode="drop")
        return out * graph.node_mask

    def step(self, graph: Graph, state: HITSState, key: jax.Array):
        mask = graph.node_mask

        def _norm(x):
            return x / jnp.maximum(jnp.sqrt(jnp.sum(x * x)), 1e-30)

        authority = _norm(segment.propagate_sum(graph, state.hub,
                                                self.method))
        hub = _norm(self._out_sum(graph, authority))
        authority = authority * mask
        hub = hub * mask
        residual = (jnp.sum(jnp.abs(hub - state.hub))
                    + jnp.sum(jnp.abs(authority - state.authority)))
        new_state = HITSState(hub=hub, authority=authority,
                              residual=residual)
        stats = {
            # Both sweeps touch every live link, dynamic region included
            # (frontier_messages counts through the dyn-aware degrees).
            "messages": 2 * segment.frontier_messages(graph,
                                                      graph.node_mask),
            "residual": residual,
        }
        return new_state, stats
