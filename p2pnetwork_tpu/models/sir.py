"""SIR epidemic / rumor spread.

The third canonical overlay protocol (BASELINE.json configs[3], 1M-node
Watts–Strogatz): nodes are Susceptible / Infected / Recovered. Each round an
infected node transmits to each neighbor independently with probability
``beta`` (so a susceptible node with k infected neighbors escapes with
probability ``(1-beta)^k``), and recovers with probability ``gamma``.
Infection pressure is one ``propagate_sum`` over the edge set — the same
batched aggregation that replaces the reference's per-edge send loop
[ref: p2pnetwork/node.py:110-112].
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.sim.graph import Graph

SUSCEPTIBLE = 0
INFECTED = 1
RECOVERED = 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SIRState:
    status: jax.Array  # i32[N_pad] in {0, 1, 2}


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class SIR:
    beta: float = 0.3  # per-edge transmission probability per round
    gamma: float = 0.1  # per-round recovery probability
    source: int = 0
    method: str = "auto"

    def init(self, graph: Graph, key: jax.Array) -> SIRState:
        base.validate_source(graph, self.source)
        status = jnp.zeros(graph.n_nodes_padded, dtype=jnp.int32)
        status = status.at[self.source].set(INFECTED)
        return SIRState(status=status * graph.node_mask)

    def coverage(self, graph: Graph, state: SIRState) -> jax.Array:
        """Ever-infected fraction (matches the ``coverage`` stat)."""
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        return jnp.sum((state.status != SUSCEPTIBLE) & graph.node_mask) / n_real

    def step(self, graph: Graph, state: SIRState, key: jax.Array):
        k_inf, k_rec = jax.random.split(key)
        infected = (state.status == INFECTED) & graph.node_mask
        susceptible = (state.status == SUSCEPTIBLE) & graph.node_mask

        # k = number of infected in-neighbors; P(infected) = 1 - (1-beta)^k.
        # 0/1 indicator sums are exact in single-pass MXU mode (the bf16
        # input rounding is lossless on 0/1; accumulation is f32).
        pressure = segment.propagate_sum(
            graph, infected.astype(jnp.float32), self.method, exact=False
        )
        p_infect = 1.0 - jnp.power(1.0 - self.beta, pressure)
        u = jax.random.uniform(k_inf, pressure.shape)
        newly_infected = susceptible & (u < p_infect)

        recovers = infected & (jax.random.uniform(k_rec, pressure.shape) < self.gamma)

        status = jnp.where(newly_infected, INFECTED, state.status)
        status = jnp.where(recovers, RECOVERED, status)

        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        stats = {
            # Every infected node transmits along each outgoing edge.
            "messages": segment.frontier_messages(graph, infected),
            "s_frac": jnp.sum((status == SUSCEPTIBLE) & graph.node_mask) / n_real,
            "i_frac": jnp.sum((status == INFECTED) & graph.node_mask) / n_real,
            "r_frac": jnp.sum((status == RECOVERED) & graph.node_mask) / n_real,
            # Flood-engine compatibility: "coverage" = ever-infected fraction.
            "coverage": jnp.sum((status != SUSCEPTIBLE) & graph.node_mask) / n_real,
        }
        return SIRState(status=status), stats
