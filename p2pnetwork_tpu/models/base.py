"""The protocol seam of the simulation backend.

The reference deliberately ships no protocol — users implement flooding /
gossip / discovery in ``node_message`` overrides [ref: README.md:20,
p2pnetwork/node.py:334]. The sim backend keeps that shape but batched: a
protocol is a pair of pure, jittable functions over the whole population
(SURVEY.md section 7 "hard parts" 1 — the honest bridge from asynchronous
per-message callbacks to synchronous-round batched transitions):

- ``init(graph, key) -> state``: per-node state as arrays (structs of arrays);
- ``step(graph, state, key) -> (state, stats)``: one synchronous round, where
  ``stats`` is a dict of scalar observables (device-side reductions — the
  sim analog of the reference's message counters, SURVEY.md section 5).

Protocol objects are dataclasses of static hyperparameters, so they hash
stably into jit caches.
"""

from __future__ import annotations

from typing import Any, Dict, Protocol as TypingProtocol, Tuple

import jax

from p2pnetwork_tpu.sim.graph import Graph

State = Any
Stats = Dict[str, jax.Array]


class Protocol(TypingProtocol):
    """Structural interface every sim protocol implements."""

    def init(self, graph: Graph, key: jax.Array) -> State: ...

    def step(self, graph: Graph, state: State, key: jax.Array) -> Tuple[State, Stats]: ...


def draw_neighbor_slot(graph: Graph, key: jax.Array):
    """One uniform draw per node over its VALID neighbor-table slots — the
    k-th-set-bit sampler shared by Gossip's partner pick, the failure
    detector's probe target, and anti-entropy's exchange partner (one
    implementation, so a sampling fix lands on all of them).

    On a healthy graph this is exactly uniform over the stored neighbors;
    after failures it stays uniform over the LIVE ones, because
    sim/failures.py re-masks the table (a draw over a min(in_degree,
    width) prefix would hit dead neighbors and, after runtime connects
    grow in_degree past the stored row, padding garbage). Runtime
    (dynamic-region) links are not candidates until a consolidation
    rebuild folds them into the table.

    Returns ``(slot, partner, has_neighbor)``: the drawn column per row,
    the neighbor id it holds (row 0's entry where no valid slot exists),
    and whether the row had any valid slot — callers must gate on
    ``has_neighbor`` (ANDed with their own liveness masks).
    """
    import jax.numpy as jnp

    mask = graph.neighbor_mask
    count = jnp.sum(mask, axis=1)
    u = jax.random.randint(key, (graph.n_nodes_padded,), 0,
                           jnp.int32(2**31 - 1))
    k = u % jnp.maximum(count, 1)
    csum = jnp.cumsum(mask, axis=1)
    slot = jnp.argmax((csum == (k + 1)[:, None]) & mask, axis=1)
    partner = jnp.take_along_axis(graph.neighbors, slot[:, None],
                                  axis=1)[:, 0]
    return slot, partner, count > 0


def validate_source(graph: Graph, source: int) -> None:
    """Reject a source index outside the padded id space (the jit scatter
    would silently clamp it to the last padded index, which the node mask
    then zeroes — a run that spins to max_rounds at coverage 0 with no
    error). Ids in ``[n_nodes, n_nodes_padded)`` are allowed: joined spare
    nodes (sim/topology.py) live there, and dead ids are already zeroed by
    the ``& node_mask`` every seed applies."""
    if not 0 <= source < graph.n_nodes_padded:
        raise ValueError(
            f"source {source} out of range for padded id space "
            f"[0, {graph.n_nodes_padded})"
        )
