"""The protocol seam of the simulation backend.

The reference deliberately ships no protocol — users implement flooding /
gossip / discovery in ``node_message`` overrides [ref: README.md:20,
p2pnetwork/node.py:334]. The sim backend keeps that shape but batched: a
protocol is a pair of pure, jittable functions over the whole population
(SURVEY.md section 7 "hard parts" 1 — the honest bridge from asynchronous
per-message callbacks to synchronous-round batched transitions):

- ``init(graph, key) -> state``: per-node state as arrays (structs of arrays);
- ``step(graph, state, key) -> (state, stats)``: one synchronous round, where
  ``stats`` is a dict of scalar observables (device-side reductions — the
  sim analog of the reference's message counters, SURVEY.md section 5).

Protocol objects are dataclasses of static hyperparameters, so they hash
stably into jit caches.
"""

from __future__ import annotations

from typing import Any, Dict, Protocol as TypingProtocol, Tuple

import jax

from p2pnetwork_tpu.sim.graph import Graph

State = Any
Stats = Dict[str, jax.Array]


class Protocol(TypingProtocol):
    """Structural interface every sim protocol implements."""

    def init(self, graph: Graph, key: jax.Array) -> State: ...

    def step(self, graph: Graph, state: State, key: jax.Array) -> Tuple[State, Stats]: ...


def validate_source(graph: Graph, source: int) -> None:
    """Reject a source index outside the padded id space (the jit scatter
    would silently clamp it to the last padded index, which the node mask
    then zeroes — a run that spins to max_rounds at coverage 0 with no
    error). Ids in ``[n_nodes, n_nodes_padded)`` are allowed: joined spare
    nodes (sim/topology.py) live there, and dead ids are already zeroed by
    the ``& node_mask`` every seed applies."""
    if not 0 <= source < graph.n_nodes_padded:
        raise ValueError(
            f"source {source} out of range for padded id space "
            f"[0, {graph.n_nodes_padded})"
        )
