"""Plumtree — epidemic broadcast trees (Leitão, Pereira, Rodrigues 2007).

THE self-optimizing broadcast of the gossip literature (the ancestor of
libp2p's gossipsub): flood the first message over every link, and let
the duplicates teach the overlay a spanning tree — each node keeps only
its FIRST deliverer as an *eager* link and demotes the rest to *lazy*
(PRUNE); lazy links carry only message-id digests (IHAVE), and a node
that misses a message GRAFTs a lazy link back into the tree. Broadcast
cost drops from O(E) messages to O(N−1) while the lazy mesh keeps the
reliability of the full flood. Reference users would build exactly this
on ``node_message`` to stop duplicate storms [ref: README.md:20 — the
library ships broadcast but no dedup at all, node.py:106-112].

Batched, round-synchronous form — one :meth:`step` is ONE broadcast
from ``source`` over the current eager set, run to completion
device-side:

- a BFS ``while_loop`` over the eager-masked edge set delivers the
  message and records arrival layers;
- PRUNE: each reached node keeps one eager in-edge from the previous
  layer (lowest edge id — the deterministic stand-in for "first
  arrival", which a synchronous round cannot distinguish); every other
  in-edge goes lazy. After one broadcast on a static overlay the eager
  set IS a spanning tree rooted at the source.
- GRAFT: when the eager wave dies with live nodes unreached (the tree
  was broken — e.g. by churn since the last broadcast), the repair that
  Plumtree drives off IHAVE timeouts fires inside the same loop: every
  unreached node with a reached lazy in-neighbor grafts its lowest-id
  such edge back to eager, and the wave continues. ``grafts`` counts
  the healed links.

Stats per broadcast: ``messages`` (eager payload sends), ``ihave``
(lazy digest sends — the price of the repair channel), ``duplicates``
(eager deliveries beyond the first — 0 once the tree has formed),
``eager_edges``, ``grafts``, ``coverage``. The headline contrast:
broadcast 1 costs ~E messages with ~E−N duplicates, broadcast 2 costs
N−1 with 0, and after ``fail_nodes`` the next broadcast pays a few
grafts to heal (see ``tests/test_plumtree.py`` for all three pinned).

Directed-edge note: the eager set lives on the stored directed edges;
on the symmetric graphs the builders produce the pruned tree is a
directed arborescence away from the source, matching Plumtree's
per-direction eager flags.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import bitset
from p2pnetwork_tpu.sim.graph import Graph


def _eager_mask(graph: Graph, eager: jax.Array) -> jax.Array:
    """Live eager edges, computed device-side (tree_graph's compaction
    must not pull the E-slot arrays to host just to mask them)."""
    s, r = graph.senders, graph.receivers
    return graph.edge_mask & eager & graph.node_mask[s] & graph.node_mask[r]


def _compact_edges(graph: Graph, idx: jax.Array) -> jax.Array:
    """``[2, K]`` (senders, receivers) at ``idx`` — one stacked gather,
    one device->host transfer for the caller."""
    return jnp.stack([jnp.take(graph.senders, idx),
                      jnp.take(graph.receivers, idx)])


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PlumtreeState:
    eager: jax.Array  # bool[E_pad] — payload-carrying links
    round: jax.Array  # i32[] — broadcasts completed


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PlumtreeBitState:
    """PlumtreeState with the per-EDGE eager flags bit-packed
    (ops/bitset.py): the carried eager set shrinks 32x — at 1M nodes /
    ~10M directed edges that is ~10 MB -> ~0.3 MB of per-broadcast carry.
    The broadcast loop unpacks transiently; results are bit-identical."""

    eager: jax.Array  # u32[E_pad // 32]
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Plumtree:
    """Self-optimizing broadcast: flood once, then tree + lazy repair.

    ``bitset=True`` carries the eager edge set bit-packed
    (:class:`PlumtreeBitState`) — same pruned trees, same stats."""

    source: int = 0
    bitset: bool = False

    def init(self, graph: Graph, key: jax.Array):
        base.validate_source(graph, self.source)
        if graph.dyn_senders is not None:
            # The eager flags live on the STATIC edge slots; a runtime
            # link would be silently invisible to broadcasts (flood folds
            # the dynamic region in, so a flood->Plumtree switch would
            # quietly lose coverage). Refuse rather than mislead —
            # consolidate (sim/topology.py) to fold runtime links into
            # static slots first.
            raise ValueError(
                "Plumtree does not track the dynamic edge region; "
                "consolidate the graph first")
        eager = jnp.ones(graph.n_edges_padded, dtype=bool)
        if self.bitset:
            return PlumtreeBitState(eager=bitset.pack_bits(eager),
                                    round=jnp.int32(0))
        return PlumtreeState(eager=eager, round=jnp.int32(0))

    @staticmethod
    def _eager_bool(graph: Graph, state) -> jax.Array:
        """The eager set as bool[E_pad], whichever state carries it."""
        if isinstance(state, PlumtreeBitState):
            return bitset.unpack_bits(state.eager, graph.n_edges_padded)
        return state.eager

    def tree_graph(self, graph: Graph, state: PlumtreeState,
                   **from_edges_kwargs) -> Graph:
        """Extract the learned eager set as its own compact :class:`Graph`.

        The per-layer cost of :meth:`step` is O(E_pad) however sparse the
        eager set is (a dynamic per-edge mask fits none of the static
        fast layouts); once the tree is stable, the cheap repeated
        broadcast is Flood over THIS graph — same ~N−1 edges, but padded
        to ~N slots instead of E (measured 3.8 s → 0.13 s per 1M-node
        broadcast; see BENCH.md).

        The eager-edge COMPACTION runs device-side (mask, count, one
        ``nonzero``), so only the ~N surviving tree edges ever cross
        device->host — not the full E-slot edge arrays, which on a
        tunneled backend were the extraction's real cost (~120 MB at 1M
        nodes vs ~8 MB compacted). The host then only sorts/pads ~N
        edges (``from_edges`` rides the native radix path,
        native/graphcore.cpp). Pass ``source_csr=True`` etc. through
        ``from_edges_kwargs`` to pick layouts."""
        import numpy as np

        from p2pnetwork_tpu.sim.graph import from_edges

        if graph.dyn_senders is not None:
            # Same refuse-rather-than-mislead rule as init: runtime
            # links would silently vanish from the extracted tree.
            raise ValueError(
                "Plumtree does not track the dynamic edge region; "
                "consolidate the graph first")
        em = _eager_mask(graph, self._eager_bool(graph, state))
        count = int(jnp.sum(em))
        idx = jnp.nonzero(em, size=max(count, 1), fill_value=0)[0]
        picked = np.asarray(_compact_edges(graph, idx))[:, :count]
        s, r = picked[0], picked[1]
        if graph.edge_weight is not None:
            # Carry link costs through the extraction (the same rule as
            # topology.consolidate): a weighted overlay's tree must not
            # silently decay to unit costs for weighted protocols.
            from_edges_kwargs.setdefault(
                "weights",
                np.asarray(jnp.take(graph.edge_weight, idx))[:count])
        # Pad to the source graph's node extent: ids and masks then line
        # up slot-for-slot whatever pad multiple the source was built
        # with (n_nodes <= n_nodes_padded makes the round-up exact).
        from_edges_kwargs.setdefault("node_pad_multiple",
                                     graph.n_nodes_padded)
        m = from_edges_kwargs["node_pad_multiple"]
        if -(-graph.n_nodes // m) * m != graph.n_nodes_padded:
            # A caller-supplied multiple that disagrees would only
            # surface as a cryptic shape error after the full build.
            raise ValueError(
                f"node_pad_multiple={m} pads to a different node extent "
                f"than the source graph's {graph.n_nodes_padded}")
        g = from_edges(s, r, graph.n_nodes, **from_edges_kwargs)
        return dataclasses.replace(g,
                                   node_mask=graph.node_mask & g.node_mask)

    def step(self, graph: Graph, state, key: jax.Array):
        eager0 = self._eager_bool(graph, state)
        n_pad = graph.n_nodes_padded
        e_pad = graph.n_edges_padded
        s, r = graph.senders, graph.receivers
        eids = jnp.arange(e_pad, dtype=jnp.int32)
        big = jnp.int32(2**31 - 1)
        live_edge = graph.edge_mask & graph.node_mask[s] & graph.node_mask[r]

        seed = jnp.zeros(n_pad, dtype=bool).at[self.source].set(True)
        seed = seed & graph.node_mask
        dist0 = jnp.where(seed, 0, -1).astype(jnp.int32)

        def seg_or(signal, emask):
            contrib = signal[s] & emask
            return jax.ops.segment_max(
                contrib.astype(jnp.int32), r, num_segments=n_pad,
                indices_are_sorted=True) > 0

        # One device-side loop runs the whole broadcast: BFS rounds over
        # the eager set; when the wave dies with live nodes unreached,
        # graft one batch of lazy links (IHAVE repair) and keep going.
        def cond(carry):
            dist, frontier, eager, layer, grafts, stop = carry
            return ~stop

        def body(carry):
            dist, frontier, eager, layer, grafts, stop = carry
            emask = live_edge & eager
            delivered = seg_or(frontier, emask)
            new = delivered & (dist < 0) & graph.node_mask
            any_new = jnp.any(new)

            # Wave died: graft lowest-id lazy edges from reached senders
            # into unreached receivers (the IHAVE->GRAFT repair). Behind
            # a lax.cond so the O(E) scatter-min is paid ONLY on dead
            # layers — on a healthy tree each broadcast hits it once, at
            # the final (empty) wave, not per layer (measured 10.9 s ->
            # ~flood-cost per 1M-node tree broadcast without the gate).
            def _graft(args):
                dist, eager = args
                unreached = graph.node_mask & (dist < 0)
                lazy_cand = (live_edge & ~eager & (dist[s] >= 0)
                             & unreached[r])
                tgt = jnp.where(lazy_cand, r, n_pad)
                best = jnp.full(n_pad, big).at[tgt].min(
                    jnp.where(lazy_cand, eids, big), mode="drop")
                graft_edge = lazy_cand & (best[jnp.where(lazy_cand, r, 0)]
                                          == eids)
                regrow = jnp.zeros(n_pad, dtype=bool).at[
                    jnp.where(graft_edge, s, n_pad)].set(True, mode="drop")
                return graft_edge, jnp.sum(graft_edge), regrow

            def _no_graft(args):
                return (jnp.zeros(e_pad, dtype=bool), jnp.int32(0),
                        jnp.zeros(n_pad, dtype=bool))

            graft_edge, n_graft, regrow = jax.lax.cond(
                any_new, _no_graft, _graft, (dist, eager))
            do_graft = ~any_new & (n_graft > 0)
            eager = jnp.where(do_graft, eager | graft_edge, eager)
            # Grafted edges deliver immediately next iteration: their
            # senders rejoin the frontier.
            frontier_next = jnp.where(do_graft, (dist >= 0) & regrow, new)

            dist = jnp.where(new, layer + 1, dist)
            stop = ~any_new & ~do_graft
            return (dist, frontier_next, eager,
                    jnp.where(any_new, layer + 1, layer),
                    grafts + jnp.where(do_graft, n_graft, 0), stop)

        dist, _, eager, _, grafts, _ = jax.lax.while_loop(
            cond, body, (dist0, seed, eager0, jnp.int32(0),
                         jnp.int32(0), jnp.array(False)))

        reached = dist >= 0
        emask = live_edge & eager
        # Every eager edge with a reached sender delivers the payload
        # (the sender fires once when the message reaches it); a reached
        # node's deliveries beyond the first are Plumtree's duplicates.
        fired = emask & reached[s]
        arrivals = jax.ops.segment_sum(
            fired.astype(jnp.int32), r, num_segments=n_pad,
            indices_are_sorted=True)
        duplicates = jnp.sum(jnp.maximum(arrivals - 1, 0)
                             * reached.astype(jnp.int32))
        messages = jnp.sum(fired)
        ihave = jnp.sum(live_edge & ~eager & reached[s])

        # PRUNE: each reached non-source node keeps its lowest-id in-edge
        # from any STRICTLY EARLIER layer (strictness keeps the parent
        # pointers acyclic; "previous layer only" would orphan nodes
        # delivered through a graft, whose sender can sit many layers
        # up). Everything else incident-in to reached nodes goes lazy;
        # edges into unreached nodes keep their flag.
        parent_cand = emask & (dist[s] >= 0) & (dist[r] >= 1) \
            & (dist[s] < dist[r])
        tgt = jnp.where(parent_cand, r, n_pad)
        best = jnp.full(n_pad, big).at[tgt].min(
            jnp.where(parent_cand, eids, big), mode="drop")
        is_parent = parent_cand & (best[jnp.where(parent_cand, r, 0)]
                                   == eids)
        into_reached = live_edge & reached[r]
        eager = jnp.where(into_reached, is_parent, eager)

        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        if isinstance(state, PlumtreeBitState):
            new_state = PlumtreeBitState(eager=bitset.pack_bits(eager),
                                         round=state.round + 1)
        else:
            new_state = PlumtreeState(eager=eager, round=state.round + 1)
        stats = {
            "messages": messages,
            "ihave": ihave,
            "duplicates": duplicates,
            "grafts": grafts,
            "eager_edges": jnp.sum(live_edge & eager),
            "coverage": jnp.sum(reached & graph.node_mask) / n_live,
        }
        return new_state, stats
