"""Batched message plane: B concurrent floods as one lane-packed state.

Production traffic is thousands of overlapping broadcasts, not one flood
(ROADMAP item 2a) — yet a :class:`~p2pnetwork_tpu.models.flood.Flood` run
per message pays B× the engine loops, B× the dispatches and B× the N-wide
state of one. This module batches them the way the sparse-GNN-on-dense-
hardware literature batches many small sparse problems into one
dense-shaped program (PAPERS.md): since ``ops/bitset.py`` packs 32
predicates per uint32, 32 broadcast states fit in the footprint of one —
``seen``/``frontier`` become ``u32[B_words, N_pad]`` where bit L of word w
at node v is message ``32w+L``'s predicate — and one jitted round-step
(``ops/segment.propagate_or_lanes``) advances every in-flight message.

Per-message semantics are EXACTLY the single-message flood's, lane by
lane: the same seed masking, the same ``new = delivered & ~seen & alive``
dedup, the same masked coverage numerator, the same per-round message
count, the same "run while coverage < target" round accounting — each
lane's final ``seen`` set and round count is bit-identical to an
independent ``Flood`` run from the same source
(tests/test_messagebatch.py pins the sweep). Completed lanes FREEZE: they
are masked out of the batch frontier, so stragglers stop paying for
finished messages.

Admission is staggered by design: a batch has fixed lane CAPACITY, and
:meth:`BatchFlood.admit` seeds new messages into open lanes between
engine calls — the seam a serving front-end drives (submit → admit,
poll → :func:`lane_seen` / :meth:`MessageBatch` metadata, complete →
:meth:`BatchFlood.retire` recycles the lane). The engine side is
``engine.run_batch_until_coverage`` — one donated-carry ``while_loop``
advancing the whole batch with per-lane completion detection.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.ops import bitset, frontier, segment
from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.telemetry import spans


class LaneExhausted(ValueError):
    """Admission refused: more messages than open lanes.

    Lane exhaustion is the batch plane's DESIGNED backpressure signal
    (PR 10) — but a bare ``ValueError`` forced the serving front-end to
    string-match to distinguish "back off and queue" from a genuine
    usage error. This subclass keeps every existing ``except ValueError``
    working (back-compat pinned in tests) while carrying the numbers an
    admission controller acts on: how many lanes were ``requested``, how
    many are ``free``, and the batch ``capacity``."""

    def __init__(self, requested: int, free_lanes: int, capacity: int):
        self.requested = int(requested)
        self.free_lanes = int(free_lanes)
        self.capacity = int(capacity)
        super().__init__(
            f"admit of {self.requested} messages into a batch with only "
            f"{self.free_lanes} open lanes of {self.capacity} — "
            "retire completed lanes or grow capacity")


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MessageBatch:
    """Lane-packed state of up to ``capacity = 32 · B_words`` concurrent
    floods. Message lane ``b`` lives at bit ``b % 32`` of word
    ``b // 32`` (ops/bitset.py lane order — ``bitset.pack_bits`` of a
    ``bool[capacity]`` flag yields exactly the per-word lane masks).

    ``seen``/``frontier`` are the broadcast predicates of every lane at
    once; the per-lane metadata tracks each message's lifecycle. A lane
    is OPEN (seedable by ``admit``) when ``~admitted``; RUNNING while
    ``admitted & ~done``; FROZEN once ``done`` (coverage target reached —
    its bits stop entering the batch frontier). ``rounds`` counts the
    steps APPLIED to the lane (identical to the single-message engine's
    round count). Per-lane send totals are NOT accumulated per round —
    that would cost a per-(node, lane) weighted reduction every round;
    instead ``sent`` records which nodes have broadcast for each lane (a
    flood node sends exactly once, the round after it first sees the
    message), and :func:`lane_messages` derives the exact per-lane total
    from it on demand, outside the hot loop."""

    seen: jax.Array       # u32[B_words, N_pad] — lane-packed seen sets
    frontier: jax.Array   # u32[B_words, N_pad] — lane-packed frontiers
    sent: jax.Array       # u32[B_words, N_pad] — nodes that have SENT
    source: jax.Array     # i32[capacity] — seed node per lane (-1 = open)
    admitted: jax.Array   # bool[capacity]
    done: jax.Array       # bool[capacity] — frozen (target reached)
    rounds: jax.Array     # i32[capacity] — steps applied per lane
    seen_count: jax.Array  # i32[capacity] — live nodes holding the message
    target: jax.Array     # f32[capacity] — per-lane coverage target

    @property
    def n_words(self) -> int:
        return self.seen.shape[0]

    @property
    def capacity(self) -> int:
        return self.n_words * bitset.WORD

    @property
    def n_nodes_padded(self) -> int:
        return self.seen.shape[1]

    def repad(self, new_n_pad: int) -> "MessageBatch":
        """Carry every in-flight lane across a node-capacity repad
        (``Graph.grow``): zero-extend the three packed bit-planes from
        the old ``N_pad`` to ``new_n_pad`` columns. Fresh capacity
        padding is unseen by every lane — exactly the state a batch
        admitted against the grown graph would hold — and the per-lane
        metadata (source, admitted, done, rounds, seen_count, target) is
        capacity-independent and rides along untouched, so admission
        order, the latched-completion contract, and each lane's
        admission-time coverage target all survive. Zero admitted lanes
        are dropped by construction. The engine seam needs nothing
        special: its jit caches key on shapes, so the first run of a
        repadded batch compiles a fresh program at the new capacity and
        later repads of the same size reuse it."""
        new_n_pad = int(new_n_pad)
        n_pad = self.n_nodes_padded
        if new_n_pad == n_pad:
            return self
        if new_n_pad < n_pad:
            raise ValueError(
                f"repad to {new_n_pad} below the current node capacity "
                f"{n_pad} — lanes cannot shrink without dropping state")
        pad = [(0, 0), (0, new_n_pad - n_pad)]
        return dataclasses.replace(
            self,
            seen=jnp.pad(self.seen, pad),
            frontier=jnp.pad(self.frontier, pad),
            sent=jnp.pad(self.sent, pad),
        )


def _lane_word(batch: MessageBatch, lane: int):
    """(word, bit) of a lane id, bounds-checked: an out-of-range lane
    would otherwise silently CLAMP to the last word and read another
    message's predicate (the same silent-clamp footgun
    base.validate_source guards seeds against, on the poll side)."""
    lane = int(lane)
    if not 0 <= lane < batch.capacity:
        raise ValueError(
            f"lane {lane} outside this batch's capacity "
            f"{batch.capacity} — stale or foreign lane id?")
    return divmod(lane, bitset.WORD)


def lane_seen(batch: MessageBatch, lane: int) -> jax.Array:
    """One lane's ``seen`` predicate as ``bool[N_pad]`` — the per-message
    result view (poll/read side of the serving seam)."""
    w, b = _lane_word(batch, lane)
    return ((batch.seen[w] >> jnp.uint32(b)) & jnp.uint32(1)).astype(bool)


def lane_frontier(batch: MessageBatch, lane: int) -> jax.Array:
    """One lane's ``frontier`` predicate as ``bool[N_pad]``."""
    w, b = _lane_word(batch, lane)
    return ((batch.frontier[w] >> jnp.uint32(b)) & jnp.uint32(1)).astype(
        bool)


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class BatchFlood:
    """The flood family's batched form: B single-source floods advanced
    by one compiled program per round.

    ``method`` picks the lane-packed lowering
    (ops/segment.propagate_or_lanes: ``auto``/``gather``/``segment``/
    ``frontier``); ``frontier_crossover`` overrides the shared
    union-frontier compaction budget exactly like ``Flood``'s knob. The
    protocol is a static-hyperparameter dataclass so it hashes stably
    into jit caches, like every other model."""

    method: str = "auto"
    frontier_crossover: object = None  # ops/frontier.py budget override

    # ------------------------------------------------------------ lifecycle

    def empty(self, graph: Graph, capacity: int) -> MessageBatch:
        """An all-open batch of ``capacity`` lanes (rounded UP to a whole
        word — ragged capacities waste only the pad lanes' bits)."""
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        n_words = bitset.n_words(capacity)
        cap = n_words * bitset.WORD
        n_pad = graph.n_nodes_padded
        return MessageBatch(
            seen=jnp.zeros((n_words, n_pad), dtype=jnp.uint32),
            frontier=jnp.zeros((n_words, n_pad), dtype=jnp.uint32),
            sent=jnp.zeros((n_words, n_pad), dtype=jnp.uint32),
            source=jnp.full(cap, -1, dtype=jnp.int32),
            admitted=jnp.zeros(cap, dtype=bool),
            done=jnp.zeros(cap, dtype=bool),
            rounds=jnp.zeros(cap, dtype=jnp.int32),
            seen_count=jnp.zeros(cap, dtype=jnp.int32),
            target=jnp.ones(cap, dtype=jnp.float32),
        )

    def init(self, graph: Graph, sources, *,
             coverage_target: float = 0.99,
             capacity: int = None) -> MessageBatch:
        """A fresh batch with one lane admitted per entry of ``sources``
        (any int sequence; duplicates are independent messages).
        ``capacity`` reserves open lanes beyond them for later
        :meth:`admit` waves (default: just enough words for
        ``len(sources)``)."""
        sources = np.asarray(sources, dtype=np.int32).reshape(-1)
        if sources.size == 0:
            raise ValueError("init needs at least one source")
        cap = capacity if capacity is not None else sources.size
        if cap < sources.size:
            raise ValueError(f"capacity {cap} < {sources.size} sources")
        batch = self.empty(graph, cap)
        batch, _ = self.admit(graph, batch, sources,
                              coverage_target=coverage_target)
        return batch

    def admit(self, graph: Graph, batch: MessageBatch, sources, *,
              coverage_target: float = 0.99):
        """Seed new messages into OPEN lanes — the between-rounds
        admission seam. Returns ``(batch, lane_ids)`` where ``lane_ids``
        (numpy i32) are the lanes assigned, in ``sources`` order.

        Host-side by design: lane assignment is control-plane work the
        serving front-end does between ``run_batch_until_coverage``
        calls, and the device updates are a handful of scatters. Each
        lane's seeding matches ``Flood.init`` + the resume loop's
        ``cov0`` exactly: the seed is masked by ``node_mask``, and a lane
        already at target (tiny graphs, dead sources never — a dead
        source seeds nothing and floods nothing, spinning to max_rounds
        like the single-message run) starts ``done``. Raises
        :class:`LaneExhausted` when open lanes run out — that is the
        backpressure signal, not a silent drop (its fields carry what an
        admission controller needs to back off)."""
        sources = np.asarray(sources, dtype=np.int32).reshape(-1)
        if sources.size == 0:  # an idle admission tick is a no-op
            return batch, np.zeros(0, dtype=np.int32)
        bad = (sources < 0) | (sources >= graph.n_nodes_padded)
        if bad.any():  # one canonical error, vectorized check (B is large)
            base.validate_source(graph, int(sources[bad.argmax()]))
        open_lanes = np.flatnonzero(~np.asarray(batch.admitted))
        if sources.size > open_lanes.size:
            raise LaneExhausted(sources.size, open_lanes.size,
                                batch.capacity)
        lanes = open_lanes[:sources.size].astype(np.int32)
        src = jnp.asarray(sources)
        # Seed scatter: bit L of word w at each source node. Two admitted
        # lanes may share the same (word, source) cell — ``.at[].set``
        # would keep only one — so fold duplicate cells' bits on the host
        # first (vectorized: sort by cell, OR-reduce each run; admission
        # is the serving plane's hot path at B=1024+).
        w_idx = lanes // bitset.WORD
        cell_bits = np.uint32(1) << (lanes % bitset.WORD).astype(np.uint32)
        cell_key = w_idx.astype(np.int64) * graph.n_nodes_padded + sources
        order = np.argsort(cell_key, kind="stable")
        starts = np.flatnonzero(
            np.diff(cell_key[order], prepend=cell_key[order[0]] - 1))
        folded = np.bitwise_or.reduceat(cell_bits[order], starts)
        ws = jnp.asarray(w_idx[order][starts])
        vs = jnp.asarray(sources[order][starts])
        bits = jnp.where(graph.node_mask[vs], jnp.asarray(folded),
                         jnp.uint32(0))
        seen = batch.seen.at[ws, vs].set(batch.seen[ws, vs] | bits)
        frontier_w = batch.frontier.at[ws, vs].set(
            batch.frontier[ws, vs] | bits)
        lanes_j = jnp.asarray(lanes)
        seeded = graph.node_mask[src]  # dead source seeds nothing
        count0 = seeded.astype(jnp.int32)
        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        cov0 = count0 / n_live
        tgt = jnp.float32(coverage_target)
        if spans.current_tracer() is not None:
            # Trace plane: one lane_submit event per admitted message —
            # the control-plane timestamp a serving front-end's
            # submit→completion latency starts from (the engine's
            # batch_run span later emits lane_admit when the lane first
            # advances). NB: `src` above is the device source array the
            # scatter below consumes — don't shadow it here.
            for lane_id, src_id in zip(lanes.tolist(), sources.tolist()):
                spans.emit("lane_submit", lane=lane_id, source=src_id)
        # sent needs no seeding: the source broadcasts in its first
        # applied round, where it enters `sent` through the frontier.
        return dataclasses.replace(
            batch,
            seen=seen,
            frontier=frontier_w,
            source=batch.source.at[lanes_j].set(src),
            admitted=batch.admitted.at[lanes_j].set(True),
            done=batch.done.at[lanes_j].set(cov0 >= tgt),
            rounds=batch.rounds.at[lanes_j].set(0),
            seen_count=batch.seen_count.at[lanes_j].set(count0),
            target=batch.target.at[lanes_j].set(tgt),
        ), lanes

    def repad(self, batch: MessageBatch, new_n_pad: int) -> MessageBatch:
        """Protocol-level spelling of :meth:`MessageBatch.repad` — the
        seam a serving driver calls right after ``Graph.grow`` repads
        node capacity, so the batch it carries matches the grown graph's
        shapes before the next engine dispatch."""
        return batch.repad(new_n_pad)

    def retire(self, batch: MessageBatch, lanes=None) -> MessageBatch:
        """Release lanes back to OPEN (default: every ``done`` lane),
        clearing their bits from the packed predicates so the next
        admit's message starts clean. Read results (:func:`lane_seen`,
        per-lane metadata) BEFORE retiring — this erases them."""
        if lanes is None:
            release = np.asarray(batch.done)
        else:
            ids = np.asarray(lanes, dtype=np.int64).reshape(-1)
            bad = (ids < 0) | (ids >= batch.capacity)
            if bad.any():  # a wrapped -1 would silently erase the LAST
                # lane's in-flight state (the _lane_word footgun, write
                # side) — refuse instead.
                raise ValueError(
                    f"retire of lane {int(ids[bad.argmax()])} outside "
                    f"this batch's capacity {batch.capacity} — stale or "
                    "foreign lane id?")
            release = np.zeros(batch.capacity, dtype=bool)
            release[ids] = True
        if spans.current_tracer() is not None:
            for lane in np.flatnonzero(release).tolist():
                spans.emit("lane_retire", lane=lane)
        clear = bitset.pack_bits(jnp.asarray(release))  # u32[B_words]
        keep = ~clear[:, None]
        rel = jnp.asarray(release)
        return dataclasses.replace(
            batch,
            seen=batch.seen & keep,
            frontier=batch.frontier & keep,
            sent=batch.sent & keep,
            source=jnp.where(rel, -1, batch.source),
            admitted=batch.admitted & ~rel,
            done=batch.done & ~rel,
            rounds=jnp.where(rel, 0, batch.rounds),
            seen_count=jnp.where(rel, 0, batch.seen_count),
        )

    # ----------------------------------------------------------------- step

    def refresh(self, graph: Graph, batch: MessageBatch) -> MessageBatch:
        """Re-derive the mask-dependent per-lane state from the CURRENT
        graph — the batched analog of the resume loop's ``cov0`` seeding
        (engine.run_until_coverage_from): node failures applied BETWEEN
        engine calls change both the masked coverage numerator and the
        live-node denominator, so a resumed batch must re-count before
        deciding which lanes are already at target (a lane at target
        under the new mask applies zero steps, exactly like the
        single-message resume).

        ``done`` is LATCHED — refresh only ever adds completions, never
        revokes one. A completed message stays delivered even if later
        node failures drop its masked coverage back under target: the
        freeze already cleared its frontier (resuming would flood from
        nothing), and serving semantics agree — re-broadcast after
        churn is a NEW message, admitted into a fresh lane. This is the
        one deliberate divergence from resuming a single-message run of
        the same state, which would keep flooding.

        The engine entry point calls refresh EAGERLY
        before dispatching the loop: inside the donated jit the stale
        ``seen_count`` input would be dead (recomputed), and jax prunes
        dead array args — silently dropping that leaf's donation (the
        graftaudit donation gate caught exactly this). Eager, it
        replaces only the two small metadata leaves, no copies of the
        packed predicates. Within one compiled run the mask is static,
        so the step's incremental count stays exact from here."""
        node_lanes = jnp.where(graph.node_mask, jnp.uint32(0xFFFFFFFF),
                               jnp.uint32(0))
        seen_count = jax.vmap(bitset.lane_counts)(
            batch.seen & node_lanes[None, :]).reshape(-1)
        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        done = batch.done | (batch.admitted
                             & (seen_count / n_live >= batch.target))
        return dataclasses.replace(batch, seen_count=seen_count, done=done)

    def step(self, graph: Graph, batch: MessageBatch, key: jax.Array):
        """One synchronous round of every RUNNING lane: frozen (done) and
        open lanes are masked out of the batch frontier, so they pay
        nothing and change nothing. Per-lane arithmetic mirrors
        ``Flood.step`` bit for bit. Per-round costs are word-level only:
        the lane-masked popcount completion check rides the 32x32
        bit-transpose (bitset.lane_counts — a few u32 passes, no
        ``[N, 32]`` expansion), the aggregate send count rides a per-NODE
        ``population_count`` against ``out_degree``, and per-lane send
        totals are deferred entirely to :func:`lane_messages` via the
        ``sent`` predicate."""
        live = batch.admitted & ~batch.done
        live_mask = bitset.pack_bits(live)  # u32[B_words] lane masks
        front = batch.frontier & live_mask[:, None]
        delivered = segment.propagate_or_lanes(
            graph, front, self.method,
            frontier_crossover=self.frontier_crossover)
        new = delivered & ~batch.seen & live_mask[:, None]
        seen = batch.seen | new
        sent = batch.sent | front  # every frontier node broadcasts once
        # Per-lane masked coverage numerator, accumulated incrementally
        # (transpose-popcount of `new` per word; lanes ride the columns,
        # b = 32w + L matching the metadata vectors' order). `new` is
        # already node-masked (the kernels zero dead receivers), and the
        # mask is STATIC within a compiled run, so incremental equals
        # Flood's per-round `sum(seen & node_mask)` exactly — provided
        # the entry state was refreshed (engine calls `refresh` before
        # dispatch; that is also what keeps this carry leaf live for
        # donation, see refresh's docstring).
        new_counts = jax.vmap(bitset.lane_counts)(new).reshape(-1)
        seen_count = batch.seen_count + new_counts
        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        coverage = seen_count / n_live
        done = batch.done | (batch.admitted & (coverage >= batch.target))
        rounds = batch.rounds + live.astype(jnp.int32)
        # Freeze lanes that just completed: their new bits never enter
        # the next frontier (next round's live_mask would mask them too;
        # clearing here keeps the carried state canonical).
        next_mask = bitset.pack_bits(batch.admitted & ~done)
        frontier_next = new & next_mask[:, None]
        active = jnp.sum((batch.admitted & ~done).astype(jnp.int32))
        deg = graph.out_degree.astype(jnp.uint32)
        stats = {
            # u32[B_words]: per-word send subtotals via per-node lane
            # popcounts x out_degree (32 lanes x E each stays under 2^32
            # for E <= 2^27 edges); the engine folds them into its exact
            # two-limb total.
            "messages_words": jax.vmap(lambda f: jnp.sum(
                deg * jax.lax.population_count(f)))(front),
            "active_lanes": active,
            "completed": jnp.sum(done.astype(jnp.int32)),
            # The union frontier's occupancy — what the shared
            # compaction budget (ops/frontier.py) is measured against.
            "batch_occupancy": frontier.occupancy(
                graph, jnp.any(frontier_next != 0, axis=0)),
        }
        return dataclasses.replace(
            batch, seen=seen, frontier=frontier_next, sent=sent,
            done=done, rounds=rounds, seen_count=seen_count,
        ), stats


def free_lane_count(batch: MessageBatch) -> int:
    """How many lanes :meth:`BatchFlood.admit` can still seed, read from
    the device state (one small host transfer — admission is
    control-plane work between engine calls, so the sync is off the hot
    loop). NB: graftserve's SimService deliberately does NOT use this —
    it tracks lane occupancy host-side so it can exclude cancel-pending
    lanes the device still shows admitted (serve/service.py tick()); this
    helper is for direct users of the admit/retire seam."""
    return int(batch.capacity - np.count_nonzero(np.asarray(batch.admitted)))


def lane_messages(graph: Graph, batch: MessageBatch) -> jax.Array:
    """Exact per-lane send totals, derived on demand: ``i32[capacity]``.

    A flood node broadcasts exactly once — the round after it first sees
    the message — so a lane's total sends are the out-degree-weighted
    count of its ``sent`` predicate. Deriving the total here (one
    weighted bit-plane reduction per word, per CALL) instead of
    accumulating per round keeps the hot loop free of the per-(node,
    lane) product. Always fits i32: a lane's sends are bounded by the
    directed edge count, and edge indices are i32 already.

    Totals are priced at the graph's CURRENT ``out_degree``: edges cut
    between engine calls retro-price the cut-edge sends of earlier
    rounds (a known divergence from a per-round accumulator under
    between-call edge failures; mask-static runs — including every
    in-run failure-free case the parity suite pins — are exact)."""
    return jax.vmap(
        lambda s: bitset.lane_counts(s, graph.out_degree))(
            batch.sent).reshape(-1)
