"""Push–pull gossip averaging.

The second protocol family reference users build on ``node_message``
[ref: README.md:20]: each node holds a value, repeatedly picks a random
neighbor, and averages with it — randomized gossip consensus. In the sim
backend one synchronous round is: every node draws one incoming neighbor
uniformly from its neighbor row and moves halfway toward that neighbor's
value (the synchronous-rounds form of push–pull averaging; BASELINE.json
configs[2], 100K-node Barabási–Albert).

Requires a graph built with a neighbor table (the default).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GossipState:
    values: jax.Array  # f32[N_pad]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Gossip:
    """Randomized pairwise averaging toward consensus."""

    #: Mixing weight toward the sampled neighbor (0.5 = halfway).
    alpha: float = 0.5

    def init(self, graph: Graph, key: jax.Array) -> GossipState:
        if graph.neighbors is None:
            raise ValueError("Gossip requires a graph with a neighbor table")
        values = jax.random.normal(key, (graph.n_nodes_padded,), dtype=jnp.float32)
        return GossipState(values=values * graph.node_mask)

    def step(self, graph: Graph, state: GossipState, key: jax.Array):
        from p2pnetwork_tpu.models.base import draw_neighbor_slot

        _, partner, has_slot = draw_neighbor_slot(graph, key)
        has_neighbor = has_slot & graph.node_mask
        pulled = state.values[partner]
        mixed = (1.0 - self.alpha) * state.values + self.alpha * pulled
        values = jnp.where(has_neighbor, mixed, state.values)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        mean = jnp.sum(values * graph.node_mask) / n_real
        var = jnp.sum(jnp.where(graph.node_mask, (values - mean) ** 2, 0.0)) / n_real
        stats = {
            # One pull + one push per sampling node — the message-count analog.
            "messages": 2 * jnp.sum(has_neighbor.astype(jnp.int32)),
            "variance": var,
            "mean": mean,
        }
        return GossipState(values=values), stats
