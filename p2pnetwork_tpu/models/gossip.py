"""Push–pull gossip averaging.

The second protocol family reference users build on ``node_message``
[ref: README.md:20]: each node holds a value, repeatedly picks a random
neighbor, and averages with it — randomized gossip consensus. In the sim
backend one synchronous round is: every node draws one incoming neighbor
uniformly from its neighbor row and moves halfway toward that neighbor's
value (the synchronous-rounds form of push–pull averaging; BASELINE.json
configs[2], 100K-node Barabási–Albert).

Requires a graph built with a neighbor table (the default).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GossipState:
    values: jax.Array  # f32[N_pad]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class Gossip:
    """Randomized pairwise averaging toward consensus."""

    #: Mixing weight toward the sampled neighbor (0.5 = halfway).
    alpha: float = 0.5

    def init(self, graph: Graph, key: jax.Array) -> GossipState:
        if graph.neighbors is None:
            raise ValueError("Gossip requires a graph with a neighbor table")
        values = jax.random.normal(key, (graph.n_nodes_padded,), dtype=jnp.float32)
        return GossipState(values=values * graph.node_mask)

    def step(self, graph: Graph, state: GossipState, key: jax.Array):
        n_pad = graph.n_nodes_padded
        # Each node draws one partner uniformly among its VALID table slots
        # (neighbor_mask) — the k-th set bit of its row. On a healthy graph
        # this is exactly a uniform draw over the stored neighbors; after
        # failures it keeps sampling uniform over the LIVE ones, because
        # sim/failures.py re-masks the table (a draw over min(in_degree,
        # width) prefix slots would hit dead neighbors and, after runtime
        # connects grow in_degree past the stored row, padding garbage).
        # Runtime (dynamic-region) links are not partner candidates until a
        # consolidation rebuild folds them into the table.
        mask = graph.neighbor_mask
        count = jnp.sum(mask, axis=1)
        u = jax.random.randint(key, (n_pad,), 0, jnp.int32(2**31 - 1))
        k = u % jnp.maximum(count, 1)
        csum = jnp.cumsum(mask, axis=1)
        slot = jnp.argmax((csum == (k + 1)[:, None]) & mask, axis=1)
        partner = jnp.take_along_axis(graph.neighbors, slot[:, None], axis=1)[:, 0]
        has_neighbor = (count > 0) & graph.node_mask
        pulled = state.values[partner]
        mixed = (1.0 - self.alpha) * state.values + self.alpha * pulled
        values = jnp.where(has_neighbor, mixed, state.values)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        mean = jnp.sum(values * graph.node_mask) / n_real
        var = jnp.sum(jnp.where(graph.node_mask, (values - mean) ** 2, 0.0)) / n_real
        stats = {
            # One pull + one push per sampling node — the message-count analog.
            "messages": 2 * jnp.sum(has_neighbor.astype(jnp.int32)),
            "variance": var,
            "mean": mean,
        }
        return GossipState(values=values), stats
