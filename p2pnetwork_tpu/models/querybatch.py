"""Batched query lanes: routing lookups, DHT chases and aggregations as
one lane-packed state per family.

The batched message plane (models/messagebatch.py) proved the economics
of advancing B in-flight requests with ONE compiled program per round —
but it only speaks boolean OR-flood, while production traffic is also
*queries*: "how far / which way to this peer" (routing), "who owns this
key" (DHT lookup), "what is the network-wide mean of X" (aggregation).
This module extends the lane template across that protocol zoo with
NON-BOOLEAN lane carriers (ops/lanes.py):

- :class:`MinPlusQueries` — K concurrent single-source shortest-path
  queries as a node-major ``f32[N_pad, K]`` min-plus carry; per-lane
  kernel = ``ops/segment.propagate_min_plus``, per-lane freeze when the
  target's distance settles (first arrival on unweighted graphs — BFS
  semantics — or the lane's Bellman-Ford fixpoint otherwise). The
  batched "route lookup" service primitive.
- :class:`DhtLookups` — Chord/Kademlia greedy successor chases over the
  structured overlays (sim/graph.py ``chord``/``kademlia``): one
  ``i32[K]`` cursor per lookup, one neighbor-row gather per compiled
  round resolving thousands of key lookups in O(log n) rounds.
- :class:`PushSumQueries` — B independent push-sum aggregation queries
  (per-lane kernel semantics exactly models/pushsum.py) sharing one
  edge gather per round; per-lane freeze when the lane's estimate
  variance drops under its threshold.

Template semantics are the PR-10 batch plane's, carried over verbatim:
per-lane results identical to an independent single-query run
(bit-identical int/f32-min lanes; bit-identical float op order for the
push-sum sums — tests/test_querybatch.py pins the sweeps), completed
lanes FREEZE — a correctness LATCH (a settled lane stops changing,
counting rounds, and sending), not a compute saving: the dense
``[N_pad, K]`` kernels pay the full batch width each round, so one
straggler prices the whole batch until the loop exits (unlike the flood
plane's frontier compaction) — staggered admission between engine calls
through ``admit``/``retire`` (:class:`~p2pnetwork_tpu.models.
messagebatch.LaneExhausted` is the backpressure signal, shared with the
flood plane), and the whole per-lane summary returns in one packed
transfer (``engine.run_queries_until_done``).

What is NEW versus boolean lanes is the cost model: an f32/i32 lane has
no 32-per-word packing, so K is budgeted **by bytes** —
``ops/lanes.lane_budget`` gates every family's ``init``/``admit`` and
refuses an over-HBM K with a loud
:class:`~p2pnetwork_tpu.ops.lanes.LaneBudgetExceeded` instead of an OOM
three rounds into a run (the PR-10 400 MB/round expansion lesson,
promoted to an API contract).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.models import base
from p2pnetwork_tpu.models.messagebatch import LaneExhausted
from p2pnetwork_tpu.ops import lanes as L
from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.ops.lanes import LaneBudgetExceeded  # re-export
from p2pnetwork_tpu.sim.graph import Graph
from p2pnetwork_tpu.telemetry import spans

__all__ = [
    "QueryBatch",
    "MinPlusQueries",
    "DhtLookups",
    "PushSumQueries",
    "LaneBudgetExceeded",
    "lane_dist",
    "free_query_lanes",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QueryBatch:
    """Lane-packed state of up to ``capacity`` concurrent queries of ONE
    family. ``payload`` holds the family's lane carriers (node-major —
    the lane axis is innermost, so one gathered node row moves K
    contiguous lane values, the f32 analog of 32 bit lanes riding one
    u32 word): ``{"dist": f32[N_pad, K]}`` for min-plus,
    ``{"cur": i32[K]}`` for DHT chases, ``{"s","w": f32[N_pad, K]}`` for
    push-sum. The metadata vectors mirror MessageBatch's lifecycle: a
    lane is OPEN while ``~admitted``, RUNNING while
    ``admitted & ~done``, FROZEN once ``done``; ``rounds`` counts steps
    APPLIED to the lane (identical to an independent single-query run's
    round count). ``target`` is the query argument (target node /
    lookup key; -1 where the family takes none) and ``threshold`` the
    convergence knob (push-sum's variance target; 0 elsewhere)."""

    payload: dict          # family lane carriers (see class docstring)
    source: jax.Array      # i32[K] — origin node / seed (-1 = open lane)
    target: jax.Array      # i32[K] — target node / lookup key (-1 = none)
    threshold: jax.Array   # f32[K] — convergence target (push-sum)
    admitted: jax.Array    # bool[K]
    done: jax.Array        # bool[K] — frozen (query settled)
    rounds: jax.Array      # i32[K] — steps applied per lane

    @property
    def capacity(self) -> int:
        return self.admitted.shape[0]


def _check_lane(qb: QueryBatch, lane: int) -> int:
    """Bounds-check a lane id on the poll side — an out-of-range lane
    would silently clamp into another query's column (the same footgun
    messagebatch._lane_word guards)."""
    lane = int(lane)
    if not 0 <= lane < qb.capacity:
        raise ValueError(
            f"lane {lane} outside this batch's capacity {qb.capacity} — "
            f"stale or foreign lane id?")
    return lane


def lane_dist(qb: QueryBatch, lane: int) -> jax.Array:
    """One min-plus lane's full distance field ``f32[N_pad]`` — the
    route-potential view (next hop toward the target from any node v is
    its neighbor minimizing ``dist``; the per-target scalar answer rides
    the packed summary's ``lane_values`` instead)."""
    return qb.payload["dist"][:, _check_lane(qb, lane)]


def free_query_lanes(qb: QueryBatch) -> int:
    """Open-lane count (one small host transfer — admission is
    control-plane work between engine calls)."""
    return int(qb.capacity - np.count_nonzero(np.asarray(qb.admitted)))


def _assign_lanes(qb: QueryBatch, count: int) -> np.ndarray:
    """Host-side open-lane assignment (the admission seam's control
    plane). Raises :class:`LaneExhausted` — the same typed backpressure
    signal the flood plane's admission controller already speaks."""
    open_lanes = np.flatnonzero(~np.asarray(qb.admitted))
    if count > open_lanes.size:
        raise LaneExhausted(count, open_lanes.size, qb.capacity)
    return open_lanes[:count].astype(np.int32)


def _validate_node_ids(graph: Graph, ids: np.ndarray) -> None:
    """Vectorized range check with the one canonical error message
    (base.validate_source) — K is large on the admission hot path."""
    bad = (ids < 0) | (ids >= graph.n_nodes_padded)
    if bad.any():
        base.validate_source(graph, int(ids[bad.argmax()]))


def _emit_submits(lanes_np: np.ndarray, sources: np.ndarray) -> None:
    """One ``lane_submit`` trace event per admitted query (the
    control-plane timestamp a serving front-end's latency starts from —
    the engine's ``query_run`` span later emits ``lane_admit`` when the
    lane first advances). No-op without an installed tracer."""
    if spans.current_tracer() is not None:
        for lane_id, src_id in zip(lanes_np.tolist(), sources.tolist()):
            spans.emit("lane_submit", lane=lane_id, source=src_id)


def _emit_retires(release: np.ndarray) -> None:
    if spans.current_tracer() is not None:
        for lane in np.flatnonzero(release).tolist():
            spans.emit("lane_retire", lane=lane)


def _release_mask(qb: QueryBatch, lanes_arg) -> np.ndarray:
    """The bool[K] release set of a retire call (default: every done
    lane), bounds-checked like messagebatch.retire — a numpy-wrapped -1
    would silently erase the LAST lane's in-flight query."""
    if lanes_arg is None:
        return np.asarray(qb.done)
    ids = np.asarray(lanes_arg, dtype=np.int64).reshape(-1)
    bad = (ids < 0) | (ids >= qb.capacity)
    if bad.any():
        raise ValueError(
            f"retire of lane {int(ids[bad.argmax()])} outside this "
            f"batch's capacity {qb.capacity} — stale or foreign lane id?")
    release = np.zeros(qb.capacity, dtype=bool)
    release[ids] = True
    return release


def _retire_metadata(qb: QueryBatch, payload: dict,
                     release: np.ndarray) -> QueryBatch:
    """The metadata half of retire, shared by all three families."""
    rel = jnp.asarray(release)
    return dataclasses.replace(
        qb,
        payload=payload,
        source=jnp.where(rel, -1, qb.source),
        target=jnp.where(rel, -1, qb.target),
        threshold=jnp.where(rel, 0.0, qb.threshold),
        admitted=qb.admitted & ~rel,
        done=qb.done & ~rel,
        rounds=jnp.where(rel, 0, qb.rounds),
    )


def _lane_sum(weights: jax.Array, mat: jax.Array) -> jax.Array:
    """``sum_n weights[n] * mat[n, k]`` per lane, as a GEMV: XLA CPU's
    strided axis-0 reduce runs single-threaded AND inlines the whole
    producer chain into its fusion (measured ~75-100x on the query
    steps); the dot lowering is multi-threaded and materializes its
    operands. ``Precision.HIGHEST`` keeps the TPU lowering in full f32 —
    these sums decide completion and price messages, and the default MXU
    precision would bf16-round them."""
    return jnp.einsum("n,nk->k", weights, mat,
                      precision=jax.lax.Precision.HIGHEST)


def _live_messages(live: jax.Array, per_lane: jax.Array) -> jax.Array:
    """Aggregate this round's sends across live lanes as u32 — exact
    while ``K * E < 2^32`` (the engine's two-limb fold consumes one
    sub-2^32 subtotal per round, the ``messages_words`` contract)."""
    return jnp.sum(jnp.where(live, per_lane, 0).astype(jnp.uint32))


def _empty_metadata(capacity: int) -> dict:
    cap = int(capacity)
    if cap < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    return dict(
        source=jnp.full(cap, -1, dtype=jnp.int32),
        target=jnp.full(cap, -1, dtype=jnp.int32),
        threshold=jnp.zeros(cap, dtype=jnp.float32),
        admitted=jnp.zeros(cap, dtype=bool),
        done=jnp.zeros(cap, dtype=bool),
        rounds=jnp.zeros(cap, dtype=jnp.int32),
    )


# --------------------------------------------------------------- min-plus


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class MinPlusQueries:
    """K concurrent shortest-path/route lookups: lane k asks "what is
    the cheapest cost from ``source[k]`` to ``target[k]``" and relaxes a
    full distance column per round (``ops/lanes.
    propagate_min_plus_lanes`` — per lane exactly ``propagate_min_plus``,
    so weights/unit hops follow the graph).

    Completion is "the target's distance settled": on UNWEIGHTED graphs
    a finite distance is final the round it appears (BFS first arrival),
    so the lane freezes at first touch; on weighted graphs — and for
    unreachable targets — the lane freezes at its Bellman-Ford fixpoint
    (a round that changed nothing), where every distance is exact.
    The per-lane answer (``dist[target]``; +inf = unreachable) rides the
    packed summary as ``lane_values``; the full route-potential field
    stays readable per lane via :func:`lane_dist`."""

    method: str = "auto"          # ops/lanes.py lowering
    budget_bytes: int = None      # lane_budget override (None = default)

    VALUES_FLOAT = True           # lane_values dtype (engine pack hint)

    def _budget(self, graph: Graph, capacity: int) -> None:
        L.lane_budget(capacity, jnp.float32, graph.n_nodes_padded,
                      carriers=1, budget_bytes=self.budget_bytes)

    def empty(self, graph: Graph, capacity: int) -> QueryBatch:
        """An all-open batch of ``capacity`` route-lookup lanes —
        byte-budget-gated (f32 lanes pay full width; there is no
        32-per-word discount here)."""
        self._budget(graph, capacity)
        n_pad = graph.n_nodes_padded
        return QueryBatch(
            payload={"dist": jnp.full((n_pad, int(capacity)), jnp.inf,
                                      dtype=jnp.float32)},
            **_empty_metadata(capacity))

    def init(self, graph: Graph, sources, targets, *,
             capacity: int = None) -> QueryBatch:
        """A fresh batch with one lane admitted per (source, target)
        pair; ``capacity`` reserves open lanes for later admit waves."""
        sources = np.asarray(sources, dtype=np.int32).reshape(-1)
        targets = np.asarray(targets, dtype=np.int32).reshape(-1)
        if sources.size == 0:
            raise ValueError("init needs at least one query")
        if sources.size != targets.size:
            raise ValueError(
                f"{sources.size} sources vs {targets.size} targets — "
                "route lookups are (source, target) pairs")
        cap = capacity if capacity is not None else sources.size
        if cap < sources.size:
            raise ValueError(f"capacity {cap} < {sources.size} queries")
        qb = self.empty(graph, cap)
        qb, _ = self.admit(graph, qb, sources, targets)
        return qb

    def admit(self, graph: Graph, qb: QueryBatch, sources, targets):
        """Seed new route lookups into OPEN lanes; returns
        ``(batch, lane_ids)``. A query whose source IS its (live) target
        starts ``done`` with distance 0 (the admission-time completion,
        like a flood already at coverage); a dead source seeds an all-inf
        lane that settles to "unreachable" in one round. Raises
        :class:`LaneExhausted` when lanes run out and
        :class:`LaneBudgetExceeded` when the batch itself is over the
        byte budget (hand-built batches bypass ``empty``'s gate)."""
        self._budget(graph, qb.capacity)
        sources = np.asarray(sources, dtype=np.int32).reshape(-1)
        targets = np.asarray(targets, dtype=np.int32).reshape(-1)
        if sources.size != targets.size:
            raise ValueError(
                f"{sources.size} sources vs {targets.size} targets — "
                "route lookups are (source, target) pairs")
        if sources.size == 0:
            return qb, np.zeros(0, dtype=np.int32)
        _validate_node_ids(graph, sources)
        _validate_node_ids(graph, targets)
        lanes_np = _assign_lanes(qb, sources.size)
        src = jnp.asarray(sources)
        tgt = jnp.asarray(targets)
        lanes_j = jnp.asarray(lanes_np)
        seeded = graph.node_mask[src]          # dead source seeds nothing
        seed_val = jnp.where(seeded, 0.0, jnp.inf).astype(jnp.float32)
        dist = qb.payload["dist"].at[src, lanes_j].set(seed_val)
        _emit_submits(lanes_np, sources)
        return dataclasses.replace(
            qb,
            payload={"dist": dist},
            source=qb.source.at[lanes_j].set(src),
            target=qb.target.at[lanes_j].set(tgt),
            admitted=qb.admitted.at[lanes_j].set(True),
            done=qb.done.at[lanes_j].set(seeded & (src == tgt)),
            rounds=qb.rounds.at[lanes_j].set(0),
        ), lanes_np

    def retire(self, qb: QueryBatch, lanes=None) -> QueryBatch:
        """Release lanes back to OPEN (default: every done lane),
        resetting their distance columns to +inf. Read results first —
        this erases them."""
        release = _release_mask(qb, lanes)
        _emit_retires(release)
        rel = jnp.asarray(release)
        dist = jnp.where(rel[None, :], jnp.inf, qb.payload["dist"])
        return _retire_metadata(qb, {"dist": dist}, release)

    def refresh(self, graph: Graph, qb: QueryBatch) -> QueryBatch:
        """Completion is LATCHED, like the flood plane's: a settled
        route answer stays answered when later failures change the graph
        (its lane froze; re-resolving after churn is a NEW query via
        admit). Running lanes relax against the CURRENT mask from the
        next step on. Nothing here is mask-derived, so refresh is the
        identity — the hook exists for engine-template parity (the
        entry calls it eagerly, where a recomputing refresh would
        otherwise dead-code a donated input leaf)."""
        return qb

    def step(self, graph: Graph, qb: QueryBatch, key: jax.Array):
        """One Bellman-Ford round of every RUNNING lane; frozen/open
        lanes are masked out of the column update and pay nothing."""
        dist = qb.payload["dist"]
        live = qb.admitted & ~qb.done
        relaxed = jnp.minimum(
            dist, L.propagate_min_plus_lanes(graph, dist, self.method))
        new_dist = jnp.where(live[None, :], relaxed, dist)
        # One improvement field serves the fixpoint check AND the
        # message count; reduced via einsum (see stats below).
        improved_f = (new_dist != dist).astype(jnp.float32)
        ones = jnp.ones(graph.n_nodes_padded, jnp.float32)
        changed = _lane_sum(ones, improved_f) > 0          # bool[K]
        k_idx = jnp.arange(qb.capacity)
        tgt = jnp.clip(qb.target, 0, graph.n_nodes_padded - 1)
        at_target = new_dist[tgt, k_idx]                  # f32[K]
        settled = ~changed                                 # lane fixpoint
        if graph.edge_weight is None:
            # Unit hops: first arrival IS the shortest distance (BFS) —
            # the target settles the round it turns finite.
            finished = jnp.isfinite(at_target) | settled
        else:
            # Weighted: only the fixpoint certifies the target's
            # distance (a cheaper multi-hop path may still be in
            # flight).
            finished = settled
        done = qb.done | (live & finished)
        rounds = qb.rounds + live.astype(jnp.int32)
        # Message model: nodes whose distance IMPROVED advertise along
        # their out-edges (the distance-vector frontier semantics,
        # models/routing.py), priced off the same improvement field the
        # fixpoint check reads, via the _lane_sum GEMV. The f32 dot is
        # exact while a lane's per-round sum stays under 2^24 — i.e.
        # E < ~16.7M directed edges; past that this TELEMETRY count is
        # approximate (the completion math never rides it, and the
        # engine's two-limb fold stays exact in what it is fed).
        per_lane = _lane_sum(graph.out_degree.astype(jnp.float32),
                             improved_f).astype(jnp.int32)
        stats = {
            "messages": _live_messages(live, per_lane),
            "changed_lanes": jnp.sum((live & changed).astype(jnp.int32)),
        }
        return dataclasses.replace(
            qb, payload={"dist": new_dist}, done=done, rounds=rounds,
        ), stats

    def lane_values(self, graph: Graph, qb: QueryBatch) -> jax.Array:
        """Per-lane answer for the packed summary: ``dist[target]``
        (f32[K]; +inf = unreachable or open lane)."""
        k_idx = jnp.arange(qb.capacity)
        tgt = jnp.clip(qb.target, 0, graph.n_nodes_padded - 1)
        return jnp.where(qb.admitted, qb.payload["dist"][tgt, k_idx],
                         jnp.inf)


# -------------------------------------------------------------- DHT chase


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class DhtLookups:
    """K concurrent DHT key lookups as greedy successor chases: lane k
    holds a cursor that hops, each round, to its closest live neighbor
    under the overlay metric (``ring`` = Chord's clockwise identifier
    distance, ``xor`` = Kademlia's) — ``ops/lanes.dht_hop_lanes``, one
    neighbor-row gather serving every lookup. On the structured overlays
    (sim/graph.py ``chord``/``kademlia``) a lookup resolves in O(log n)
    hops; the lane freezes when the cursor ARRIVES (cursor == key's
    node) or STALLS (no strictly closer neighbor — dead responsible
    node, partitioned overlay: the lookup's honest failure mode).

    Keys live in the real id space ``[0, n_nodes)`` — the overlay
    geometry's modulus. The per-lane answer (final cursor, i32) rides
    the packed summary; ``found`` is ``lane_values == target``."""

    metric: str = "ring"          # ops/lanes.DHT_METRICS
    budget_bytes: int = None

    VALUES_FLOAT = False          # lane_values are raw i32 node ids

    def __post_init__(self):
        if self.metric not in L.DHT_METRICS:
            raise ValueError(
                f"unknown DHT metric {self.metric!r} — one of "
                f"{L.DHT_METRICS}")

    def _budget(self, graph: Graph, capacity: int) -> None:
        # The cursor state is O(1) per lane (i32 cursor; n_pad plays no
        # part) — budgeted all the same so a million-lookup admit on a
        # tight budget still fails loudly instead of surprising later.
        L.lane_budget(capacity, jnp.int32, 1, carriers=1,
                      budget_bytes=self.budget_bytes)

    def empty(self, graph: Graph, capacity: int) -> QueryBatch:
        self._budget(graph, capacity)
        return QueryBatch(
            payload={"cur": jnp.zeros(int(capacity), dtype=jnp.int32)},
            **_empty_metadata(capacity))

    def init(self, graph: Graph, origins, keys, *,
             capacity: int = None) -> QueryBatch:
        """A fresh batch with one lookup admitted per (origin, key)
        pair."""
        origins = np.asarray(origins, dtype=np.int32).reshape(-1)
        keys = np.asarray(keys, dtype=np.int32).reshape(-1)
        if origins.size == 0:
            raise ValueError("init needs at least one lookup")
        if origins.size != keys.size:
            raise ValueError(
                f"{origins.size} origins vs {keys.size} keys — DHT "
                "lookups are (origin, key) pairs")
        cap = capacity if capacity is not None else origins.size
        if cap < origins.size:
            raise ValueError(f"capacity {cap} < {origins.size} lookups")
        qb = self.empty(graph, cap)
        qb, _ = self.admit(graph, qb, origins, keys)
        return qb

    def admit(self, graph: Graph, qb: QueryBatch, origins, keys):
        """Seed new lookups into OPEN lanes; returns ``(batch,
        lane_ids)``. An origin already AT the key completes at admission
        (0 hops); a dead origin completes immediately as a failed lookup
        (a crashed node issues nothing). Keys must live in
        ``[0, n_nodes)`` — the metric's modulus."""
        self._budget(graph, qb.capacity)
        origins = np.asarray(origins, dtype=np.int32).reshape(-1)
        keys = np.asarray(keys, dtype=np.int32).reshape(-1)
        if origins.size != keys.size:
            raise ValueError(
                f"{origins.size} origins vs {keys.size} keys — DHT "
                "lookups are (origin, key) pairs")
        if origins.size == 0:
            return qb, np.zeros(0, dtype=np.int32)
        _validate_node_ids(graph, origins)
        bad = (keys < 0) | (keys >= graph.n_nodes)
        if bad.any():
            raise ValueError(
                f"lookup key {int(keys[bad.argmax()])} outside the "
                f"overlay id space [0, {graph.n_nodes}) — keys speak "
                "the ring/xor metric's modulus, not the padded space")
        lanes_np = _assign_lanes(qb, origins.size)
        org = jnp.asarray(origins)
        key_ids = jnp.asarray(keys)
        lanes_j = jnp.asarray(lanes_np)
        alive = graph.node_mask[org]
        _emit_submits(lanes_np, origins)
        return dataclasses.replace(
            qb,
            payload={"cur": qb.payload["cur"].at[lanes_j].set(org)},
            source=qb.source.at[lanes_j].set(org),
            target=qb.target.at[lanes_j].set(key_ids),
            admitted=qb.admitted.at[lanes_j].set(True),
            done=qb.done.at[lanes_j].set((org == key_ids) | ~alive),
            rounds=qb.rounds.at[lanes_j].set(0),
        ), lanes_np

    def retire(self, qb: QueryBatch, lanes=None) -> QueryBatch:
        release = _release_mask(qb, lanes)
        _emit_retires(release)
        rel = jnp.asarray(release)
        cur = jnp.where(rel, 0, qb.payload["cur"])
        return _retire_metadata(qb, {"cur": cur}, release)

    def refresh(self, graph: Graph, qb: QueryBatch) -> QueryBatch:
        """Identity — an arrived lookup stays arrived (latched, like
        every lane completion); a running chase re-routes around nodes
        that died between calls at its next hop, since hop validity
        reads the CURRENT mask."""
        return qb

    def step(self, graph: Graph, qb: QueryBatch, key: jax.Array):
        """One greedy hop of every RUNNING lookup (one message per hop);
        frozen/open lanes keep their cursor and send nothing."""
        cur = qb.payload["cur"]
        live = qb.admitted & ~qb.done
        nxt, hopped = L.dht_hop_lanes(graph, cur, qb.target, self.metric)
        new_cur = jnp.where(live, nxt, cur)
        arrived = new_cur == qb.target
        finished = arrived | ~hopped       # stalled = no closer neighbor
        done = qb.done | (live & finished)
        rounds = qb.rounds + live.astype(jnp.int32)
        stats = {
            "messages": _live_messages(live & hopped,
                                       jnp.ones_like(qb.rounds)),
            "arrived_lanes": jnp.sum((live & arrived).astype(jnp.int32)),
        }
        return dataclasses.replace(
            qb, payload={"cur": new_cur}, done=done, rounds=rounds,
        ), stats

    def lane_values(self, graph: Graph, qb: QueryBatch) -> jax.Array:
        """Per-lane answer: the final cursor (i32[K]; -1 on open lanes).
        ``found`` is ``lane_values == target`` — a stalled chase's
        cursor names the closest reachable node, the overlay's honest
        "who should own it" fallback."""
        return jnp.where(qb.admitted, qb.payload["cur"], -1)


# --------------------------------------------------------------- push-sum


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class PushSumQueries:
    """B independent push-sum aggregation queries sharing one edge
    gather per round: lane k runs models/pushsum.py's mass-splitting
    consensus over its OWN seeded value field (``s``/``w`` lane columns)
    and freezes when its estimate variance drops under the lane's
    threshold — "what is the network mean of X" as a batched, admitted,
    retirable query.

    Per-lane float semantics are EXACTLY the single
    :class:`~p2pnetwork_tpu.models.pushsum.PushSum` run's: the lane
    kernels accumulate in ``propagate_sum(method="segment")``'s edge
    order and the share multiply is the same two-f32 product, so a
    lane's s/w trajectory matches an independent run from the same seed
    float op for float op — the order contract
    tests/test_querybatch.py pins bitwise step-for-step (eager; the
    compiled loop may fuse the share multiply-add, a documented
    last-ulp freedom the same-K isolation pin bounds).
    Each lane's seed field is ``normal(fold_in(key(seed_salt), seed))``
    masked to live nodes, exactly ``PushSum.init``'s recipe with the
    lane's seed folded in."""

    method: str = "auto"          # ops/lanes.py lowering
    seed_salt: int = 0            # base key of the per-lane value fields
    budget_bytes: int = None

    VALUES_FLOAT = True           # lane_values are f32 mean estimates

    def _budget(self, graph: Graph, capacity: int) -> None:
        L.lane_budget(capacity, jnp.float32, graph.n_nodes_padded,
                      carriers=2, budget_bytes=self.budget_bytes)

    def empty(self, graph: Graph, capacity: int) -> QueryBatch:
        """An all-open batch — byte-budget-gated at TWO f32 carriers
        per lane (s and w both ride the loop)."""
        self._budget(graph, capacity)
        n_pad = graph.n_nodes_padded
        zeros = jnp.zeros((n_pad, int(capacity)), dtype=jnp.float32)
        return QueryBatch(payload={"s": zeros, "w": zeros},
                          **_empty_metadata(capacity))

    def init(self, graph: Graph, seeds, *, threshold: float = 1e-4,
             capacity: int = None) -> QueryBatch:
        """A fresh batch with one aggregation query admitted per seed."""
        seeds = np.asarray(seeds, dtype=np.int32).reshape(-1)
        if seeds.size == 0:
            raise ValueError("init needs at least one query")
        cap = capacity if capacity is not None else seeds.size
        if cap < seeds.size:
            raise ValueError(f"capacity {cap} < {seeds.size} queries")
        qb = self.empty(graph, cap)
        qb, _ = self.admit(graph, qb, seeds, threshold=threshold)
        return qb

    def admit(self, graph: Graph, qb: QueryBatch, seeds, *,
              threshold: float = 1e-4):
        """Seed new aggregation queries into OPEN lanes; returns
        ``(batch, lane_ids)``. Every admitted lane runs at least one
        round before its variance is consulted — matching
        ``run_until_converged``'s value0=inf contract, so a batched lane
        and an independent single run apply identical step counts."""
        self._budget(graph, qb.capacity)
        if not threshold > 0:
            raise ValueError(
                f"threshold must be > 0, got {threshold} (push-sum "
                "variance has an f32 floor — see run_until_converged)")
        seeds = np.asarray(seeds, dtype=np.int32).reshape(-1)
        if seeds.size == 0:
            return qb, np.zeros(0, dtype=np.int32)
        lanes_np = _assign_lanes(qb, seeds.size)
        lanes_j = jnp.asarray(lanes_np)
        base_key = jax.random.key(self.seed_salt)
        keys = jax.vmap(lambda s: jax.random.fold_in(base_key, s))(
            jnp.asarray(seeds))
        n_pad = graph.n_nodes_padded
        values = jax.vmap(
            lambda k: jax.random.normal(k, (n_pad,), dtype=jnp.float32)
        )(keys)                                     # f32[count, N_pad]
        mask_f = graph.node_mask.astype(jnp.float32)
        s_cols = (values * mask_f[None, :]).T       # node-major columns
        w_cols = jnp.broadcast_to(mask_f[:, None],
                                  (n_pad, int(seeds.size)))
        _emit_submits(lanes_np, seeds)
        return dataclasses.replace(
            qb,
            payload={"s": qb.payload["s"].at[:, lanes_j].set(s_cols),
                     "w": qb.payload["w"].at[:, lanes_j].set(w_cols)},
            source=qb.source.at[lanes_j].set(jnp.asarray(seeds)),
            threshold=qb.threshold.at[lanes_j].set(
                jnp.float32(threshold)),
            admitted=qb.admitted.at[lanes_j].set(True),
            done=qb.done.at[lanes_j].set(False),
            rounds=qb.rounds.at[lanes_j].set(0),
        ), lanes_np

    def retire(self, qb: QueryBatch, lanes=None) -> QueryBatch:
        release = _release_mask(qb, lanes)
        _emit_retires(release)
        rel = jnp.asarray(release)
        payload = {k: jnp.where(rel[None, :], 0.0, v)
                   for k, v in qb.payload.items()}
        return _retire_metadata(qb, payload, release)

    def refresh(self, graph: Graph, qb: QueryBatch) -> QueryBatch:
        """Identity — converged estimates latch; running lanes keep
        consenting over the CURRENT mask (mass conservation holds per
        compiled run, where the mask is static)."""
        return qb


    def _variance(self, graph: Graph, s: jax.Array,
                  w: jax.Array) -> jax.Array:
        """Per-lane estimate variance over live nodes — the same
        ``est``/``mean``/``var`` math as models/pushsum.py's stats (the
        mask multiply replaces its ``where``: identical f32 values).
        The [N, K] -> [K] reductions ride einsum (GEMV): XLA CPU's
        strided axis-0 reduce runs single-threaded and drags the whole
        producer chain into its fusion (measured ~100x on this step)."""
        mask_f = graph.node_mask.astype(jnp.float32)
        est = jnp.where(w > 0, s / jnp.maximum(w, 1e-30), 0.0)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        mean = _lane_sum(mask_f, est) / n_real
        return _lane_sum(mask_f, (est - mean[None, :]) ** 2) / n_real

    def step(self, graph: Graph, qb: QueryBatch, key: jax.Array):
        """One mass-splitting round of every RUNNING lane — two shared
        edge gathers advance all of them (models/pushsum.py's step, per
        column). Frozen lanes keep their masses untouched.

        Convergence is checked on the ENTERING masses — a lane whose
        variance is already under threshold freezes before stepping.
        That applies exactly the same step count as check-after-step
        semantics (run_until_converged's: the round that crosses is the
        last applied either way) while letting the check read the loop
        CARRY, which keeps the variance fusion decoupled from this
        round's gather chains (the check-after form re-inlined them,
        measured ~100x). The one visible difference: a lane already
        under threshold AT ADMISSION completes with 0 rounds, like a
        flood admitted at coverage."""
        s, w = qb.payload["s"], qb.payload["w"]
        var = self._variance(graph, s, w)
        done = qb.done | (qb.admitted & (var < qb.threshold))
        live = qb.admitted & ~done
        mask_f = graph.node_mask.astype(jnp.float32)
        shares = 1.0 / (graph.out_degree.astype(jnp.float32) + 1.0)
        # Kept share and sent shares both read ONE materialized s_sh
        # (two consumers), the same structure — and so the same float
        # ops — as PushSum.step's s_share.
        s_sh = s * shares[:, None]
        w_sh = w * shares[:, None]
        s2 = (s_sh + L.propagate_sum_lanes(graph, s_sh,
                                           self.method)) * mask_f[:, None]
        w2 = (w_sh + L.propagate_sum_lanes(graph, w_sh,
                                           self.method)) * mask_f[:, None]
        rounds = qb.rounds + live.astype(jnp.int32)
        # One share per outgoing edge of every live node, per live lane
        # (models/pushsum.py's message model).
        per_round = segment.frontier_messages(graph, graph.node_mask)
        stats = {
            "messages": (per_round.astype(jnp.uint32)
                         * jnp.sum(live.astype(jnp.uint32))),
            "variance_max": jnp.max(jnp.where(live, var, 0.0)),
        }
        return dataclasses.replace(
            qb,
            payload={"s": jnp.where(live[None, :], s2, s),
                     "w": jnp.where(live[None, :], w2, w)},
            done=done, rounds=rounds,
        ), stats

    def lane_values(self, graph: Graph, qb: QueryBatch) -> jax.Array:
        """Per-lane answer: the network-mean estimate (f32[K]) — the
        aggregation result each query asked for (0 on open lanes)."""
        s, w = qb.payload["s"], qb.payload["w"]
        est = jnp.where(w > 0, s / jnp.maximum(w, 1e-30), 0.0)
        mask_f = graph.node_mask.astype(jnp.float32)
        n_real = jnp.maximum(jnp.sum(graph.node_mask), 1)
        mean = _lane_sum(mask_f, est) / n_real
        return jnp.where(qb.admitted, mean, 0.0)
