"""Anti-entropy replication: push–pull set reconciliation.

What reference users actually ship dict messages for [ref:
examples/dict_application, README.md:20]: every peer holds a partial
set of items (rumors, key versions, file announcements) and
periodically reconciles with a random neighbor until everyone has
everything — Demers-style anti-entropy, the epidemic backbone of
eventually-consistent stores. Batched TPU form: state is the whole
population's possession matrix ``bool[N_pad, n_items]``; one round
draws each node's partner with Gossip's k-th-set-bit slot draw, then
merges sets both ways — pull as a gather-OR from the partner's row,
push as a scatter-OR onto it (``.at[partner].max``). Items can only
travel along live table edges, and possession is monotone — the two
invariants the tests pin.

Stats expose ``missing`` (live-node item gaps — converge with
``engine.run_until_converged(..., stat="missing", threshold=1)``:
quiescence is full replication on a connected overlay), ``coverage``
(filled fraction of the live possession matrix), ``complete_items``
(items already everywhere), and the push/pull message count.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from p2pnetwork_tpu.sim.graph import Graph


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class AntiEntropyState:
    have: jax.Array  # bool[N_pad, n_items] — possession matrix
    round: jax.Array  # i32[]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class AntiEntropy:
    """Push–pull anti-entropy over the neighbor table."""

    #: Number of replicated items; each starts on one uniform live node.
    n_items: int = 64
    #: Exchange directions — defaults to full push–pull (fastest
    #: epidemic); disable one to measure the push-only / pull-only
    #: convergence phases the literature contrasts.
    push: bool = True
    pull: bool = True

    def init(self, graph: Graph, key: jax.Array) -> AntiEntropyState:
        if graph.neighbors is None:
            raise ValueError(
                "AntiEntropy requires a graph with a neighbor table")
        if not (self.push or self.pull):
            raise ValueError("enable push, pull, or both")
        n_pad = graph.n_nodes_padded
        p = graph.node_mask / jnp.maximum(jnp.sum(graph.node_mask), 1)
        holders = jax.random.choice(key, n_pad, (self.n_items,), p=p)
        have = jnp.zeros((n_pad, self.n_items), dtype=bool)
        have = have.at[holders, jnp.arange(self.n_items)].set(True)
        return AntiEntropyState(have=have & graph.node_mask[:, None],
                                round=jnp.int32(0))

    def step(self, graph: Graph, state: AntiEntropyState, key: jax.Array):
        from p2pnetwork_tpu.models.base import draw_neighbor_slot

        _, partner, has_slot = draw_neighbor_slot(graph, key)
        active = has_slot & graph.node_mask & graph.node_mask[partner]

        have = state.have
        sendable = have & active[:, None]
        if self.pull:
            have = have | (state.have[partner] & active[:, None])
        if self.push:
            # Scatter-OR each active node's set onto its partner; inactive
            # rows scatter all-False (index 0 is harmless then).
            have = have.at[jnp.where(active, partner, 0)].max(sendable)
        have = have & graph.node_mask[:, None]

        n_live = jnp.maximum(jnp.sum(graph.node_mask), 1)
        held = jnp.sum(have, axis=0)  # per item
        missing = n_live * self.n_items - jnp.sum(held)
        exchanged = int(self.push) + int(self.pull)
        stats = {
            "messages": exchanged * jnp.sum(active.astype(jnp.int32)),
            "missing": missing,
            "coverage": jnp.sum(held) / (n_live * self.n_items),
            "complete_items": jnp.sum(held == n_live),
        }
        return AntiEntropyState(have=have, round=state.round + 1), stats
