"""Deadline watchdog: runtime detection of wedged device dispatches.

bench.py's ``_backend_alive`` probe catches a wedged device tunnel *before*
a run launches; nothing caught one wedging *mid-run* — a dispatch that
never returns holds the GIL-side caller forever and the only witness is
wall clock. :class:`Watchdog` is that witness: a daemon thread fed
heartbeats by the chunked run loop (supervise/runner.py), firing a
structured stall event when the gap between heartbeats exceeds the
deadline.

Stall handling mirrors PR 4's ``retrace_guard`` modes:

- ``"raise"`` (default): the stall is recorded when detected, and
  :class:`StallTimeout` is raised in the *supervised* thread at its next
  ``heartbeat()`` (or at context exit). A truly wedged dispatch never
  reaches that heartbeat — which is exactly why the next mode exists.
- ``"warn"``: a ``RuntimeWarning`` from the watchdog thread the moment
  the stall is detected.
- callable: invoked with the watchdog from the watchdog thread at
  detection time — the driver seam (emit a structured record, trigger an
  emergency checkpoint of the last undonated state, kill the process so
  a supervisor restarts it).

Every stall increments ``supervise_watchdog_timeouts_total{name}`` and
publishes the observed gap as the ``supervise_stall_seconds{name}`` gauge
(which keeps climbing while the stall persists — a live scrape of a
wedged run shows a growing number, not a one-shot blip).

The watchdog thread's waits are bounded (graftlint ``wait-untimed``): it
sleeps at most the time remaining to the current deadline, and ``close``
joins it with a timeout.
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Callable, Optional, Union

from p2pnetwork_tpu import concurrency, telemetry

__all__ = ["Watchdog", "StallTimeout"]


class StallTimeout(RuntimeError):
    """A supervised dispatch exceeded its heartbeat deadline."""

    def __init__(self, name: str, stalled_s: float, deadline_s: float):
        self.name = name
        self.stalled_s = stalled_s
        self.deadline_s = deadline_s
        super().__init__(
            f"watchdog[{name}]: no heartbeat for {stalled_s:.1f}s "
            f"(deadline {deadline_s:.1f}s) — device dispatch wedged?")


class Watchdog:
    """Deadline watchdog over a heartbeat stream.

    Usage::

        with Watchdog(deadline_s=30.0, name="1m") as dog:
            for chunk in chunks:
                dog.heartbeat()       # raises StallTimeout here if a
                run_chunk(chunk)      # previous gap breached the deadline
            # exit also raises a pending stall (mode "raise")

    Thread-safe: ``heartbeat`` may be called from any thread; detection
    runs on the watchdog's own daemon thread so a dispatch that never
    returns still produces a stall event (modes "warn"/callable fire from
    that thread at detection time).
    """

    def __init__(self, deadline_s: float, *, name: str = "run",
                 on_stall: Union[str, Callable[["Watchdog"], None]] = "raise",
                 registry: Optional[telemetry.Registry] = None):
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if not (on_stall in ("raise", "warn") or callable(on_stall)):
            raise ValueError("on_stall must be 'raise', 'warn' or callable")
        self.deadline_s = float(deadline_s)
        self.name = str(name)
        self.on_stall = on_stall
        reg = registry if registry is not None else telemetry.default_registry()
        self._m_timeouts = reg.counter(
            "supervise_watchdog_timeouts_total",
            "Stall events fired by supervised-run watchdogs (one per "
            "heartbeat gap exceeding the deadline).", ("watchdog",)
        ).labels(self.name)
        self._m_stall = reg.gauge(
            "supervise_stall_seconds",
            "Seconds since the supervised run's last heartbeat, as "
            "observed by its watchdog — climbs while a dispatch is "
            "wedged, resets on the next heartbeat.", ("watchdog",)
        ).labels(self.name)
        self._lock = concurrency.lock()
        self._stop = concurrency.event()
        self._last_beat = time.monotonic()
        self._fired_this_gap = False     # one stall event per heartbeat gap
        self._pending_raise: Optional[StallTimeout] = None
        #: Total stall events fired over the watchdog's lifetime.
        self.stalls = 0
        #: Gap length of the most recent stall event (seconds).
        self.last_stall_s = 0.0
        self._thread: Optional[Any] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Watchdog":
        if self._thread is not None:
            raise RuntimeError("Watchdog already started")
        self._stop.clear()
        now = time.monotonic()
        with self._lock:
            self._last_beat = now
            self._fired_this_gap = False
        self._thread = concurrency.thread(
            target=self._watch, name=f"Watchdog({self.name})", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop the watchdog thread (bounded join; idempotent). Resets the
        stall gauge: a closed watchdog is not witnessing a stall, and a
        lingering non-zero ``supervise_stall_seconds`` would read as an
        ongoing wedge on an idle process."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.deadline_s + 5.0)
            self._thread = None
        self._m_stall.set(0.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        if exc_type is None:
            self.check()  # a pending stall surfaces even without a final beat
        return False

    # ------------------------------------------------------------ heartbeat

    def heartbeat(self) -> None:
        """Record liveness. In mode ``"raise"``, a stall detected since the
        previous heartbeat raises :class:`StallTimeout` here — in the
        supervised thread, where the caller can unwind cleanly."""
        now = time.monotonic()
        with self._lock:
            self._last_beat = now
            self._fired_this_gap = False
        self._m_stall.set(0.0)
        self.check()

    def check(self) -> None:
        """Raise any pending stall (mode ``"raise"``); no-op otherwise."""
        with self._lock:
            pending, self._pending_raise = self._pending_raise, None
        if pending is not None:
            raise pending

    # ------------------------------------------------------------- internal

    def _watch(self) -> None:
        while True:
            now = time.monotonic()
            with self._lock:
                gap = now - self._last_beat
                remaining = self.deadline_s - gap
                stalled = remaining <= 0
                fire = stalled and not self._fired_this_gap
                if fire:
                    self._fired_this_gap = True
                    self.stalls += 1
                    self.last_stall_s = gap
            if stalled:
                # Keep the gauge climbing while the stall persists; re-check
                # on a short cadence so heartbeat resets surface quickly.
                self._m_stall.set(gap)
                wait = min(1.0, self.deadline_s)
            else:
                wait = max(remaining, 0.01)
            if fire:
                self._m_timeouts.inc()
                self._fire(gap)
            if self._stop.wait(timeout=wait):
                return

    def _fire(self, gap: float) -> None:
        err = StallTimeout(self.name, gap, self.deadline_s)
        if self.on_stall == "raise":
            with self._lock:
                self._pending_raise = err
        elif self.on_stall == "warn":
            warnings.warn(str(err), RuntimeWarning, stacklevel=2)
        else:
            try:
                self.on_stall(self)
            except Exception as e:  # a crashing driver hook must not kill
                # the watchdog thread — the NEXT stall still needs a witness.
                warnings.warn(
                    f"watchdog[{self.name}]: on_stall callback raised "
                    f"{type(e).__name__}: {e}", RuntimeWarning, stacklevel=2)
