"""SupervisedRun: crash-tolerant chunked execution of the sim engine.

The engine's run-to-* loops (sim/engine.py) are single device programs —
maximally fast, and maximally fragile: a preemption or a wedged device
tunnel mid-run loses everything since the last *manual*
``sim/checkpoint.py`` save. :class:`SupervisedRun` drives those same loops
in round chunks and owns everything around them:

- **auto-checkpoint** every N rounds or T seconds into a
  :class:`~p2pnetwork_tpu.supervise.store.CheckpointStore` (atomic entries,
  manifest latest-pointer, retention, corrupt-skip resume);
- **resume**: a run killed at any point — SIGKILL included — restarts from
  the newest loadable entry and produces a final state **bit-identical**
  to an uninterrupted supervised run (tests/test_supervise.py proves it
  under double SIGKILL);
- **watchdog**: a deadline thread fed heartbeats at chunk boundaries
  (supervise/watchdog.py) turns a wedged dispatch into a structured stall
  event at runtime, not just at bench probe time;
- **deterministic preemption**: ``arm_preemption`` / ``failures.preempt``
  kill the harness at an exact round (:class:`Preempted`), and the next
  ``run_*`` call revives it from the last durable checkpoint.

Determinism contract: the PRNG chain is keyed per chunk as
``fold_in(base_key, chunk_start_round + 1)``, and chunk boundaries are a
pure function of (chunk_rounds, start round). Checkpoints only land at
chunk boundaries, so a resumed run re-enters exactly the boundary schedule
the uninterrupted run walked — same chunk keys, same states. (Chunked runs
differ from *unchunked* ``engine.run_until_coverage`` only in RNG chain;
PRNG-independent protocols like Flood are bit-identical to those too.)

Donation across chunks preserves PR 3's semantics: the state carry is
donated between chunks (one live copy in HBM), EXCEPT the chunk that feeds
a checkpoint save, which runs ``donate=False`` — its input state stays
alive as the in-memory fallback, so a dispatch that dies at a checkpoint
boundary (exactly where stalls get killed) still leaves the harness a
valid state to emergency-checkpoint before unwinding
(:meth:`SupervisedRun.emergency_checkpoint`, also safe to call from an
``on_stall`` hook).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Union

import jax
import numpy as np

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.supervise.store import CheckpointStore
from p2pnetwork_tpu.supervise.watchdog import Watchdog
from p2pnetwork_tpu.telemetry import spans

__all__ = ["SupervisedRun", "Preempted"]


class Preempted(RuntimeError):
    """The harness was deterministically killed at a round boundary
    (``failures.preempt`` / ``arm_preemption``). Revive by calling the
    same ``run_*`` entry again — it resumes from the last durable
    checkpoint, never from this exception's in-memory state."""

    def __init__(self, round_index: int):
        self.round_index = round_index
        super().__init__(
            f"supervised run preempted at round {round_index} "
            f"(resume from the checkpoint store to revive)")


class SupervisedRun:
    """Crash-tolerant harness over ``engine.run_from`` /
    ``engine.run_until_coverage_from``.

    Parameters
    ----------
    graph, protocol:
        Exactly the engine's contract.
    store:
        A :class:`CheckpointStore`, or a directory path (a store with
        ``retain`` entries is created there).
    chunk_rounds:
        Rounds per device dispatch. Smaller chunks mean finer checkpoint
        and heartbeat granularity at the cost of more host round-trips;
        the per-chunk overhead is one dispatch plus one packed-summary
        transfer (coverage mode).
    checkpoint_every_rounds / checkpoint_every_s:
        Auto-checkpoint cadence, whichever fires first, evaluated at
        chunk boundaries. Defaults to every chunk when neither is set.
    deadline_s / on_stall:
        Watchdog deadline per chunk dispatch and its stall mode
        (``"raise"`` / ``"warn"`` / callable, like ``retrace_guard``).
        ``None`` disables the watchdog.
    heal:
        A :class:`~p2pnetwork_tpu.supervise.heal.RetryPolicy`
        (graftquake self-healing): every chunk dispatch runs undonated
        under a :class:`~p2pnetwork_tpu.supervise.heal.Healer`, so a
        detected DISPATCH fault (injected chip preemption, wedged
        dispatch, watchdog stall surfaced inside the dispatch) rolls
        the chunk back to its retained input and re-executes with the
        SAME chunk key — the healed run is bit-identical to an
        unfaulted one. Integrity DETECTION (template audit, checksum
        cross-validation) needs a template/verify dispatch the generic
        runner cannot derive — drive
        :meth:`~p2pnetwork_tpu.supervise.heal.Healer.run_chunk`
        directly to add those. Costs one extra live state copy;
        ``None`` (default) keeps mid-cadence chunk donation.
    on_chunk:
        Optional ``callable(run, info)`` fired after every chunk with
        ``{"round", "executed", "coverage", "checkpointed"}`` — the
        progress seam (bench telemetry, tests).
    """

    def __init__(self, graph, protocol,
                 store: Union[CheckpointStore, str], *,
                 chunk_rounds: int = 32,
                 checkpoint_every_rounds: Optional[int] = None,
                 checkpoint_every_s: Optional[float] = None,
                 retain: int = 3,
                 deadline_s: Optional[float] = None,
                 on_stall: Union[str, Callable] = "raise",
                 heal=None,
                 on_chunk: Optional[Callable] = None,
                 registry: Optional[telemetry.Registry] = None):
        if chunk_rounds < 1:
            raise ValueError("chunk_rounds must be >= 1")
        if checkpoint_every_rounds is not None and checkpoint_every_rounds < 1:
            raise ValueError("checkpoint_every_rounds must be >= 1")
        self.graph = graph
        self.protocol = protocol
        self.store = store if isinstance(store, CheckpointStore) \
            else CheckpointStore(store, retain=retain, registry=registry)
        self.chunk_rounds = int(chunk_rounds)
        if checkpoint_every_rounds is None and checkpoint_every_s is None:
            checkpoint_every_rounds = self.chunk_rounds
        self.checkpoint_every_rounds = checkpoint_every_rounds
        self.checkpoint_every_s = checkpoint_every_s
        self.deadline_s = deadline_s
        self.on_stall = on_stall
        self.heal = heal
        self.on_chunk = on_chunk
        self._registry = registry
        reg = registry if registry is not None else telemetry.default_registry()
        self._m_chunks = reg.counter(
            "supervise_chunks_total",
            "Device-dispatch chunks executed by supervised runs.")
        self._m_runs = reg.counter(
            "supervise_runs_total",
            "Supervised run invocations, by outcome.", ("outcome",))
        self._m_resumes = reg.counter(
            "supervise_resumes_total",
            "Supervised runs that restored state from the checkpoint store "
            "instead of a fresh protocol init.")
        self._preempt_at: Optional[int] = None
        # Fallback snapshot for emergency checkpoints: the undonated input
        # of a checkpoint-boundary chunk, published for the duration of
        # that chunk's dispatch. Guarded: the watchdog's on_stall hook
        # reads it from the watchdog thread while the run thread swaps it.
        self._fb_lock = concurrency.lock()
        self._fallback: Optional[tuple] = None

    # ----------------------------------------------------------- preemption

    def arm_preemption(self, at_round: int) -> None:
        """Arm a one-shot deterministic kill: the chunk loop raises
        :class:`Preempted` at the first chunk boundary at or past
        ``at_round``, BEFORE taking any checkpoint due there — exactly the
        damage a real SIGKILL at that moment inflicts. Prefer arming via
        ``sim.failures.preempt``, which also counts the injection."""
        self._preempt_at = int(at_round)

    # ------------------------------------------------------------ emergency

    def emergency_checkpoint(self) -> Optional[str]:
        """Persist the current fallback state, if one is alive.

        Safe from any thread (an ``on_stall`` hook runs on the watchdog
        thread). Only checkpoint-boundary chunks publish a fallback (their
        input runs undonated); mid-cadence chunks have donated their input
        away, so there is nothing valid to save and this returns ``None``.
        """
        with self._fb_lock:
            fb = self._fallback
        if fb is None:
            return None
        state, base_key, rnd, msgs = fb
        return self.store.save(state, base_key, rnd, msgs)

    def _set_fallback(self, fb: Optional[tuple]) -> None:
        with self._fb_lock:
            self._fallback = fb

    # ----------------------------------------------------------- entrypoints

    def run_until_coverage(self, key, *, coverage_target: float = 0.99,
                           max_rounds: int = 1024, steps_per_round: int = 1,
                           resume: bool = True) -> tuple:
        """Supervised ``engine.run_until_coverage_from``: chunked, auto-
        checkpointed, resumable. Returns ``(state, summary)`` where
        ``summary`` carries ``rounds`` (cumulative, resumed rounds
        included), ``coverage``, exact ``messages``, plus supervision
        fields (``chunks``, ``checkpoints``, ``resumed_from``,
        ``checkpoint_path``, ``stalls``).

        ``key`` seeds a FRESH run only; on resume the checkpoint's stored
        base key is authoritative (the RNG chain must continue the
        interrupted run's, not start a new one). A fresh start into a
        directory still holding a previous trail CLEARS that trail —
        ``resume=False`` means this run owns the directory."""
        return self._drive("coverage", key, max_rounds,
                           coverage_target=coverage_target,
                           steps_per_round=steps_per_round, resume=resume)

    def run_rounds(self, key, rounds: int, *, resume: bool = True) -> tuple:
        """Supervised ``engine.run_from``: execute ``rounds`` total rounds
        (checkpointed progress counts toward the total on resume).
        Returns ``(state, summary)``."""
        return self._drive("rounds", key, rounds, resume=resume)

    # ------------------------------------------------------------ the loop

    def _restore_or_init(self, key, resume: bool):
        template = jax.eval_shape(
            lambda k: self.protocol.init(self.graph, k), key)
        template = jax.tree_util.tree_map(
            lambda s: np.zeros(s.shape, s.dtype), template)
        # grow=True: a trail written before a Graph.grow capacity repad is
        # still this run's trail — zero-extend it into the grown template
        # (checkpoint.grow_state) so resume-across-repad is bit-identical
        # to an uninterrupted grown run. Identity when shapes match.
        restored = self.store.load_latest(template, grow=True) \
            if resume else None
        if restored is not None:
            state, base_key, rnd, msgs, path = restored
            # device_put once: checkpoint leaves come back as host numpy,
            # and donating committed host buffers is a silent no-op plus a
            # warning — land them on device so chunk donation is real.
            state = jax.device_put(state)
            self._m_resumes.inc()
            return state, base_key, int(rnd), int(msgs), int(rnd)
        # Fresh start (resume=False, or nothing in the trail loaded): any
        # leftover entries belong to a PREVIOUS run — clear them, or this
        # run's round-N checkpoints would interleave with (and resume
        # under) the stale trail's higher rounds.
        if self.store.entries():
            self.store.clear()
        state = self.protocol.init(self.graph, key)
        return state, key, 0, 0, None

    def _ckpt_due(self, rounds_since: int, t_last: float) -> bool:
        if self.checkpoint_every_rounds is not None \
                and rounds_since >= self.checkpoint_every_rounds:
            return True
        if self.checkpoint_every_s is not None \
                and time.monotonic() - t_last >= self.checkpoint_every_s:
            return True
        return False

    def _drive(self, mode: str, key, total_target: int, *,
               coverage_target: float = 0.99, steps_per_round: int = 1,
               resume: bool = True) -> tuple:
        # graftscope trace plane: one supervised_run span per drive,
        # chunk boundaries / checkpoints / resumes as point events under
        # it (telemetry/spans.py; no-ops when no tracer is installed).
        with spans.span("supervised_run", mode=mode):
            return self._drive_under_span(
                mode, key, total_target, coverage_target=coverage_target,
                steps_per_round=steps_per_round, resume=resume)

    def _drive_under_span(self, mode: str, key, total_target: int, *,
                          coverage_target: float = 0.99,
                          steps_per_round: int = 1,
                          resume: bool = True) -> tuple:
        state, base_key, total, messages, resumed_from = \
            self._restore_or_init(key, resume)
        if resumed_from is not None:
            spans.emit("resume", round=total)
        last_ckpt_round, t_last_ckpt = total, time.monotonic()
        coverage = None
        chunks = n_ckpts = 0
        last_path = None
        outcome = "completed"
        watchdog = None
        if self.deadline_s is not None:
            watchdog = Watchdog(self.deadline_s, name=f"supervised-{mode}",
                                on_stall=self.on_stall,
                                registry=self._registry).start()
        healer = None
        if self.heal is not None:
            from p2pnetwork_tpu.supervise.heal import Healer

            # Rollback authority is the RETAINED chunk input (healing
            # forces donate=False below), never the store: the store's
            # newest entry can be an older boundary, and re-executing
            # one chunk from an older round would corrupt the round
            # accounting this loop owns.
            healer = Healer(self.heal, registry=self._registry)
        try:
            while total < total_target:
                chunk = min(self.chunk_rounds, total_target - total)
                ckpt_feeding = self._ckpt_due(
                    total + chunk - last_ckpt_round, t_last_ckpt) \
                    or (total + chunk >= total_target)
                chunk_key = jax.random.fold_in(base_key, total + 1)
                if watchdog is not None:
                    watchdog.heartbeat()
                if ckpt_feeding:
                    # This chunk feeds a checkpoint save: keep its input
                    # alive (donate=False) as the emergency fallback for
                    # the duration of the dispatch (module docstring).
                    self._set_fallback((state, base_key, total, messages))
                try:
                    donate_chunk = healer is None and not ckpt_feeding
                    if mode == "coverage":
                        def _chunk_cov(s, _key=chunk_key, _n=chunk):
                            return engine.run_until_coverage_from(
                                self.graph, self.protocol, s, _key,
                                coverage_target=coverage_target,
                                max_rounds=_n,
                                steps_per_round=steps_per_round,
                                donate=donate_chunk)

                        if healer is not None:
                            state, out = healer.run_chunk(
                                _chunk_cov, state, chunk_index=chunks)
                        else:
                            state, out = _chunk_cov(state)
                        executed = int(out["rounds"])  # graftlint: ignore[host-sync-in-loop] -- packed summary already transferred by the engine; these are host scalars
                        messages += int(out["messages"])  # graftlint: ignore[host-sync-in-loop] -- host scalar (see above)
                        coverage = float(out["coverage"])  # graftlint: ignore[host-sync-in-loop] -- host scalar (see above)
                    else:
                        def _chunk_rounds(s, _key=chunk_key, _n=chunk):
                            return engine.run_from(
                                self.graph, self.protocol, s, _key,
                                _n, donate=donate_chunk)

                        if healer is not None:
                            state, stats = healer.run_chunk(
                                _chunk_rounds, state, chunk_index=chunks)
                        else:
                            state, stats = _chunk_rounds(state)
                        executed = chunk
                        if "messages" in stats:
                            messages += int(  # graftlint: ignore[host-sync-in-loop] -- one transfer per CHUNK is the supervised design (checkpoint totals need it), not a per-round sync
                                np.asarray(stats["messages"]).sum())
                except BaseException:
                    # The dispatch died mid-chunk. If this was a boundary
                    # chunk its input is still valid — make it durable so
                    # even a crash the periodic cadence missed resumes
                    # from here, then unwind.
                    try:
                        self.emergency_checkpoint()
                    except Exception:
                        pass  # a failing save must not mask the real error
                    raise
                finally:
                    self._set_fallback(None)
                if watchdog is not None:
                    watchdog.heartbeat()
                total += executed
                chunks += 1
                self._m_chunks.inc()
                done = (total >= total_target or
                        (mode == "coverage" and
                         (executed == 0 or
                          (coverage is not None
                           and coverage >= coverage_target))))
                if self._preempt_at is not None \
                        and total >= self._preempt_at:
                    # Deterministic kill: fires BEFORE the checkpoint due
                    # at this boundary, like a real SIGKILL would.
                    self._preempt_at = None
                    outcome = "preempted"
                    raise Preempted(total)
                checkpointed = False
                if done or self._ckpt_due(total - last_ckpt_round,
                                          t_last_ckpt):
                    last_path = self.store.save(
                        state, base_key, total, messages)
                    last_ckpt_round, t_last_ckpt = total, time.monotonic()
                    n_ckpts += 1
                    checkpointed = True
                    spans.emit("checkpoint", round=total, path=last_path)
                spans.emit("chunk", round=total, executed=executed,
                           checkpointed=checkpointed)
                # graftsight: a chunk that needed healing leaves its
                # attempt history on the healer — surface it next to the
                # chunk event (correlated by round) and hand it to the
                # on_chunk observer, so a supervised soak's trace answers
                # "which chunks healed, from what" without log archaeology.
                heal_report = None if healer is None else healer.last_report
                if heal_report is not None and heal_report["events"]:
                    spans.emit("heal_report", round=total,
                               chunk=heal_report["chunk"],
                               attempts=heal_report["attempts"],
                               healed=heal_report["healed"],
                               fallback=heal_report["fallback"])
                if self.on_chunk is not None:
                    self.on_chunk(self, {
                        "round": total, "executed": executed,
                        "coverage": coverage, "checkpointed": checkpointed,
                        "heal": heal_report,
                    })
                if done:
                    break
        except Preempted:
            raise
        except BaseException:
            outcome = "error"
            raise
        finally:
            if watchdog is not None:
                watchdog.close()
            self._m_runs.labels(outcome).inc()
        summary: Dict[str, Any] = {
            "rounds": total, "messages": messages, "chunks": chunks,
            "checkpoints": n_ckpts, "resumed_from": resumed_from,
            "checkpoint_path": last_path,
            "stalls": watchdog.stalls if watchdog is not None else 0,
        }
        if coverage is not None:
            summary["coverage"] = coverage
        return state, summary
