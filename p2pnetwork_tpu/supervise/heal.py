"""Self-healing recovery: integrity checks + policy-routed chunk retry.

supervise/runner.py recovers from process DEATH (checkpoint + resume);
nothing recovered from DETECTED bad state — a corrupted halo word, a
chip lost mid-traffic, a wedged dispatch. This module is that half:

- **Detection** — cheap end-of-chunk integrity checks on the harvested
  carry, surfaced as typed :class:`IntegrityViolation` naming the
  failing leaf, chunk and shard: a shape/dtype/finiteness audit against
  the state template (:func:`audit_state`), monotonicity invariants on
  the batch plane's latched progress (:func:`check_monotonic` — seen
  bits only gain, counters/rounds never regress, done never unlatches),
  and an optional checksum cross-validation against a *replicated
  reference fold* (re-executing the chunk on the trusted path — the
  single-chip engine or the clean comm backend, bit-identical peers by
  the PR-11 parity pin — and comparing :func:`state_checksum`). The
  cheap checks catch state damage; the reference fold catches
  semantically-consistent comm corruption, which no local invariant
  can.

- **Recovery** — :class:`RetryPolicy` (exponential backoff with seeded
  deterministic jitter, a max-attempt budget, per-failure-class action
  routing) driving :class:`Healer.run_chunk`: roll the chunk back to
  its input (the retained undonated state, or the last
  :class:`~p2pnetwork_tpu.supervise.store.CheckpointStore` entry when a
  store is configured), optionally reroute to a fallback dispatch
  (clean comm backend / single-chip engine), re-execute. Chunk keys are
  the supervise schedule (``fold_in(base_key, round + 1)``), so a
  healed re-run is bit-identical to a chunk that never faulted.

Retries count into ``heal_retries_total{outcome}`` (``retry`` /
``fallback`` routing decisions, ``healed`` chunks that recovered,
``exhausted`` budget overruns); detected integrity failures count into
``quake_integrity_failures_total{kind}`` and every rollback into
``heal_rollbacks_total{source}``; the trace plane gets ``heal_retry``
/ ``heal_rollback`` / ``heal_recovered`` ride-along events (integrity
failures carry their check kind). :attr:`Healer.last_report` keeps the
most recent chunk's attempt history as a plain dict so an adopter —
graftserve's driver — can replay what happened to the lanes riding
that chunk as per-ticket correlated trace events (graftsight).

Top-level import is stdlib-only (jax/numpy defer into the check
functions) so bench.py's parent process can share :class:`RetryPolicy`
for its probe backoff without touching jax.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, Mapping, Optional

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.chaos.device import ChipLost, WedgedDispatch
from p2pnetwork_tpu.supervise.watchdog import StallTimeout
from p2pnetwork_tpu.telemetry import spans

__all__ = [
    "IntegrityViolation", "RetryPolicy", "Healer", "classify_failure",
    "audit_state", "check_monotonic", "state_checksum",
]

#: Healer retry-policy actions a failure class can route to.
ACTIONS = ("retry", "fallback", "raise")

#: Default per-failure-class routing: deterministic comm corruption
#: (integrity) re-runs the SAME faults if retried in place, so it routes
#: to the fallback path; one-shot dispatch faults (preempt/wedge) retry
#: where they ran.
DEFAULT_ROUTES: Mapping[str, str] = {
    "integrity": "fallback",
    "preempt": "retry",
    "wedged": "retry",
}


class IntegrityViolation(RuntimeError):
    """A detected-bad-state failure: the end-of-chunk integrity checks
    rejected a harvested carry. ``kind`` names the check (``template`` /
    ``nonfinite`` / ``monotonicity`` / ``checksum``), ``leaf`` the
    failing state leaf, ``chunk`` the chunk index, ``shard`` the shard
    when the check localizes one."""

    def __init__(self, kind: str, *, leaf: str = "", chunk: int = -1,
                 shard: Optional[int] = None, detail: str = ""):
        self.kind = kind
        self.leaf = leaf
        self.chunk = int(chunk)
        self.shard = shard
        self.detail = detail
        where = f"chunk {chunk}" + (f", shard {shard}"
                                    if shard is not None else "")
        what = f" leaf {leaf!r}" if leaf else ""
        tail = f": {detail}" if detail else ""
        super().__init__(f"integrity violation [{kind}] at {where}{what}"
                         f"{tail}")


# --------------------------------------------------------------- checks


def _named_leaves(state):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def audit_state(state, template, *, chunk: int = -1) -> None:
    """Template audit of a harvested carry: every leaf must match the
    template's shape and dtype, and float leaves must be finite (the
    corrupt fault's bitcast bit-flips mint NaN/Inf patterns). Raises
    :class:`IntegrityViolation` on the first failing leaf."""
    import numpy as np

    got = _named_leaves(state)
    want = _named_leaves(template)
    if len(got) != len(want):
        raise IntegrityViolation(
            "template", chunk=chunk,
            detail=f"state has {len(got)} leaves, template {len(want)}")
    for (name, leaf), (_, tpl) in zip(got, want):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        if shape != tuple(tpl.shape) or str(dtype) != str(tpl.dtype):
            raise IntegrityViolation(
                "template", leaf=name, chunk=chunk,
                detail=f"got {shape}/{dtype}, template "
                       f"{tuple(tpl.shape)}/{tpl.dtype}")
        arr = np.asarray(leaf)  # graftlint: ignore[host-sync-in-loop] -- ONE audited host pull of the harvested carry per CHUNK is this check's documented cost; never per round
        if np.issubdtype(arr.dtype, np.floating) \
                and not np.isfinite(arr).all():
            raise IntegrityViolation(
                "nonfinite", leaf=name, chunk=chunk,
                detail="non-finite values in a float leaf")


def check_monotonic(prev, curr, *, chunk: int = -1) -> None:
    """Monotonicity invariants between one chunk's input and output for
    batch-plane states (duck-typed on the MessageBatch fields; other
    state shapes pass through — their progress algebra is not latched):
    seen bits only GAIN, per-lane seen counts and round counts never
    regress, done never unlatches. Catches zeroing/rollback damage that
    a per-leaf audit cannot (each side is individually well-formed).

    Assumes a FIXED live population between the chunk's input and
    output: node failures applied to the graph between healed chunks
    make the entry-time refresh legitimately LOWER ``seen_count`` under
    the new mask (the seen BITS still only gain). Apply churn at healer
    boundaries with ``monotonic=False`` for that chunk, or re-baseline
    — the in-tree adopters (graftserve, SupervisedRun) hold their graph
    fixed, so they never hit this."""
    import numpy as np

    if not (hasattr(curr, "seen") and hasattr(curr, "seen_count")
            and hasattr(curr, "done") and hasattr(curr, "rounds")):
        return
    prev_seen = np.asarray(prev.seen)
    curr_seen = np.asarray(curr.seen)
    lost = prev_seen & ~curr_seen
    if lost.any():
        raise IntegrityViolation(
            "monotonicity", leaf="seen", chunk=chunk,
            detail=f"{int(np.count_nonzero(lost))} seen words lost bits")
    if (np.asarray(curr.seen_count) < np.asarray(prev.seen_count)).any():
        raise IntegrityViolation(
            "monotonicity", leaf="seen_count", chunk=chunk,
            detail="per-lane coverage numerator regressed")
    if (np.asarray(curr.rounds) < np.asarray(prev.rounds)).any():
        raise IntegrityViolation(
            "monotonicity", leaf="rounds", chunk=chunk,
            detail="per-lane round counter regressed")
    if (np.asarray(prev.done) & ~np.asarray(curr.done)).any():
        raise IntegrityViolation(
            "monotonicity", leaf="done", chunk=chunk,
            detail="a completed lane's done flag unlatched")


def state_checksum(state) -> str:
    """sha256 over every leaf's bytes (shape/dtype framed) — the
    bit-identity witness the checksum cross-validation compares between
    a chunk result and its replicated reference fold."""
    import numpy as np

    h = hashlib.sha256()
    for name, leaf in _named_leaves(state):
        arr = np.asarray(leaf)  # graftlint: ignore[host-sync-in-loop] -- the checksum IS a per-chunk host fold of every leaf; bounded by the state size, once per chunk
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


# --------------------------------------------------------------- policy


def _seeded_unit(seed: int, salt: int, attempt: int) -> float:
    """Deterministic uniform in [0, 1) from (seed, salt, attempt) — a
    sha256 fold, identical on every platform (no RNG state, no wall
    clock)."""
    digest = hashlib.sha256(
        f"{seed}:{salt}:{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


def classify_failure(exc: BaseException) -> Optional[str]:
    """The failure class a retry policy routes on, or None for
    exceptions healing must not swallow (caller errors, supervise
    Preempted kills, anything unknown)."""
    if isinstance(exc, IntegrityViolation):
        return "integrity"
    if isinstance(exc, ChipLost):
        return "preempt"
    if isinstance(exc, (WedgedDispatch, StallTimeout)):
        return "wedged"
    return None


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with seeded jitter plus per-class routing.

    ``backoff_s(attempt)`` is ``backoff_base_s * 2**(attempt-1)`` capped
    at ``backoff_max_s``, jittered by ``±jitter/2`` of itself with a
    deterministic sha256-seeded uniform — same (seed, salt, attempt) ⇒
    same delay, on any platform (bench.py's probe loop shares this, so
    probe logs are replayable). ``routes`` maps a failure class
    (:func:`classify_failure`) to an action in :data:`ACTIONS`;
    unlisted classes raise."""

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0
    routes: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_ROUTES))

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        for cls, action in self.routes.items():
            if action not in ACTIONS:
                raise ValueError(
                    f"route for {cls!r} must be one of {ACTIONS}, "
                    f"got {action!r}")

    def backoff_s(self, attempt: int, salt: int = 0) -> float:
        """Delay before retrying after the ``attempt``-th failure
        (1-based)."""
        if attempt < 1:
            raise ValueError("attempt is 1-based")
        base = min(self.backoff_base_s * (2.0 ** (attempt - 1)),
                   self.backoff_max_s)
        u = _seeded_unit(self.seed, salt, attempt)
        return max(0.0, base * (1.0 + self.jitter * (u - 0.5)))

    def delays(self, n: int, salt: int = 0):
        """The first ``n`` backoff delays — what a probe log records."""
        return [self.backoff_s(a, salt) for a in range(1, n + 1)]

    def action_for(self, failure_class: Optional[str]) -> str:
        return self.routes.get(failure_class, "raise") \
            if failure_class is not None else "raise"


# --------------------------------------------------------------- healer


class Healer:
    """The recovery engine: wrap a chunk dispatch with integrity checks,
    rollback and policy-routed retry.

    ``dispatch`` callables are ``state -> (state, out)`` and MUST NOT
    donate their input (the retained input is the rollback fallback and
    the monotonicity baseline — run the engine loops with
    ``donate=False`` under healing; one extra live state copy is the
    cost of rollback eligibility). Rollback prefers the configured
    :class:`CheckpointStore`'s newest loadable entry (``store`` +
    ``template``) — the durable authority — and falls back to the
    retained input.

    Checks per attempt: template audit (when ``template`` is set),
    monotonicity (``monotonic=True``, batch-plane states), and the
    checksum cross-validation when a ``verify`` dispatch is given —
    the replicated reference fold re-executes the chunk on the trusted
    path and the results must be bit-identical (the comm backends and
    the engine/sharded pair are pinned exact peers, so there are no
    false positives — and no tolerance for silent wrong answers).
    """

    def __init__(self, policy: Optional[RetryPolicy] = None, *,
                 template: Any = None, monotonic: bool = True,
                 fallback_dispatch: Optional[Callable] = None,
                 verify_dispatch: Optional[Callable] = None,
                 store=None,
                 registry: Optional[telemetry.Registry] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        self.policy = policy if policy is not None else RetryPolicy()
        self.template = template
        self.monotonic = bool(monotonic)
        self.fallback_dispatch = fallback_dispatch
        self.verify_dispatch = verify_dispatch
        self.store = store
        self._sleep = sleep if sleep is not None else concurrency.sleep
        reg = registry if registry is not None \
            else telemetry.default_registry()
        self._m_retries = reg.counter(
            "heal_retries_total",
            "Healing decisions by outcome: retry/fallback route taken, "
            "healed chunk recovered, exhausted attempt budget.",
            ("outcome",))
        self._m_integrity = reg.counter(
            "quake_integrity_failures_total",
            "Integrity-check rejections by check kind "
            "(template/nonfinite/monotonicity/checksum).",
            ("kind",))
        self._m_rollbacks = reg.counter(
            "heal_rollbacks_total",
            "Chunk rollbacks before a retry, by rollback source: the "
            "checkpoint store's newest entry or the retained undonated "
            "input.", ("source",))
        #: Attempt history of the most recent :meth:`run_chunk` call —
        #: ``{"chunk", "attempts", "healed", "fallback", "exhausted",
        #: "events": [{"attempt", "failure", "action", "degraded",
        #: "integrity_kind"?, "leaf"?}, ...]}``. Plain data, written by
        #: the chunk-driving thread only (driver-confined like the
        #: serve plane's batch state); ``None`` until the first chunk.
        self.last_report: Optional[dict] = None

    # ------------------------------------------------------------ checks

    def check(self, prev, state, *, chunk: int = -1) -> None:
        """The cheap always-on integrity checks (template + finiteness +
        monotonicity): ONE host pull of the harvested carry per CHUNK
        (shared by both checks — never per round) plus the input's four
        latched-progress leaves when monotonicity applies. States the
        monotonicity duck-typing rejects (engine protocol tuples) cost
        nothing here unless a template audit is configured."""
        monotonic_applies = (
            self.monotonic and prev is not None
            and all(hasattr(state, f)
                    for f in ("seen", "seen_count", "done", "rounds")))
        if self.template is None and not monotonic_applies:
            return
        import jax

        state_h = jax.device_get(state)
        if self.template is not None:
            audit_state(state_h, self.template, chunk=chunk)
        if monotonic_applies:
            check_monotonic(prev, state_h, chunk=chunk)

    # ------------------------------------------------------------- drive

    def _rollback_input(self, retained, chunk: int):
        if self.store is not None and self.template is not None:
            restored = self.store.load_latest(self.template)
            if restored is not None:
                self._m_rollbacks.labels("store").inc()
                if spans.current_tracer() is not None:
                    spans.emit("heal_rollback", chunk=chunk,
                               round=int(restored[2]),
                               path=restored[4])
                import jax

                return jax.device_put(restored[0])
        self._m_rollbacks.labels("retained").inc()
        if spans.current_tracer() is not None:
            spans.emit("heal_rollback", chunk=chunk, round=-1,
                       path="")
        return retained

    def run_chunk(self, dispatch: Callable, state, *, chunk_index: int = -1,
                  salt: Optional[int] = None,
                  fallback: Optional[Callable] = None,
                  verify: Optional[Callable] = None):
        """Execute one chunk with healing; returns ``(state, out)``.

        ``fallback`` / ``verify`` override the healer-level dispatches
        for this chunk (chunked drivers rebuild them per chunk key).
        Unroutable failures propagate untouched; a routable failure
        rolls back, backs off (seeded, deterministic) and re-executes —
        on the fallback path when the policy says so — until the
        attempt budget exhausts."""
        fallback = fallback if fallback is not None \
            else self.fallback_dispatch
        verify = verify if verify is not None else self.verify_dispatch
        salt = chunk_index if salt is None else salt
        current = dispatch
        on_fallback = False
        failed = False
        attempt = 0
        report = {"chunk": int(chunk_index), "attempts": 0,
                  "healed": False, "fallback": False, "exhausted": False,
                  "events": []}
        self.last_report = report
        while True:
            attempt += 1
            report["attempts"] = attempt
            inp = state if attempt == 1 \
                else self._rollback_input(state, chunk_index)
            try:
                new_state, out = current(inp)
                self.check(inp, new_state, chunk=chunk_index)
                if verify is not None and not on_fallback:
                    ref_state, _ = verify(inp)
                    if state_checksum(new_state) != state_checksum(ref_state):
                        raise IntegrityViolation(
                            "checksum", chunk=chunk_index,
                            detail="chunk result diverges from the "
                                   "replicated reference fold")
                if failed:
                    report["healed"] = True
                    report["fallback"] = on_fallback
                    self._m_retries.labels("healed").inc()
                    if spans.current_tracer() is not None:
                        spans.emit("heal_recovered", chunk=chunk_index,
                                   attempts=attempt,
                                   fallback=on_fallback)
                return new_state, out
            except (IntegrityViolation, ChipLost, WedgedDispatch,
                    StallTimeout) as e:
                failed = True
                cls = classify_failure(e)
                entry = {"attempt": attempt, "failure": cls,
                         "action": "", "degraded": False}
                if isinstance(e, IntegrityViolation):
                    entry["integrity_kind"] = e.kind
                    entry["leaf"] = e.leaf
                    self._m_integrity.labels(e.kind).inc()
                report["events"].append(entry)
                action = self.policy.action_for(cls)
                if action == "raise" or attempt >= self.policy.max_attempts:
                    # "exhausted" counts BUDGET overruns only — a
                    # raise-routed class propagating on attempt 1 is a
                    # routing decision, not an exhausted budget.
                    if attempt >= self.policy.max_attempts:
                        report["exhausted"] = True
                        self._m_retries.labels("exhausted").inc()
                    entry["action"] = "raise"
                    raise
                # The outcome label records the decision taken on THIS
                # failure — a retry-routed failure after the fallback
                # path engaged still counts as "retry". A fallback route
                # with no fallback dispatch configured degrades to an
                # in-place retry; that degrade is made visible (trace
                # event field) because re-running DETERMINISTIC comm
                # corruption in place reproduces it — though for the
                # single-chip drivers, where integrity damage means a
                # transient, the in-place retry is the right response.
                degraded = action == "fallback" and fallback is None
                if action == "fallback" and not degraded:
                    current = fallback
                    on_fallback = True
                    outcome = "fallback"
                else:
                    outcome = "retry"
                entry["action"] = outcome
                entry["degraded"] = degraded
                self._m_retries.labels(outcome).inc()
                if spans.current_tracer() is not None:
                    spans.emit("heal_retry", chunk=chunk_index,
                               attempt=attempt, failure=cls,
                               action=outcome, degraded=degraded,
                               integrity_kind=entry.get("integrity_kind",
                                                        ""))
                delay = self.policy.backoff_s(attempt, salt=salt)
                if delay > 0:
                    self._sleep(delay)
