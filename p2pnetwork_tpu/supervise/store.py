"""Atomic checkpoint *directory* protocol on top of ``sim/checkpoint.py``.

One ``sim/checkpoint.py`` file is atomic (tmp + rename), but a single file
is a single point of damage: a run that overwrites its one checkpoint and
is SIGKILLed a moment later has nothing if that file turns out unreadable.
:class:`CheckpointStore` keeps a small rotation instead:

- every entry is its own content-hashed file
  (``ckpt_r<round>_<sha12>.npz``), written atomically and never rewritten;
- ``manifest.json`` is the latest-pointer plus the entry index, updated by
  atomic rename AFTER the entry lands — a kill between the two leaves the
  previous manifest intact and at worst one orphaned (complete, loadable)
  entry file;
- retention keeps the last ``retain`` entries, pruning oldest-first;
- resume (:meth:`load_latest`) walks entries newest-first, verifying the
  manifest's file hash and the in-file digest (``checkpoint.load``), and
  SKIPS corrupt/partial/missing entries instead of dying on them — a
  SIGKILL mid-save costs at most the cadence since the previous entry.

The store knows nothing about protocols or engines: it moves
``(state, key, round, message_count)`` tuples, exactly the
``sim/checkpoint.py`` contract. ``supervise/runner.py`` owns cadence and
resume policy.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import warnings
from typing import Any, Dict, List, Optional, Tuple

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.sim import checkpoint as ckpt
from p2pnetwork_tpu.telemetry import spans

__all__ = ["CheckpointStore", "atomic_write_json"]

_MANIFEST = "manifest.json"


def atomic_write_json(path: str, doc: Any, *,
                      suffix: str = ".json.tmp",
                      durable: bool = True) -> None:
    """Rename-publish ``doc`` as JSON at ``path``: tmp file in the same
    directory, ``os.replace``, tmp unlinked on failure. The ONE home of
    this crash-safety pattern — the manifest below and graftserve's
    sidecar (serve/service.py) both publish through it, so the graftdur
    hardening below landed everywhere at once.

    ``durable=True`` (default) closes the power-loss windows a bare
    rename leaves open: the temp file is fsynced BEFORE the rename (so
    the name can never point at unwritten bytes) and the directory
    entry is fsynced AFTER it (so the publish itself survives the
    cut). A SIGKILL never needed either — the rename is atomic in the
    page cache — so callers on the hot path that only fear kills (not
    power) may pass ``durable=False`` and skip both syncs. The
    directory fsync is best-effort: some filesystems refuse
    ``open(O_RDONLY)`` on directories, and losing IT costs only the
    rename, never consistency."""
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)), suffix=suffix)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    if durable:
        try:
            dfd = os.open(os.path.dirname(os.path.abspath(path)),
                          os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)
        except OSError:
            pass
        finally:
            os.close(dfd)


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


class CheckpointStore:
    """A retention-bounded directory of content-hashed checkpoints.

    Single-*process* by design (one supervised run owns one directory),
    but not single-thread: ``emergency_checkpoint`` is documented safe
    from a watchdog ``on_stall`` hook, so the manifest read-modify-write
    in :meth:`save` is serialized by a lock. Readers (resume, the bench
    parent publishing a partial record) only ever see complete files
    because both the entries and the manifest are rename-published.
    """

    def __init__(self, directory: str, *, retain: int = 3,
                 registry: Optional[telemetry.Registry] = None):
        if retain < 1:
            raise ValueError("retain must be >= 1")
        self.directory = os.path.abspath(directory)
        self.retain = int(retain)
        os.makedirs(self.directory, exist_ok=True)
        # Serializes the manifest read-modify-write: the run thread's
        # boundary save can race an emergency_checkpoint fired from the
        # watchdog's on_stall thread.
        self._save_lock = concurrency.lock()
        reg = registry if registry is not None else telemetry.default_registry()
        self._m_written = reg.counter(
            "supervise_checkpoints_written_total",
            "Checkpoint entries durably published by supervised runs.")
        self._m_skipped = reg.counter(
            "supervise_checkpoints_skipped_total",
            "Checkpoint entries skipped during resume, by cause (corrupt "
            "in-file digest, manifest/file hash mismatch, missing file, "
            "template mismatch; manifest-missing counts a resume that "
            "fell back to a directory scan because the manifest itself "
            "was gone or unreadable).", ("reason",))

    # -------------------------------------------------------------- writing

    def save(self, state: Any, key, round_index: int,
             message_count: int = 0) -> str:
        """Durably publish one checkpoint entry; returns its path.

        Write order is the crash-safety argument: (1) the entry lands
        under a temp name via ``checkpoint.save`` (itself atomic), (2) it
        is renamed to its content-hashed final name, (3) the manifest is
        rename-replaced to reference it, (4) retention prunes. A SIGKILL
        after any step leaves a loadable store."""
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".entry.tmp")
        os.close(fd)
        try:
            ckpt.save(tmp, state, key, round_index, message_count)
            sha = _file_sha256(tmp)
            fname = f"ckpt_r{int(round_index):012d}_{sha[:12]}.npz"
            final = os.path.join(self.directory, fname)
            os.replace(tmp, final)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        with self._save_lock:
            entries = [e for e in self._read_manifest()
                       if e.get("file") != fname]
            new = {"file": fname, "round": int(round_index),
                   "message_count": int(message_count), "sha256": sha}
            entries.append(new)
            entries.sort(key=lambda e: (e["round"], e["file"]))
            keep = entries[-self.retain:]
            if new not in keep:
                # The fresh entry sorted below the retained window (a
                # stale higher-round trail shares the directory —
                # resume=False reuse; the runner clears such trails, this
                # is the store-level backstop): a save must never prune
                # ITS OWN checkpoint, so evict the oldest survivor
                # instead. `new` has the lowest round of `keep`, so
                # prepending preserves round order.
                keep = [new] + keep[1:] if self.retain > 1 else [new]
            pruned = [e for e in entries if e not in keep]
            self._write_manifest(keep)
        for e in pruned:
            try:
                os.unlink(os.path.join(self.directory, e["file"]))
            except OSError:
                pass  # already gone — retention is best-effort cleanup
        self._m_written.inc()
        return final

    def clear(self) -> None:
        """Delete every entry and the manifest — the fresh-trail reset.

        The runner calls this when a run starts from round 0 into a
        directory that still holds a previous trail (``resume=False``, or
        every prior entry proved unloadable): two interleaved trails in
        one manifest would make ``load_latest`` resume the WRONG run the
        moment the stale trail's rounds are higher."""
        with self._save_lock:
            for name in list(os.listdir(self.directory)):  # graftlint: ignore[lock-open-call] -- serializing store mutation against concurrent save() IS this lock's job; local fs ops, bounded
                if name == _MANIFEST or (name.startswith("ckpt_r")
                                         and name.endswith(".npz")):
                    try:
                        os.unlink(os.path.join(self.directory, name))  # graftlint: ignore[lock-open-call] -- same: the clear must be atomic w.r.t. save
                    except OSError:
                        pass  # already gone

    def _write_manifest(self, entries: List[Dict[str, Any]]) -> None:
        doc = {"version": 1,
               "latest": entries[-1]["file"] if entries else None,
               "entries": entries}
        atomic_write_json(os.path.join(self.directory, _MANIFEST), doc,
                          suffix=".manifest.tmp")

    # -------------------------------------------------------------- reading

    def _read_manifest(self) -> List[Dict[str, Any]]:
        """Manifest entries oldest-first; [] when absent/unreadable (the
        resume path then falls back to a directory scan)."""
        path = os.path.join(self.directory, _MANIFEST)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            entries = doc.get("entries", [])
            return [e for e in entries
                    if isinstance(e, dict) and "file" in e and "round" in e]
        except (OSError, ValueError):
            return []

    def _scan_entries(self) -> List[Dict[str, Any]]:
        """Directory-scan fallback when the manifest is gone: every
        ``ckpt_r*.npz`` present, oldest-first, hashes unvalidated at the
        manifest level (the in-file digest still guards each load)."""
        found = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if not (name.startswith("ckpt_r") and name.endswith(".npz")):
                continue
            try:
                rnd = int(name[len("ckpt_r"):].split("_")[0])
            except ValueError:
                continue
            found.append({"file": name, "round": rnd, "sha256": None})
        found.sort(key=lambda e: (e["round"], e["file"]))
        return found

    def entries(self) -> List[Dict[str, Any]]:
        """Manifest entries oldest-first (directory scan if no manifest)."""
        return self._read_manifest() or self._scan_entries()

    def latest_round(self) -> Optional[int]:
        ents = self.entries()
        return int(ents[-1]["round"]) if ents else None

    def load_latest(self, template: Any, *, grow: bool = False) -> Optional[
            Tuple[Any, Any, int, int, str]]:
        """Restore the newest loadable checkpoint, skipping damage.

        Walks entries newest-first; each candidate must (a) exist, (b)
        match the manifest's file hash when one is recorded, and (c) pass
        ``checkpoint.load``'s in-file digest and structure checks. Any
        failure skips to the next-older entry (counted into
        ``supervise_checkpoints_skipped_total{reason}``). ``grow=True``
        accepts repad-compatible entries written before a ``Graph.grow``
        capacity change (leaves zero-extended into the template's grown
        shapes via ``checkpoint.grow_state``); entries that cannot grow
        into the template still skip as ``template_mismatch``. A resume whose
        manifest is gone/unreadable but whose directory still holds
        entries falls back to the scan, counted once as
        ``{reason="manifest-missing"}``, and the entry it recovers is
        logged (warning + ``store_scan_recovery`` trace event) — damage
        survived should be visible, not silent. Returns
        ``(state, key, round_index, message_count, path)``, or ``None``
        when no entry is loadable (fresh start)."""
        ents = self._read_manifest()
        scan_fallback = False
        if not ents:
            ents = self._scan_entries()
            if ents:
                # A trail with no manifest is damage (the manifest is
                # rename-published after every entry), not a fresh dir —
                # count the fallback; an empty directory stays silent.
                scan_fallback = True
                self._m_skipped.labels("manifest-missing").inc()
        for entry in reversed(ents):
            path = os.path.join(self.directory, entry["file"])
            if not os.path.exists(path):
                self._m_skipped.labels("missing").inc()
                continue
            recorded = entry.get("sha256")
            if recorded is not None and _file_sha256(path) != recorded:
                self._m_skipped.labels("hash_mismatch").inc()
                continue
            try:
                state, key, rnd, msgs = ckpt.load(path, template, grow=grow)
            except ckpt.CheckpointCorrupt:
                self._m_skipped.labels("corrupt").inc()
                continue
            except ValueError:
                # Structure mismatch: the file is intact but from another
                # protocol/graph — a caller problem, but resume-over-
                # damage semantics say keep walking, counted distinctly.
                self._m_skipped.labels("template_mismatch").inc()
                continue
            if scan_fallback:
                warnings.warn(
                    f"checkpoint manifest missing/unreadable in "
                    f"{self.directory}; recovered entry "
                    f"{entry['file']!r} (round {rnd}) via directory "
                    f"scan", RuntimeWarning, stacklevel=2)
                if spans.current_tracer() is not None:
                    spans.emit("store_scan_recovery", round=int(rnd),
                               path=path)
            return state, key, rnd, msgs, path
        return None
