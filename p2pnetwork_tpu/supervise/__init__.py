"""Supervised execution plane: watchdogs, auto-checkpoint/resume,
crash-tolerant long runs.

- :class:`~p2pnetwork_tpu.supervise.watchdog.Watchdog` /
  :class:`~p2pnetwork_tpu.supervise.watchdog.StallTimeout` — deadline
  watchdog over heartbeats (stdlib-only, importable without jax);
- :class:`~p2pnetwork_tpu.supervise.store.CheckpointStore` — atomic,
  retention-bounded checkpoint directory with corrupt-skip resume;
- :class:`~p2pnetwork_tpu.supervise.runner.SupervisedRun` /
  :class:`~p2pnetwork_tpu.supervise.runner.Preempted` — chunked,
  auto-checkpointing, resumable driver for the sim engine's run-to-*
  loops;
- :class:`~p2pnetwork_tpu.supervise.heal.RetryPolicy` /
  :class:`~p2pnetwork_tpu.supervise.heal.Healer` /
  :class:`~p2pnetwork_tpu.supervise.heal.IntegrityViolation` —
  graftquake self-healing: end-of-chunk integrity checks plus
  policy-routed rollback-and-retry of detected bad state (stdlib-only
  at import; jax defers into the check functions).

The store and runner need jax (they sit on ``sim/checkpoint.py`` and the
engine); they load lazily so the sockets-only surface of this package —
the watchdog — imports clean without it, matching the repo's "sockets
backend is stdlib-only" rule.
"""

from p2pnetwork_tpu.supervise.watchdog import StallTimeout, Watchdog

__all__ = ["Watchdog", "StallTimeout", "CheckpointStore", "SupervisedRun",
           "Preempted", "RetryPolicy", "Healer", "IntegrityViolation"]

_LAZY = {
    "CheckpointStore": ("p2pnetwork_tpu.supervise.store", "CheckpointStore"),
    "SupervisedRun": ("p2pnetwork_tpu.supervise.runner", "SupervisedRun"),
    "Preempted": ("p2pnetwork_tpu.supervise.runner", "Preempted"),
    "RetryPolicy": ("p2pnetwork_tpu.supervise.heal", "RetryPolicy"),
    "Healer": ("p2pnetwork_tpu.supervise.heal", "Healer"),
    "IntegrityViolation": ("p2pnetwork_tpu.supervise.heal",
                           "IntegrityViolation"),
}


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
