"""graftquake device plane: seeded fault injection for the compiled engines.

The sockets backend has a chaos plane (chaos/plane.py) and the thread
plane has graftrace, but until now the DEVICE plane — the sharded ring
engine and the serving driver the production story rides on — had zero
fault coverage: a flipped halo word was silent corruption, a lost chip
an opaque XLA error. This module injects those failures on purpose,
deterministically, through the existing seams:

- **Halo-hop faults** (:class:`FaultSchedule` + :class:`FaultSpec`): a
  ``comm=`` value for parallel/sharded.py entry points that wraps either
  halo backend (``ppermute`` / ``pallas``) in a :class:`FaultyComm`. On
  ring step ``t`` of round ``r``, shard ``d``'s received block is
  corrupted (seeded sparse bit-flips), zeroed (hop lost), or delayed
  (rotation stalls — the shard keeps its own block) when the schedule
  says so. Every decision is ``fold_in(seed, round, step, shard)``
  pure-jax, so fault sites are byte-replayable and host-predictable
  (:meth:`FaultSchedule.sites_between` replays them without a mesh).
  Off by default and zero cost when absent: a plain backend string
  compiles exactly the code it always did.

- **Dispatch faults** (:class:`DispatchChaos`): chunk-boundary chip
  preemption (:class:`ChipLost`) and a wedged-dispatch mode
  (:class:`WedgedDispatch`) raised at the engine/serve chunk dispatch
  gate (``engine.run_batch_until_coverage``,
  ``engine.run_until_coverage_from``, ``engine.run_from``,
  ``sharded.run_batch_until_coverage``). Armings are one-shot, so a
  retry (supervise/heal.py) lands on a healthy dispatch — the
  fail-stop-then-recover shape of a real preemption.

Injections count into ``chaos_device_faults_total{kind}``; the halo
counts are a host replay of the schedule over the rounds a run actually
executed, so the counter reflects the schedule exactly. Recovery is the
other half of the story: supervise/heal.py detects (integrity checks)
and re-executes (rollback + retry policy) — see GETTING_STARTED.md
"Device-plane chaos & self-healing".

Top-level import is stdlib-only (jax is deferred into the fault math)
so the dispatch gate costs the engines one module attribute read plus a
None check when nothing is installed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.telemetry import spans

__all__ = [
    "FAULT_KINDS", "FaultSchedule", "FaultSpec", "FaultyComm",
    "ChipLost", "WedgedDispatch", "DispatchChaos", "UnreachableFaultSite",
    "install_dispatch_chaos", "dispatch_gate", "record_faults",
]

#: Halo-hop fault kinds, in device-code order (code = index + 1; 0 = none).
FAULT_KINDS = ("corrupt", "zero", "delay")
_KIND_CODE = {k: i + 1 for i, k in enumerate(FAULT_KINDS)}

#: FaultSpec wraps one of these concrete backends (sharded.COMM_BACKENDS;
#: literal here so this module stays importable without jax — the spec is
#: re-validated by _RingComm construction inside the trace either way).
_BACKENDS = ("ppermute", "pallas")


def _faults_counter(registry: Optional[telemetry.Registry] = None):
    reg = registry if registry is not None else telemetry.default_registry()
    return reg.counter(
        "chaos_device_faults_total",
        "Device-plane faults injected by graftquake, by kind (corrupt / "
        "zero / delay halo hops from a FaultSchedule; preempt / wedge "
        "dispatch faults from DispatchChaos).", ("kind",))


class UnreachableFaultSite(UserWarning):
    """An explicit ``FaultSchedule.sites`` entry can never fire on the
    ring it was handed to: its step or shard index is outside
    ``[0, axis_size)``. The classic way to hit this is live overlay
    growth — a schedule authored against the pre-grow shard count is
    replayed against the regrown ring and some sites fall off the end.
    A site that silently never fires would make a chaos run look
    healthier than it is, so the mismatch is loud (this warning plus a
    ``fault_sites_unreachable`` trace event), but not fatal: the
    in-range sites still inject exactly as scheduled."""


class ChipLost(RuntimeError):
    """An injected chunk-boundary chip preemption: the dispatch never ran
    (the gate raises before any buffer is touched), exactly the damage a
    real mid-job chip loss inflicts at a chunk boundary. Healable — the
    arming is one-shot, so a policy-driven retry lands clean."""

    def __init__(self, dispatch_index: int):
        self.dispatch_index = int(dispatch_index)
        super().__init__(
            f"injected chip preemption at dispatch {dispatch_index} "
            "(chaos/device.DispatchChaos)")


class WedgedDispatch(RuntimeError):
    """An injected wedged device dispatch: stands in for the
    watchdog-detected stall a hung tunnel produces (the real thing hangs
    holding the GIL — raising is the testable surrogate, the same shape
    supervise/watchdog.py turns a live stall into)."""

    def __init__(self, dispatch_index: int):
        self.dispatch_index = int(dispatch_index)
        super().__init__(
            f"injected wedged dispatch at index {dispatch_index} "
            "(chaos/device.DispatchChaos)")


# ------------------------------------------------------ halo-hop faults


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """A seeded, byte-replayable schedule of halo-hop faults.

    Every (round, step, shard) site draws one uniform from
    ``fold_in(fold_in(fold_in(key(seed), round), step), shard)`` and
    maps it through the ``corrupt``/``zero``/``delay`` probability
    thresholds — the same pure-jax draw inside the compiled loop and in
    the host replay (:meth:`sites_between`), so fault sites are
    identical wherever they are computed. ``round`` is the GLOBAL round
    (chunked drivers pass ``fault_round0`` so resumed/retried chunks
    key the same sites an unchunked run would). ``sites`` adds exact
    explicit placements ``(round, step, shard, kind)`` on top —
    deterministic test vectors; they ignore the round window.

    Kinds, applied to the block shard ``d`` RECEIVES at that hop:

    - ``corrupt``: seeded sparse bit-flips (``corrupt_density`` of the
      payload's elements XOR a random nonzero pattern; bools flip);
    - ``zero``: the whole hop zeroed (payload lost);
    - ``delay``: the rotation stalls — the shard keeps its own
      pre-shift block for this hop.
    """

    seed: int = 0
    corrupt: float = 0.0
    zero: float = 0.0
    delay: float = 0.0
    start_round: int = 0
    stop_round: int = 1 << 30
    corrupt_density: float = 1.0 / 64.0
    sites: Tuple[Tuple[int, int, int, str], ...] = ()

    def __post_init__(self):
        # Coerce list-form sites to tuples: the schedule must stay
        # hashable (FaultSpec keys the lru-cached compiled-loop
        # factories like a backend string does).
        object.__setattr__(self, "sites",
                           tuple(tuple(s) for s in self.sites))
        total = self.corrupt + self.zero + self.delay
        if min(self.corrupt, self.zero, self.delay) < 0 or total > 1.0:
            raise ValueError(
                "fault probabilities must be >= 0 and sum to <= 1, got "
                f"corrupt={self.corrupt} zero={self.zero} "
                f"delay={self.delay}")
        if not 0.0 < self.corrupt_density <= 1.0:
            raise ValueError("corrupt_density must be in (0, 1]")
        for site in self.sites:
            if len(site) != 4 or site[3] not in _KIND_CODE:
                raise ValueError(
                    f"schedule site must be (round, step, shard, kind) "
                    f"with kind in {FAULT_KINDS}, got {site!r}")

    @property
    def active(self) -> bool:
        """False for the empty schedule — FaultyComm then passes every
        hop through untouched (bit-identical to the bare backend)."""
        return bool(self.sites) or (self.corrupt + self.zero
                                    + self.delay) > 0.0

    # ------------------------------------------------------- device side

    def _site_key(self, rnd, step, shard):
        import jax

        k = jax.random.key(self.seed)
        k = jax.random.fold_in(k, rnd)
        k = jax.random.fold_in(k, step)
        return jax.random.fold_in(k, shard)

    def kind_at(self, rnd, step, shard):
        """Fault-kind code (i32: 0 none, 1 corrupt, 2 zero, 3 delay) at
        one site. Pure jax — traceable inside the ring pass and
        vmappable for the host replay."""
        import jax
        import jax.numpy as jnp

        kind = jnp.int32(0)
        p_c, p_z, p_d = self.corrupt, self.zero, self.delay
        if p_c + p_z + p_d > 0.0:
            u = jax.random.uniform(
                jax.random.fold_in(self._site_key(rnd, step, shard), 0))
            kind = jnp.where(
                u < p_c, 1,
                jnp.where(u < p_c + p_z, 2,
                          jnp.where(u < p_c + p_z + p_d, 3, 0)),
            ).astype(jnp.int32)
            in_window = (rnd >= self.start_round) & (rnd < self.stop_round)
            kind = jnp.where(in_window, kind, jnp.int32(0))
        for sr, st, sd, sk in self.sites:
            hit = (rnd == sr) & (step == st) & (shard == sd)
            kind = jnp.where(hit, jnp.int32(_KIND_CODE[sk]), kind)
        return kind

    def corrupt_payload(self, payload, rnd, step, shard):
        """The seeded bit-flipped form of one hop's payload (same shape
        and dtype; a ``corrupt_density`` fraction of elements XOR a
        random nonzero pattern — floats go through a bitcast, so NaN/Inf
        patterns are possible and the integrity audit's finiteness check
        is a real detector)."""
        import jax
        import jax.numpy as jnp

        k = jax.random.fold_in(self._site_key(rnd, step, shard), 1)
        k_mask, k_bits = jax.random.split(k)
        if payload.dtype == jnp.bool_:
            return payload ^ jax.random.bernoulli(
                k_mask, self.corrupt_density, payload.shape)
        itemsize = jnp.dtype(payload.dtype).itemsize
        uint = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}.get(itemsize)
        if uint is None:
            raise NotImplementedError(
                f"corrupt fault has no bit-flip form for {payload.dtype} "
                "(64-bit payloads need jax x64)")
        words = payload if payload.dtype == uint else \
            jax.lax.bitcast_convert_type(payload, uint)
        flip = jax.random.bernoulli(k_mask, self.corrupt_density,
                                    payload.shape)
        bits = jax.random.bits(k_bits, payload.shape, uint) | uint(1)
        words = jnp.where(flip, words ^ bits, words)
        return words if payload.dtype == uint else \
            jax.lax.bitcast_convert_type(words, payload.dtype)

    # --------------------------------------------------------- host side

    def sites_between(self, round0: int, round1: int, n_steps: int,
                      n_shards: int):
        """Host replay of the device draw: every fault site with
        ``round0 <= round < round1`` over ``n_steps`` hops per round and
        ``n_shards`` shards, as ``[(round, step, shard, kind), ...]``
        sorted by site. Byte-identical across calls and identical to
        what the compiled loop applied (same fold_in chain)."""
        if round1 <= round0 or n_steps <= 0 or n_shards <= 0 \
                or not self.active:
            return []
        import jax
        import numpy as np

        rr, tt, dd = np.meshgrid(
            np.arange(round0, round1), np.arange(n_steps),
            np.arange(n_shards), indexing="ij")
        kinds = np.asarray(jax.vmap(self.kind_at)(
            rr.ravel(), tt.ravel(), dd.ravel()))
        out = []
        for r, t, d, k in zip(rr.ravel().tolist(), tt.ravel().tolist(),
                              dd.ravel().tolist(), kinds.tolist()):
            if k:
                out.append((r, t, d, FAULT_KINDS[k - 1]))
        return out

    def counts_between(self, round0: int, round1: int, n_steps: int,
                       n_shards: int):
        """Fault counts by kind over the same window — what
        :func:`record_faults` feeds ``chaos_device_faults_total``."""
        counts = {k: 0 for k in FAULT_KINDS}
        for _, _, _, kind in self.sites_between(round0, round1, n_steps,
                                                n_shards):
            counts[kind] += 1
        return counts


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """A ``comm=`` value for the sharded entry points: run the ring on
    ``backend`` with ``schedule``'s faults injected at the halo hops.
    Hashable (it keys the same compiled-loop caches a backend string
    does). The fault-wired entries — ``flood_until_coverage`` and
    ``run_batch_until_coverage`` — feed the ring the global round via
    ``fault_round0``; other entries run with round context 0 (every
    round keys the same sites — fine for single-pass calls like
    ``propagate``, wrong for multi-round accounting, so wire before
    relying on counts there). ``backend`` must be concrete ("ppermute"
    or "pallas" — resolve "auto" with parallel/auto.resolve_comm
    first)."""

    schedule: FaultSchedule
    backend: str = "ppermute"

    def __post_init__(self):
        if self.backend not in _BACKENDS:
            raise ValueError(
                f"FaultSpec.backend must be one of {_BACKENDS} (resolve "
                f"'auto' before building the spec), got {self.backend!r}")

    def make(self, axis_name: str, axis_size: int) -> "FaultyComm":
        """The sharded._make_ring_comm seam: build this spec's comm
        object for one ring. Rebuilt on every recompile — in particular
        after a live ``Graph.grow`` changes the ring size — so this is
        where explicit schedule sites are checked against the ring they
        will actually run on: a site whose step or shard is outside
        ``[0, axis_size)`` can never fire (ring steps and shard indices
        both range over the axis size) and draws a structured
        :class:`UnreachableFaultSite` warning instead of vanishing."""
        import warnings

        stale = [s for s in self.schedule.sites
                 if not (0 <= s[1] < axis_size and 0 <= s[2] < axis_size)]
        if stale:
            warnings.warn(
                f"{len(stale)} explicit fault site(s) unreachable on "
                f"ring axis {axis_name!r} (size {axis_size}): "
                f"{stale[:8]!r}{' ...' if len(stale) > 8 else ''} — "
                "step/shard must lie in [0, axis_size); a schedule "
                "authored before overlay growth must be re-targeted",
                UnreachableFaultSite, stacklevel=2)
            if spans.current_tracer() is not None:
                spans.emit("fault_sites_unreachable", axis=axis_name,
                           axis_size=int(axis_size), n_stale=len(stale),
                           sites=[list(s) for s in stale[:16]])
        return FaultyComm(self, axis_name, axis_size)


class FaultyComm:
    """A ``_RingComm``-interface wrapper that injects the schedule's
    faults into the forward halo hops. The wrapped inner backend does
    the real transfer (and the payload-template validation); this layer
    only rewrites what the receiving shard sees, keyed on
    ``(round, step, shard)`` — round/step context arrives through
    :meth:`set_context` (the ring bodies call it; ``wants_step`` makes
    ``_ring_pass`` thread the step index through its scan), shard is
    ``lax.axis_index`` at apply time.

    ``shift_back`` (the remask Horner accumulation) stays clean — the
    schedule's sites name forward hops. ``fuses`` is False: the fused
    DMA-under-segment-sum kernel is bit-identical to shift+apply (the
    PR-11 pin), and the unfused form is where the hop payload is
    exposed for injection.
    """

    #: _ring_pass threads its scan's step index to set_context when set.
    wants_step = True
    fuses = False

    def __init__(self, spec: FaultSpec, axis_name: str, axis_size: int):
        from p2pnetwork_tpu.parallel.sharded import _RingComm

        self._inner = _RingComm(spec.backend, axis_name, axis_size)
        self.backend = spec.backend
        self.axis_name = axis_name
        self.axis_size = axis_size
        self.schedule = spec.schedule
        self._round = None
        self._step = None

    def set_context(self, round=None, step=None):
        """Record the device round/step the next hops belong to (trace
        time: the values are tracers closed over by the fault math)."""
        if round is not None:
            self._round = round
        if step is not None:
            self._step = step

    def shift(self, x):
        return self._apply(x, self._inner.shift(x))

    def shift_back(self, x):
        return self._inner.shift_back(x)

    def fused_segment_sum(self, rot, contrib, local_dst, block, exact):
        return None  # force the separate hop so faults can inject

    def _apply(self, prev, shifted):
        import jax
        import jax.numpy as jnp

        sched = self.schedule
        if not sched.active:
            return shifted
        rnd = self._round if self._round is not None else jnp.int32(0)
        step = self._step if self._step is not None else jnp.int32(0)
        shard = jax.lax.axis_index(self.axis_name)
        kind = sched.kind_at(rnd, step, shard)
        out = jnp.where(kind == 1,
                        sched.corrupt_payload(shifted, rnd, step, shard),
                        shifted)
        out = jnp.where(kind == 2, jnp.zeros_like(shifted), out)
        return jnp.where(kind == 3, prev, out)


def record_faults(schedule: FaultSchedule, *, rounds: int, n_steps: int,
                  n_shards: int, round0: int = 0,
                  registry: Optional[telemetry.Registry] = None):
    """Count the faults a finished run's executed window actually hit
    into ``chaos_device_faults_total{kind}`` (host replay — the compiled
    loop carries no counter, and the replay is exact by construction).
    Returns the per-kind counts. The sharded fault-wired entries call
    this after every faulted run."""
    counts = schedule.counts_between(round0, round0 + rounds, n_steps,
                                     n_shards)
    ctr = _faults_counter(registry)
    total = 0
    for kind in FAULT_KINDS:
        if counts[kind]:
            ctr.labels(kind).inc(counts[kind])
            total += counts[kind]
    if total and spans.current_tracer() is not None:
        spans.emit("device_faults", round0=round0, rounds=rounds, **counts)
        # graftsight correlation: each fault SITE as its own point event
        # (round/step/shard/kind), bounded so a dense schedule cannot
        # flood the span store — the aggregate event above always
        # carries the exact totals.
        for rnd, step, shard, kind in schedule.sites_between(
                round0, round0 + rounds, n_steps, n_shards)[:64]:
            spans.emit("device_fault", round=rnd, step=step,
                       shard=shard, kind=kind)
    return counts


# ------------------------------------------------------- dispatch faults


class DispatchChaos:
    """One-shot dispatch faults at the engine/serve chunk boundary.

    ``preempt_at`` / ``wedge_at`` name 0-based dispatch indices (the
    process-wide count of gated dispatches while installed). When the
    gate reaches an armed index it raises :class:`ChipLost` /
    :class:`WedgedDispatch` BEFORE the dispatch touches any state —
    chunk-boundary damage — and disarms that index, so a healing retry
    of the same chunk runs clean. Install with
    :func:`install_dispatch_chaos`; injections count into
    ``chaos_device_faults_total{kind="preempt"|"wedge"}``."""

    def __init__(self, *, preempt_at=(), wedge_at=(),
                 registry: Optional[telemetry.Registry] = None):
        self._lock = concurrency.lock()
        self._preempt = {int(i) for i in preempt_at}
        self._wedge = {int(i) for i in wedge_at}
        self._dispatches = 0
        self._ctr = _faults_counter(registry)

    @property
    def dispatches(self) -> int:
        with self._lock:
            return self._dispatches

    def on_dispatch(self, loop: str) -> None:
        kind = None
        with self._lock:
            n = self._dispatches
            self._dispatches += 1
            if n in self._preempt:
                self._preempt.discard(n)
                kind = "preempt"
            elif n in self._wedge:
                self._wedge.discard(n)
                kind = "wedge"
        if kind is None:
            return
        self._ctr.labels(kind).inc()
        if spans.current_tracer() is not None:
            spans.emit("dispatch_fault", kind=kind, loop=loop, index=n)
        if kind == "preempt":
            raise ChipLost(n)
        raise WedgedDispatch(n)


#: The installed dispatch-fault injector (None = off; the gate is one
#: attribute read + None check — the spans.install_tracer pattern).
_dispatch_chaos: Optional[DispatchChaos] = None


def install_dispatch_chaos(dc: Optional[DispatchChaos]):
    """Install (or clear, with None) the process-wide dispatch-fault
    injector; returns the previous one so tests can restore it."""
    global _dispatch_chaos
    prev = _dispatch_chaos
    _dispatch_chaos = dc
    return prev


def dispatch_gate(loop: str) -> None:
    """The engines' chunk-dispatch hook: raise the armed fault, if any.
    No-op (one None check) when nothing is installed."""
    dc = _dispatch_chaos
    if dc is not None:
        dc.on_dispatch(loop)
