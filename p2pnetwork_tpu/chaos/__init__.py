"""Chaos plane for the sockets backend: seeded, deterministic fault
injection mirroring the sim failures API (``sim/failures.py``) name-for-name
— ``kill_nodes`` / ``revive_nodes`` / ``cut_links`` / ``partition`` — plus
sockets-only faults (latency, throttle, frame drop/duplicate/corrupt,
slow-drain peer). See :mod:`p2pnetwork_tpu.chaos.plane` for the design and
GETTING_STARTED.md "Fault injection & chaos" for the sim↔sockets mapping.

Stdlib-only, like the rest of the sockets backend — no jax import.
"""

from p2pnetwork_tpu.chaos.plane import ChaosPlane
from p2pnetwork_tpu.chaos.streams import ChaosReader, ChaosWriter

__all__ = ["ChaosPlane", "ChaosReader", "ChaosWriter"]
