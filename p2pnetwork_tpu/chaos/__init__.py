"""Chaos plane: seeded, deterministic fault injection for BOTH backends.

- **Sockets** (:mod:`p2pnetwork_tpu.chaos.plane`): faults mirroring the
  sim failures API (``sim/failures.py``) name-for-name — ``kill_nodes``
  / ``revive_nodes`` / ``cut_links`` / ``partition`` — plus sockets-only
  faults (latency, throttle, frame drop/duplicate/corrupt, slow-drain
  peer). See GETTING_STARTED.md "Fault injection & chaos".
- **Device** (:mod:`p2pnetwork_tpu.chaos.device`, graftquake): seeded
  halo-hop faults for the sharded ring (:class:`FaultSchedule` /
  :class:`FaultSpec` as a ``comm=`` value) and one-shot chunk-dispatch
  faults (:class:`DispatchChaos` — chip preemption, wedged dispatch)
  for the engine/serve drivers. Recovery lives in
  :mod:`p2pnetwork_tpu.supervise.heal`; see GETTING_STARTED.md
  "Device-plane chaos & self-healing".

- **Churn** (:mod:`p2pnetwork_tpu.chaos.storm`, graftchurn): seeded
  join/leave/grow overlay storms (:class:`ChurnPattern` /
  :class:`ChurnSchedule`) driven through graftserve's live mutation
  plane — byte-replayable, interleavable with a traffic schedule. See
  GETTING_STARTED.md "Live overlay growth & churn storms".

- **Crash storms** (:mod:`p2pnetwork_tpu.chaos.crashstorm`, graftdur):
  seeded SIGKILL schedules (:class:`CrashSchedule` / :class:`KillPoint`)
  against graftserve's durability seams — mid-tick, mid-journal-append,
  mid-sidecar-publish, disk-full — driven as a subprocess soak
  (:func:`run_campaign`) asserting zero acknowledged-ticket loss. See
  GETTING_STARTED.md "Durability & failover".

Top-level import stays stdlib-only (device.py defers jax into the fault
math; storm.py and crashstorm.py — which speak the jax-backed serving
plane — load lazily on first attribute access), preserving the sockets
backend's no-jax rule.
"""

from p2pnetwork_tpu.chaos.device import (ChipLost, DispatchChaos,
                                          FaultSchedule, FaultSpec,
                                          FaultyComm, UnreachableFaultSite,
                                          WedgedDispatch,
                                          install_dispatch_chaos)
from p2pnetwork_tpu.chaos.plane import ChaosPlane
from p2pnetwork_tpu.chaos.streams import ChaosReader, ChaosWriter

__all__ = [
    "ChaosPlane", "ChaosReader", "ChaosWriter",
    "FaultSchedule", "FaultSpec", "FaultyComm", "DispatchChaos",
    "ChipLost", "WedgedDispatch", "UnreachableFaultSite",
    "install_dispatch_chaos",
    "ChurnPattern", "ChurnSchedule",
    "CrashSchedule", "KillPoint", "CampaignError", "KILL_KINDS",
]

_STORM_NAMES = ("ChurnPattern", "ChurnSchedule")

_CRASHSTORM_NAMES = ("CrashSchedule", "KillPoint", "CampaignError",
                     "KILL_KINDS")


def __getattr__(name):
    if name in _STORM_NAMES:
        from p2pnetwork_tpu.chaos import storm
        return getattr(storm, name)
    if name in _CRASHSTORM_NAMES:
        from p2pnetwork_tpu.chaos import crashstorm
        return getattr(crashstorm, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
