"""graftchurn storms: seeded join/leave/grow overlay churn as a workload.

serve/traffic.py made "millions of users" a reproducible workload; this
module does the same for "the overlay is being rebuilt under you". One
PRNG seed materializes a complete churn schedule — capacity-only grows
(headroom pre-provisioning), join batches (grow + an undirected wiring
delta attaching each joiner to seeded live peers), and leaves (a delta
removing every storm-added edge still incident to a departing joiner) —
and drives a :class:`~p2pnetwork_tpu.serve.service.SimService` with it,
one schedule tick per driver tick, optionally interleaved with a traffic
schedule so tickets flow WHILE the overlay churns.

Everything is a pure function of ``(pattern, n_nodes, seed)``: the
schedule serializes to bytes (:meth:`ChurnSchedule.to_bytes`) and two
generations are byte-identical; driving two fresh services with the same
storm (and the same traffic) produces identical per-ticket records —
which is what makes the soak's "faulted-and-healed run == unfaulted run"
comparison meaningful. tests/test_graftchurn.py pins both.

Leave semantics are deliberately storm-scoped: a departing node sheds
exactly the edges the storm wired for it (the generator tracks them, so
removals always name live edges — ``apply_delta`` refuses phantom
removals by design). Base-graph nodes never leave; the storm does not
know their edges and guessing would break the pure-function contract.

Like the rest of the chaos package's top level, importing this module
pulls no jax — it speaks numpy and the service's public mutation API.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from p2pnetwork_tpu.serve.service import (TERMINAL_STATES,
                                           Rejected, SimService)
from p2pnetwork_tpu.serve.traffic import TrafficSchedule
from p2pnetwork_tpu.serve.traffic import _consume_replay
from p2pnetwork_tpu.sim.graph import GraphDelta

__all__ = ["ChurnPattern", "ChurnSchedule", "generate", "drive"]

#: Event kinds in schedule-array code order.
EVENT_KINDS = ("grow", "join", "leave")
_KIND_CODE = {k: i for i, k in enumerate(EVENT_KINDS)}


def _replay_mutation(service: SimService, t: int, want_kind: str) -> bool:
    """Positional churn replay (graftdur resume): when the service's
    journal-replay suffix heads with exactly the mutation this storm
    event would queue (same kind, due at or before tick ``t``), replay
    that record instead of re-queueing a duplicate. The storm schedule
    is seed-deterministic, so records line up event-for-event with the
    re-driven schedule."""
    head = service.replay_peek()
    if (head is not None and int(head.get("tick", 0)) <= t
            and head.get("kind") == want_kind):
        service.replay_next()
        return True
    return False


@dataclasses.dataclass(frozen=True)
class ChurnPattern:
    """Shape of the churn storm (all knobs deterministic given the seed;
    probabilities are per driver TICK — the service's mutation plane
    drains its queue once per tick, so a schedule replays identically at
    any wall speed).

    ``join_prob`` ticks land a join event of ``join_batch`` new nodes,
    each wired undirected to ``fanout`` distinct live peers;
    ``leave_prob`` ticks depart one uniformly-chosen still-live joiner
    (no-op while none have joined); ``grow_prob`` ticks pre-provision
    ``grow_batch`` capacity-only nodes (no wiring — the repad headroom
    pattern)."""

    ticks: int = 32
    join_prob: float = 0.25
    join_batch: int = 4
    fanout: int = 2
    leave_prob: float = 0.1
    grow_prob: float = 0.0
    grow_batch: int = 8

    def __post_init__(self):
        if self.ticks < 1:
            raise ValueError("ticks must be >= 1")
        for name in ("join_prob", "leave_prob", "grow_prob"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {p}")
        if self.join_batch < 1:
            raise ValueError("join_batch must be >= 1")
        if self.fanout < 1:
            raise ValueError("fanout must be >= 1")
        if self.grow_batch < 1:
            raise ValueError("grow_batch must be >= 1")


@dataclasses.dataclass(frozen=True)
class ChurnSchedule:
    """A fully materialized churn schedule: an event table plus the edge
    rows each event adds or removes, all parallel numpy arrays (the
    traffic-schedule idiom), plus the provenance that generated them.

    ``ev_amount`` is the node count for grow/join events and the
    departing node id for leaves. ``edge_event`` maps each undirected
    edge pair ``(edge_a, edge_b)`` to its event row — adds for joins,
    removals for leaves."""

    pattern: ChurnPattern
    seed: int
    n_nodes: int             # base overlay size the storm was drawn for
    ev_tick: np.ndarray      # i32[events], nondecreasing
    ev_kind: np.ndarray      # i32[events] — index into EVENT_KINDS
    ev_amount: np.ndarray    # i32[events]
    edge_event: np.ndarray   # i32[pairs] — owning event row
    edge_a: np.ndarray       # i32[pairs]
    edge_b: np.ndarray       # i32[pairs]

    def __len__(self) -> int:
        return int(self.ev_tick.size)

    @property
    def ticks(self) -> int:
        return self.pattern.ticks

    @property
    def n_final(self) -> int:
        """Live node count after the whole storm lands on the base."""
        kinds = self.ev_kind
        added = self.ev_amount[(kinds == _KIND_CODE["grow"])
                               | (kinds == _KIND_CODE["join"])]
        return int(self.n_nodes + added.sum())

    def events_at(self, t: int) -> List[Tuple[str, int,
                                              Optional[GraphDelta]]]:
        """``[(kind, amount, delta), ...]`` landing at schedule tick
        ``t``, in draw order. ``delta`` is the join wiring / leave
        shedding batch (both directions — :meth:`GraphDelta.undirected`)
        and ``None`` for capacity-only grows."""
        out: List[Tuple[str, int, Optional[GraphDelta]]] = []
        for ev in np.flatnonzero(self.ev_tick == int(t)).tolist():
            kind = EVENT_KINDS[int(self.ev_kind[ev])]
            amount = int(self.ev_amount[ev])
            delta: Optional[GraphDelta] = None
            if kind != "grow":
                rows = np.flatnonzero(self.edge_event == ev)
                a, b = self.edge_a[rows], self.edge_b[rows]
                if kind == "join":
                    delta = GraphDelta.undirected(add_senders=a,
                                                  add_receivers=b)
                else:
                    delta = GraphDelta.undirected(remove_senders=a,
                                                  remove_receivers=b)
            out.append((kind, amount, delta))
        return out

    def to_bytes(self) -> bytes:
        """Canonical serialization — the byte-identity witness the
        determinism tests compare (header JSON + the six arrays)."""
        header = json.dumps({
            "pattern": dataclasses.asdict(self.pattern),
            "seed": self.seed, "n_nodes": self.n_nodes,
            "events": len(self), "pairs": int(self.edge_event.size),
        }, sort_keys=True).encode("utf-8")
        return b"\n".join([header, self.ev_tick.tobytes(),
                           self.ev_kind.tobytes(), self.ev_amount.tobytes(),
                           self.edge_event.tobytes(), self.edge_a.tobytes(),
                           self.edge_b.tobytes()])


def generate(pattern: ChurnPattern, n_nodes: int,
             seed: int = 0) -> ChurnSchedule:
    """Materialize the churn schedule off ONE ``default_rng(seed)``
    stream (draw order is fixed: per tick — grow coin, join coin, per
    joining node its fanout peer draws, leave coin + departing-node
    choice), so a storm is byte-replayable.

    The generator simulates the overlay's bookkeeping as it goes: join
    wiring targets are drawn from the CURRENT live set (base nodes plus
    joiners that have not left), and a leave removes exactly the
    still-live storm edges incident to the departer — so every delta the
    schedule emits is valid against the graph state the drive will have
    at that tick."""
    if n_nodes < 1:
        raise ValueError("n_nodes must be >= 1")
    rng = np.random.default_rng(int(seed))
    cur_n = int(n_nodes)
    live_joined: List[int] = []
    # Storm-added undirected pairs still live, keyed (lo, hi) -> True.
    live_edges: Dict[Tuple[int, int], bool] = {}
    ev_tick: List[int] = []
    ev_kind: List[int] = []
    ev_amount: List[int] = []
    edge_event: List[int] = []
    edge_a: List[int] = []
    edge_b: List[int] = []

    def _emit(t: int, kind: str, amount: int,
              pairs: List[Tuple[int, int]]) -> None:
        ev = len(ev_tick)
        ev_tick.append(t)
        ev_kind.append(_KIND_CODE[kind])
        ev_amount.append(amount)
        for a, b in pairs:
            edge_event.append(ev)
            edge_a.append(a)
            edge_b.append(b)

    for t in range(pattern.ticks):
        if pattern.grow_prob > 0 and rng.random() < pattern.grow_prob:
            _emit(t, "grow", pattern.grow_batch, [])
            cur_n += pattern.grow_batch
        if pattern.join_prob > 0 and rng.random() < pattern.join_prob:
            new = list(range(cur_n, cur_n + pattern.join_batch))
            live = np.concatenate([
                np.arange(n_nodes, dtype=np.int64),
                np.asarray(sorted(live_joined), dtype=np.int64)])
            pairs: List[Tuple[int, int]] = []
            for node in new:
                k = min(pattern.fanout, live.size)
                for peer in rng.choice(live, size=k,
                                       replace=False).tolist():
                    pair = (min(node, int(peer)), max(node, int(peer)))
                    if pair not in live_edges:
                        live_edges[pair] = True
                        pairs.append(pair)
            _emit(t, "join", pattern.join_batch, pairs)
            cur_n += pattern.join_batch
            live_joined.extend(new)
        if pattern.leave_prob > 0 and live_joined \
                and rng.random() < pattern.leave_prob:
            node = int(live_joined.pop(
                int(rng.integers(0, len(live_joined)))))
            shed = [p for p in live_edges if node in p]
            for p in shed:
                del live_edges[p]
            _emit(t, "leave", node, sorted(shed))
    return ChurnSchedule(
        pattern=pattern, seed=int(seed), n_nodes=int(n_nodes),
        ev_tick=np.asarray(ev_tick, dtype=np.int32),
        ev_kind=np.asarray(ev_kind, dtype=np.int32),
        ev_amount=np.asarray(ev_amount, dtype=np.int32),
        edge_event=np.asarray(edge_event, dtype=np.int32),
        edge_a=np.asarray(edge_a, dtype=np.int32),
        edge_b=np.asarray(edge_b, dtype=np.int32))


def drive(service: SimService, storm: ChurnSchedule, *,
          traffic: Optional[TrafficSchedule] = None,
          from_tick: Optional[int] = None, drain: bool = True,
          max_drain_ticks: int = 1024) -> Dict[str, object]:
    """Drive the service through the storm, one schedule tick per driver
    tick, synchronously (the deterministic mode — the service's
    background thread must NOT be running). Each tick queues that tick's
    churn events (``service.grow`` / ``service.apply_delta``; the
    mutation plane applies them atomically at the next tick's ``mutate``
    phase), submits the tick's traffic arrivals when a ``traffic``
    schedule rides along, then ticks.

    ``from_tick`` aligns a resumed service with the schedules (default
    ``service.tick_index`` — the traffic-drive resume contract); churn
    events before ``from_tick`` are assumed already in the resumed
    graph. Returns the traffic-drive result dict plus
    ``{"events": {kind: count}, "graph_nodes", "graph_capacity"}`` —
    every field deterministic for a given (storm, traffic, service
    config)."""
    if service.driver_running:
        raise RuntimeError(
            "drive() needs exclusive control of the driver: the "
            "service's background thread is running (construct without "
            "start(), or close() it first) — concurrent ticks would "
            "race the driver-confined batch state")
    if traffic is not None and traffic.ticks > storm.ticks:
        raise ValueError(
            f"traffic schedule runs {traffic.ticks} ticks but the storm "
            f"only {storm.ticks} — arrivals past the storm would be "
            "dropped silently; generate matching lengths")
    start = service.tick_index if from_tick is None else int(from_tick)
    pending: set = set()
    tickets: Dict[str, Optional[dict]] = {}
    shed: List[dict] = []
    events = {k: 0 for k in EVENT_KINDS}
    submitted = 0
    peak = 0
    rounds = 0

    def _tick() -> None:
        nonlocal peak, rounds
        info = service.tick()
        peak = max(peak, info["running"])
        rounds += info["executed_rounds"]
        for tid in sorted(pending):
            rec = service.poll(tid)
            if rec is not None and rec["status"] in TERMINAL_STATES:
                tickets[tid] = rec
                pending.discard(tid)

    replayed = 0
    for t in range(start, storm.ticks):
        for kind, amount, delta in storm.events_at(t):
            events[kind] += 1
            if kind in ("grow", "join"):
                if not _replay_mutation(service, t, "grow"):
                    service.grow(amount)
            if delta is not None:
                if not _replay_mutation(service, t, "delta"):
                    service.apply_delta(delta)
        if traffic is not None:
            for source, tenant in traffic.arrivals_at(t):
                rec = _consume_replay(service, t)
                if rec is not None:
                    replayed += 1
                    if rec["kind"] == "submit":
                        submitted += 1
                        pending.add(str(rec["ticket"]))
                    else:
                        shed.append({"tick": t, "source": int(source),
                                     "tenant": tenant,
                                     "reason": str(rec.get("reason",
                                                           "replayed"))})
                    continue
                try:
                    tid = service.submit(
                        source,
                        target_coverage=traffic.pattern.coverage_target,
                        tenant=tenant)
                    submitted += 1
                    pending.add(tid)
                except Rejected as e:
                    shed.append({"tick": t, "source": int(source),
                                 "tenant": tenant, "reason": e.reason})
        _tick()
    drained = 0
    while drain and service.busy() and drained < max_drain_ticks:
        _tick()
        drained += 1
    for tid in sorted(pending):
        tickets[tid] = service.poll(tid)
    completed = sum(1 for rec in tickets.values()
                    if rec is not None and rec["status"] == "done")
    return {"tickets": tickets, "shed": shed, "submitted": submitted,
            "completed": completed, "replayed": replayed,
            "drain_ticks": drained,
            "peak_concurrent_lanes": peak, "executed_rounds": rounds,
            "events": events,
            "graph_nodes": int(service.graph.n_nodes),
            "graph_capacity": int(service.graph.n_nodes_padded)}
