"""Fault-injecting proxies for one ``NodeConnection`` stream pair.

The chaos plane never touches protocol code: ``ChaosPlane.attach`` wraps the
``(StreamReader, StreamWriter)`` pair at the ``create_new_connection``
factory seam [ref: p2pnetwork/node.py:196-201], so every byte a connection
reads or writes flows through these two proxies. Anything not explicitly
intercepted delegates to the wrapped stream (``__getattr__``), which keeps
``NodeConnection``'s transport bookkeeping (``is_closing``, write-buffer
size, ``transport.abort``) working unchanged.

Fault placement is deliberately asymmetric:

- **frame faults** (drop / duplicate / corrupt) live on the WRITE side,
  because ``NodeConnection._write`` issues exactly one ``write()`` per
  frame — so the faults are frame-aligned and their schedule is a pure
  function of ``(seed, src, dst, frame index)``;
- **time faults** (added latency, bandwidth throttle, slow-drain stall)
  live on the READ side, where the coroutine can ``await asyncio.sleep``
  without reordering writes;
- **severed links** (killed endpoint, cut link, partition) blackhole
  writes and turn the next read into EOF, which drives the connection
  through the normal death path (``node_disconnected`` fires, reconnect
  and quarantine logic take over) — chaos exercises the same recovery
  machinery a real failure would.
"""

from __future__ import annotations

import asyncio


class ChaosWriter:
    """StreamWriter proxy applying seeded frame faults on the send side.

    Each delivered frame consumes exactly four draws from the per-stream
    RNG (drop, duplicate, corrupt, corrupt-position), whether or not any
    frame fault is armed — so the fault schedule for frame ``i`` depends
    only on ``(seed, src, dst, i)``, never on which faults were active
    earlier. Blackholed frames (severed link) consume no draws: they are
    timing-dependent and must not shift the schedule of the frames that
    do get through.
    """

    def __init__(self, plane, node_id: str, peer_id: str, writer,
                 framing: str = "eot"):
        self._plane = plane
        self._node_id = node_id
        self._peer_id = peer_id
        self._writer = writer
        self._rng = plane._stream_rng(node_id, peer_id, "send")
        self._frame_idx = 0
        # Corruptable byte range depends on the frame layout (wire.py):
        # "eot" frames are payload + trailing delimiter (spare the last
        # byte); "length" frames are 4-byte length prefix + compression
        # flag + payload (spare the first five — corrupting the prefix
        # would desync or tear down the stream instead of damaging one
        # payload, and the flag byte never reaches the application).
        self._framing = framing
        self._corrupt_lo = 5 if framing == "length" else 0
        self._corrupt_hi_off = 0 if framing == "length" else 1

    def write(self, data: bytes) -> None:
        plane = self._plane
        if not plane.link_ok(self._node_id, self._peer_id):
            # Severed link: blackhole silently. The read side reports the
            # EOF; counting these would make counters timing-dependent.
            return
        idx = self._frame_idx
        self._frame_idx += 1
        r_drop, r_dup, r_corrupt, r_pos = (self._rng.random(),
                                           self._rng.random(),
                                           self._rng.random(),
                                           self._rng.random())
        drop_p, dup_p, corrupt_p = plane.frame_fault_probs()
        if r_drop < drop_p:
            # Drop decides first: a dropped frame must not also count a
            # corruption that never reached the wire (per-frame kinds
            # count APPLIED faults). The draws above happen regardless,
            # so the seeded schedule is unaffected by fault ordering.
            plane._fault_applied("drop", self._node_id, self._peer_id, idx)
            return
        span = len(data) - self._corrupt_hi_off - self._corrupt_lo
        if r_corrupt < corrupt_p and span > 0:
            # Flip one PAYLOAD byte (framing metadata is spared, see
            # __init__) so the corruption surfaces as a decode error /
            # wrong payload on the peer (counted there as rerr), not as
            # a desynced or wedged stream.
            pos = self._corrupt_lo + int(r_pos * span)
            flipped = data[pos] ^ 0x5A
            if self._framing == "eot" and flipped == 0x04:
                # 0x5E would flip INTO the EOT delimiter and split the
                # frame in two; a fallback mask keeps the damage inside
                # one payload (0x5E ^ 0x25 = 0x7B, never 0x04).
                flipped = data[pos] ^ 0x25
            data = data[:pos] + bytes([flipped]) + data[pos + 1:]
            plane._fault_applied("corrupt", self._node_id, self._peer_id, idx)
        self._writer.write(data)
        if r_dup < dup_p:
            plane._fault_applied("duplicate", self._node_id, self._peer_id, idx)
            self._writer.write(data)

    def __getattr__(self, name):
        return getattr(self._writer, name)


class ChaosReader:
    """StreamReader proxy applying time faults and severed-link EOF."""

    def __init__(self, plane, node_id: str, peer_id: str, reader):
        self._plane = plane
        self._node_id = node_id
        self._peer_id = peer_id
        self._reader = reader
        self._rng = plane._stream_rng(node_id, peer_id, "recv")

    async def read(self, n: int = -1) -> bytes:
        plane = self._plane
        if not plane.link_ok(self._node_id, self._peer_id):
            return b""  # severed: the connection sees a clean EOF
        stall = plane.slow_drain_stall(self._node_id)
        if stall > 0:
            # Slow-drain peer: this node stops draining its sockets, so
            # the SENDER's write buffer grows until its max_send_buffer
            # backpressure bound trips — the fault is observed remotely.
            await asyncio.sleep(stall)
        chunk = await self._reader.read(n)
        if not chunk:
            return chunk
        delay = plane.recv_delay(len(chunk), self._rng)
        if delay > 0:
            await asyncio.sleep(delay)
        if not plane.link_ok(self._node_id, self._peer_id):
            return b""  # link severed while the chunk was in flight
        return chunk

    def __getattr__(self, name):
        return getattr(self._reader, name)
