"""Deterministic, seeded fault injection for the sockets backend.

The sim backend treats failure as a first-class input (`sim/failures.py`:
kill nodes / cut links by flipping device-side masks); this module is the
sockets-side counterpart, mirroring that API name-for-name so a failure
scenario written against one backend reads the same against the other:

==========================  ===========================================
sim (``sim.failures``)      sockets (``ChaosPlane``)
==========================  ===========================================
``kill_nodes(g, ids)``      ``plane.kill_nodes(ids)``
``revive_nodes(g, ids, o)`` ``plane.revive_nodes(ids)``
``cut_links(g, edge_ids)``  ``plane.cut_links(pairs)``
``partition(g, groups)``    ``plane.partition(groups)``
``preempt(run, at_round)``  ``plane.preempt(ids)`` / ``revive_preempted()``
==========================  ===========================================

(The sim's ``preempt`` kills the *run harness* at a round boundary — the
supervised-run lifecycle, ``supervise/runner.py``; the sockets mirror
preempts *peers*: fail-stop now, revive en bloc later — both count under
the shared ``preempt`` fault kind.)

plus sockets-only faults no mask can express: added latency, bandwidth
throttle, frame drop / duplicate / corrupt, and a slow-drain peer (stops
reading so the sender's backpressure bound trips).

Mechanism: :meth:`ChaosPlane.attach` wraps a node's
``create_new_connection`` factory so every accepted or dialed connection
gets its ``(StreamReader, StreamWriter)`` pair wrapped in
:class:`~p2pnetwork_tpu.chaos.streams.ChaosReader` /
:class:`~p2pnetwork_tpu.chaos.streams.ChaosWriter`. No protocol code
changes to be chaos-able, and any ``Node`` subclass (Phi, CRDT, secure…)
is injectable because the seam is the factory the subclass already
honors.

Known seam boundary: the plaintext id handshake runs on the RAW streams
before the factory is called, so a reconnect attempt toward a killed or
partitioned peer still completes TCP + handshake before the wrapped
connection dies on its first read (the factory closes the transport
immediately, so not one application byte crosses). The observable cost is
a transient connected/disconnected event pair per attempt — the
firewall-RST flavor of partition rather than the pulled-cable one — and
give-up policies keyed on ``trials`` can, rarely, see a tick land inside
that sub-millisecond window and reset the count.

Determinism: every per-frame fault decision is drawn from a per-stream
``random.Random`` seeded by ``sha256(seed | src | dst | direction)`` —
the schedule for frame ``i`` of a stream is a pure function of
``(seed, src, dst, i)``, independent of event-loop interleaving across
nodes. Same seed ⇒ byte-identical schedule; different seed ⇒ a different
one. (Give nodes explicit stable ids for cross-run reproducibility —
auto-generated ids are random per process.)

Telemetry: every injected fault increments
``chaos_injected_failures_total{kind}`` in the PR-1 registry — the same
``*_injected_failures_total`` naming the sim uses
(``sim_injected_failures_total{kind}``) — so one snapshot reports
"N faults injected, overlay recovered in T". Deterministic control ops
(``node``/``node_revive``/``link``/``link_heal``) count entities like
the sim's deterministic kinds; ``partition``/``partition_heal`` and the
armed time faults (``latency``/``throttle``/``slow_drain``) count calls;
the per-frame kinds (``drop``/``duplicate``/``corrupt``) count applied
frames. Structural state is mirrored in the
``chaos_active_faults{kind}`` gauge (``dead_nodes``, ``cut_links``,
``partition_groups``, ``slow_drain_nodes``).
"""

from __future__ import annotations

import collections
import hashlib
import random
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from p2pnetwork_tpu import concurrency, telemetry
from p2pnetwork_tpu.chaos.streams import ChaosReader, ChaosWriter

__all__ = ["ChaosPlane"]


class ChaosPlane:
    """One seeded fault-injection controller shared by a whole overlay.

    Attach every node under test, then drive faults from the test/driver
    thread; all methods are thread-safe. Severing ops (kill / cut /
    partition) close matching live connections immediately (via the
    thread-safe ``NodeConnection.stop``) and blackhole + EOF any future
    ones, so recovery machinery (reconnect backoff, phi quarantine) is
    exercised exactly as by a real fault.
    """

    def __init__(self, seed: int = 0,
                 registry: Optional[telemetry.Registry] = None):
        self.seed = int(seed)
        self._lock = concurrency.rlock()
        self._nodes: Dict[str, object] = {}
        self._orig_factory: Dict[str, object] = {}
        self._dead: set = set()
        self._preempted: set = set()    # subset of _dead, revivable en bloc
        self._cut: set = set()          # frozenset({a, b}) pairs
        self._groups: Dict[str, int] = {}
        self._latency = 0.0
        self._jitter = 0.0
        self._rate: Optional[float] = None  # bytes/sec
        self._drop_p = 0.0
        self._dup_p = 0.0
        self._corrupt_p = 0.0
        self._slow: Dict[str, float] = {}
        # Bounded: per-frame faults append one entry each, and a multi-hour
        # soak under armed frame faults must not grow memory without limit.
        # 64k entries comfortably covers determinism audits of test runs.
        self._log: collections.deque = collections.deque(maxlen=65536)
        reg = registry if registry is not None else telemetry.default_registry()
        self._m_injected = reg.counter(
            "chaos_injected_failures_total",
            "Failures injected into the sockets overlay, by kind (entity "
            "counts for node/link ops, applied-frame counts for "
            "drop/duplicate/corrupt, call counts otherwise).",
            ("kind",))
        self._m_active = reg.gauge(
            "chaos_active_faults",
            "Currently armed structural faults (dead nodes, cut links, "
            "partition groups, slow-drain peers).",
            ("kind",))

    # ------------------------------------------------------------- attach

    def attach(self, *nodes):
        """Wrap each node's ``create_new_connection`` so every present and
        future connection runs through the chaos stream proxies. Returns
        the nodes for chaining. Attach BEFORE connecting — existing
        connections are not rewrapped."""
        for node in nodes:
            with self._lock:
                if node.id in self._nodes:
                    continue
                self._nodes[node.id] = node
                orig = node.create_new_connection
                self._orig_factory[node.id] = orig
            def factory(connection, id, host, port, _plane=self, _orig=orig,
                        _nid=node.id, _node=node):
                reader, writer = connection
                if not _plane.link_ok(_nid, str(id)):
                    # The id handshake ran on the raw streams (node code,
                    # before this seam), so a severed peer still completes
                    # it; close the transport NOW so the connection is
                    # born dead — its first read EOFs instantly and the
                    # normal disconnect path reclaims it. The transient
                    # connected/disconnected event pair is the documented
                    # cost of the factory-seam design.
                    try:
                        writer.close()
                    except Exception:
                        pass
                return _orig(
                    (ChaosReader(_plane, _nid, str(id), reader),
                     ChaosWriter(_plane, _nid, str(id), writer,
                                 framing=_node.config.framing)),
                    id, host, port)

            node.create_new_connection = factory
        return nodes[0] if len(nodes) == 1 else nodes

    def detach(self, *nodes) -> None:
        """Restore the original factory; live wrapped connections keep
        their proxies until they close."""
        for node in nodes:
            with self._lock:
                orig = self._orig_factory.pop(node.id, None)
                self._nodes.pop(node.id, None)
            if orig is not None:
                node.create_new_connection = orig

    # -------------------------------------------------- sim-parity faults

    def kill_nodes(self, node_ids: Iterable) -> None:
        """Fail-stop the given node ids: every connection from or to them
        dies, future ones EOF immediately. The processes keep running (a
        kill is a network-visible fault, not SIGKILL) — ``revive_nodes``
        heals."""
        ids = [str(i) for i in node_ids]
        with self._lock:
            self._dead.update(ids)
            for i in ids:
                self._log.append(("node", i, None, None))
        self._count("node", len(ids))
        self._sever(lambda a, b: a in ids or b in ids)
        self._update_gauges()

    def revive_nodes(self, node_ids: Iterable) -> None:
        """Un-kill node ids; reconnect machinery re-establishes links."""
        ids = [str(i) for i in node_ids]
        with self._lock:
            self._dead.difference_update(ids)
            self._preempted.difference_update(ids)
            for i in ids:
                self._log.append(("node_revive", i, None, None))
        self._count("node_revive", len(ids))
        self._update_gauges()

    def preempt(self, node_ids: Iterable) -> None:
        """Preempt node ids: fail-stop now (identical network effect to
        :meth:`kill_nodes`), revive later en bloc via
        :meth:`revive_preempted` — the sockets mirror of the sim side's
        ``failures.preempt`` kill-then-revive lifecycle, and the
        machine-reclaimed flavor of failure (a preempted VM comes back;
        a killed one is a decision). Counted under its own ``preempt``
        kind so a scenario's transient capacity loss reads apart from its
        permanent one."""
        ids = [str(i) for i in node_ids]
        with self._lock:
            self._dead.update(ids)
            self._preempted.update(ids)
            for i in ids:
                self._log.append(("preempt", i, None, None))
        self._count("preempt", len(ids))
        self._sever(lambda a, b: a in ids or b in ids)
        self._update_gauges()

    def revive_preempted(self) -> List[str]:
        """Revive every currently-preempted node (deterministic inverse of
        :meth:`preempt`); returns the revived ids. Reconnect machinery
        re-establishes their links, as after any revive."""
        with self._lock:
            ids = sorted(self._preempted)
            self._preempted.clear()
            self._dead.difference_update(ids)
            for i in ids:
                self._log.append(("preempt_revive", i, None, None))
        self._count("preempt_revive", len(ids))
        self._update_gauges()
        return ids

    def cut_links(self, pairs: Iterable[Tuple]) -> None:
        """Cut the given (a, b) node-id links, both directions."""
        cut = [frozenset((str(a), str(b))) for a, b in pairs]
        with self._lock:
            self._cut.update(cut)
            for pair in cut:
                a, b = sorted(pair)
                self._log.append(("link", a, b, None))
        self._count("link", len(cut))
        self._sever(lambda a, b: frozenset((a, b)) in cut)
        self._update_gauges()

    def heal_links(self, pairs: Iterable[Tuple]) -> None:
        """Restore previously cut links."""
        healed = [frozenset((str(a), str(b))) for a, b in pairs]
        with self._lock:
            self._cut.difference_update(healed)
            for pair in healed:
                a, b = sorted(pair)
                self._log.append(("link_heal", a, b, None))
        self._count("link_heal", len(healed))
        self._update_gauges()

    def partition(self, groups: Sequence[Iterable]) -> None:
        """Split the overlay: nodes in different groups cannot exchange a
        byte; nodes in the same group (or in no group) are unaffected.
        Replaces any previous partition. ``heal_partition`` reunites."""
        mapping = {}
        for gi, group in enumerate(groups):
            for node_id in group:
                mapping[str(node_id)] = gi
        with self._lock:
            self._groups = mapping
            self._log.append(
                ("partition", tuple(sorted(mapping)), len(groups), None))
        self._count("partition", 1)
        self._sever(lambda a, b: not self._same_side(a, b))
        self._update_gauges()

    def heal_partition(self) -> None:
        """Remove the partition; reconnect machinery re-bridges it."""
        with self._lock:
            self._groups = {}
            self._log.append(("partition_heal", None, None, None))
        self._count("partition_heal", 1)
        self._update_gauges()

    # ------------------------------------------------ sockets-only faults

    def add_latency(self, seconds: float, jitter: float = 0.0) -> None:
        """Delay every received chunk by ``seconds`` plus a uniform draw
        from ``[0, jitter)`` (per-stream seeded RNG). 0 disarms — disarm
        calls are logged but not counted as injected failures."""
        armed = seconds > 0 or jitter > 0
        with self._lock:
            self._latency = float(seconds)
            self._jitter = float(jitter)
            self._log.append(("latency", None, None, (seconds, jitter)))
        self._count("latency", 1 if armed else 0)

    def throttle(self, bytes_per_sec: Optional[float]) -> None:
        """Bound receive bandwidth (every chunk sleeps size/rate).
        ``None`` disarms (logged, not counted)."""
        with self._lock:
            self._rate = None if not bytes_per_sec else float(bytes_per_sec)
            self._log.append(("throttle", None, None, bytes_per_sec))
        self._count("throttle", 1 if bytes_per_sec else 0)

    def drop_frames(self, p: float) -> None:
        """Drop each sent frame independently with probability ``p``."""
        with self._lock:
            self._drop_p = float(p)
            self._log.append(("drop_arm", None, None, p))

    def duplicate_frames(self, p: float) -> None:
        """Send each frame twice with probability ``p``."""
        with self._lock:
            self._dup_p = float(p)
            self._log.append(("duplicate_arm", None, None, p))

    def corrupt_frames(self, p: float) -> None:
        """Flip one body byte of each frame with probability ``p``."""
        with self._lock:
            self._corrupt_p = float(p)
            self._log.append(("corrupt_arm", None, None, p))

    def slow_drain(self, node_id, stall: float = 1.0) -> None:
        """Make ``node_id`` drain its sockets one stalled chunk at a time,
        so peers' write buffers grow until their ``max_send_buffer``
        backpressure bound trips. ``stall <= 0`` disarms (logged, not
        counted)."""
        nid = str(node_id)
        with self._lock:
            if stall > 0:
                self._slow[nid] = float(stall)
            else:
                self._slow.pop(nid, None)
            self._log.append(("slow_drain", nid, None, stall))
        self._count("slow_drain", 1 if stall > 0 else 0)
        self._update_gauges()

    def clear_faults(self) -> None:
        """Disarm every non-structural fault (latency, throttle, frame
        faults, slow-drain); kills/cuts/partitions stay."""
        with self._lock:
            self._latency = self._jitter = 0.0
            self._rate = None
            self._drop_p = self._dup_p = self._corrupt_p = 0.0
            self._slow.clear()
            self._log.append(("clear_faults", None, None, None))
        self._update_gauges()

    def reset(self) -> None:
        """Back to a fault-free plane (structural faults included)."""
        with self._lock:
            self._dead.clear()
            self._preempted.clear()
            self._cut.clear()
            self._groups = {}
            self._log.append(("reset", None, None, None))
        self.clear_faults()

    # ------------------------------------------------------------ queries

    def link_ok(self, a: str, b: str) -> bool:
        """May a byte flow between node ids ``a`` and ``b`` right now?"""
        with self._lock:
            if a in self._dead or b in self._dead:
                return False
            if self._cut and frozenset((a, b)) in self._cut:
                return False
            return self._same_side(a, b)

    def _same_side(self, a: str, b: str) -> bool:
        # Takes the plane lock itself: besides link_ok (which already
        # holds it — RLock, re-entry is free), this runs as _sever's
        # predicate on the partition path, where reading _groups unlocked
        # would race a concurrent partition()/heal_partition() swap.
        with self._lock:
            ga = self._groups.get(a)
            gb = self._groups.get(b)
        return ga is None or gb is None or ga == gb

    def frame_fault_probs(self) -> Tuple[float, float, float]:
        with self._lock:
            return self._drop_p, self._dup_p, self._corrupt_p

    def slow_drain_stall(self, node_id: str) -> float:
        with self._lock:
            return self._slow.get(node_id, 0.0)

    def recv_delay(self, nbytes: int, rng: random.Random) -> float:
        """Receive-side sleep for one chunk: latency + jitter + throttle."""
        with self._lock:
            latency, jitter, rate = self._latency, self._jitter, self._rate
        delay = latency
        if jitter > 0:
            delay += jitter * rng.random()
        if rate:
            delay += nbytes / rate
        return delay

    def fault_log(self) -> List[Tuple]:
        """Ordered record of every control op and applied frame fault:
        ``(kind, src, dst, detail)`` tuples. Frame-fault entries carry the
        per-stream frame index as ``detail`` — with stable node ids and
        deterministic per-stream traffic, two runs under the same seed
        produce the identical log. Bounded to the last 65536 entries."""
        with self._lock:
            return list(self._log)

    def fault_schedule(self, src, dst, n_frames: int) -> List[Tuple[float, ...]]:
        """The first ``n_frames`` frame-fault draws for the ``src -> dst``
        stream: ``(r_drop, r_dup, r_corrupt, r_pos)`` per frame. A pure
        function of ``(seed, src, dst)`` — what the determinism tests
        compare byte-for-byte across planes."""
        rng = self._stream_rng(str(src), str(dst), "send")
        return [tuple(rng.random() for _ in range(4)) for _ in range(n_frames)]

    # ----------------------------------------------------------- internal

    def _stream_rng(self, src: str, dst: str, direction: str) -> random.Random:
        """Per-stream RNG: stable under event-loop interleaving because it
        depends only on the seed and the directed endpoint pair (Python's
        builtin hash is process-salted, hence sha256)."""
        digest = hashlib.sha256(
            f"{self.seed}|{src}|{dst}|{direction}".encode()).digest()
        return random.Random(int.from_bytes(digest[:8], "big"))

    def _sever(self, pred) -> None:
        """Close every live attached connection whose (owner, peer) id
        pair matches; NodeConnection.stop is thread-safe and idempotent."""
        with self._lock:
            nodes = list(self._nodes.values())
        for node in nodes:
            for conn in list(node.all_nodes):
                if pred(node.id, conn.id) or pred(conn.id, node.id):
                    conn.stop()

    def _fault_applied(self, kind: str, src: str, dst: str, idx: int) -> None:
        with self._lock:
            self._log.append((kind, src, dst, idx))
        self._m_injected.labels(kind).inc()

    def _count(self, kind: str, n: int) -> None:
        if n:
            self._m_injected.labels(kind).inc(n)

    def _update_gauges(self) -> None:
        with self._lock:
            dead, cut = len(self._dead), len(self._cut)
            preempted = len(self._preempted)
            groups = len(set(self._groups.values()))
            slow = len(self._slow)
        self._m_active.labels("dead_nodes").set(dead)
        self._m_active.labels("preempted_nodes").set(preempted)
        self._m_active.labels("cut_links").set(cut)
        self._m_active.labels("partition_groups").set(groups)
        self._m_active.labels("slow_drain_nodes").set(slow)
