"""graftdur crash-storm campaign: seeded SIGKILLs against the serving
trail, asserting zero acknowledged-ticket loss.

A :class:`CrashSchedule` is a byte-replayable list of kill points drawn
from one stdlib ``random.Random(seed)`` stream. Each kill names a seam
the service side planted for exactly this purpose:

- ``"tick"`` — die mid-phase: after the tick's engine dispatch, before
  harvest (``SimService._tick_fault``), so the journal holds acks the
  boundary pair does not;
- ``"sidecar_publish"`` — die inside the checkpoint, between the store
  entry landing and the sidecar rename (``_publish_fault``) — the
  classic torn-pair window;
- ``"journal_append"`` — die between a record's header and payload
  writes (the journal's ``fault_hook`` at ``"append_mid"``), leaving a
  genuinely torn tail the next life must truncate past;
- ``"disk_full"`` — same seam, but raise ``ENOSPC`` instead of dying:
  the service must flip to ``DurabilityLost`` shedding, not crash and
  not silently accept unloggable work.

:func:`run_campaign` drives the storm as a subprocess soak: one
reference child runs a seeded traffic + grow-only churn workload
uninterrupted; K children run the SAME workload over a shared trail,
each dying at its scheduled kill (``SIGKILL`` — no atexit, no flush);
a final child runs the workload to completion over the survivors'
trail. After every kill the parent scans the dead child's trail with
:func:`acked_tickets` (pure stdlib reads — sidecar JSON plus the
journal suffix past its ``journal_seqno``); the campaign FAILS unless
every ticket ever observed acknowledged appears in the final table, and
the final table — per-ticket status, rounds, seen hashes — is
bit-identical to the uninterrupted reference.

Churn in the campaign is GROW-ONLY (capacity pre-provisioning): edge
deltas mutate the overlay beyond what the sidecar's recorded growth
steps can replay onto a fresh construction, so a delta-churned trail
deliberately refuses resume (``GraphMismatch`` — see
``SimService._try_resume``). Pending-delta journal replay is covered
in-process by tests/test_graftdur.py instead.

``disk_full`` is deliberately NOT a campaign kill: it degrades
availability (arrivals shed loudly while the trail advances), so the
final table legitimately differs from the reference. It is installable
via :func:`install` for the in-process DurabilityLost tests.

Like storm.py, this module speaks the serving plane (the journal scan
lives under ``serve/``), so chaos/__init__ loads it lazily to keep the
sockets backend's top-level no-jax rule; the campaign parent itself
never touches devices — only the children dispatch.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Set, Tuple

from p2pnetwork_tpu.serve.journal import read_records

__all__ = ["KILL_KINDS", "KillPoint", "CrashSchedule", "CampaignError",
           "generate", "install", "acked_tickets", "run_campaign",
           "DEFAULT_CONFIG"]

#: Kill seams a :class:`KillPoint` can name (module doc).
KILL_KINDS = ("tick", "journal_append", "sidecar_publish", "disk_full")

# Keep in sync with serve.service._SIDECAR (not imported: that module
# pulls jax, and the campaign parent must stay device-free).
_SIDECAR = "service_state.json"

_REPO = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))


class CampaignError(RuntimeError):
    """The crash-storm campaign's contract was violated: an
    acknowledged ticket vanished, the final table diverged from the
    uninterrupted reference, or a child failed outside its kill."""


@dataclasses.dataclass(frozen=True)
class KillPoint:
    """One scheduled kill. ``at`` is the trigger ordinal: for
    ``"tick"`` / ``"sidecar_publish"`` the first driver tick index at
    or past which the seam fires; for ``"journal_append"`` /
    ``"disk_full"`` the Nth (1-based) record append of the child's
    life."""

    kind: str
    at: int

    def __post_init__(self):
        if self.kind not in KILL_KINDS:
            raise ValueError(f"kill kind {self.kind!r} not in {KILL_KINDS}")
        if self.at < 1:
            raise ValueError("kill point `at` must be >= 1")


@dataclasses.dataclass(frozen=True)
class CrashSchedule:
    """A materialized kill schedule plus the seed that drew it."""

    kills: Tuple[KillPoint, ...]
    seed: int

    def __len__(self) -> int:
        return len(self.kills)

    def to_bytes(self) -> bytes:
        """Canonical serialization — the byte-identity witness the
        determinism test compares (two generations must match)."""
        return json.dumps({
            "seed": self.seed,
            "kills": [dataclasses.asdict(k) for k in self.kills],
        }, sort_keys=True, separators=(",", ":")).encode("utf-8")


def generate(n_kills: int, *, seed: int = 0, ticks: int = 32,
             require: Tuple[str, ...] = ("journal_append",
                                         "sidecar_publish")) -> CrashSchedule:
    """Draw a :class:`CrashSchedule` of ``n_kills`` SIGKILL points off
    one ``random.Random(seed)`` stream (byte-replayable). ``require``
    kinds are guaranteed present (the acceptance soak needs at least
    one mid-journal-append and one mid-sidecar-publish kill); the rest
    are drawn uniformly from the SIGKILL kinds. Tick-keyed kills get
    increasing trigger ticks spread across the ``ticks``-long schedule
    so successive lives keep making progress; append-keyed kills
    trigger early in their life (a torn tail needs appends, not
    ticks)."""
    if n_kills < 1:
        raise ValueError("n_kills must be >= 1")
    for kind in require:
        if kind not in KILL_KINDS or kind == "disk_full":
            raise ValueError(
                f"require kind {kind!r} must be a SIGKILL kind "
                f"(one of {tuple(k for k in KILL_KINDS if k != 'disk_full')})")
    if n_kills < len(require):
        raise ValueError(
            f"n_kills={n_kills} cannot cover required kinds {require}")
    rng = random.Random(int(seed))
    pool = [k for k in KILL_KINDS if k != "disk_full"]
    kinds: List[str] = list(require)
    kinds += [pool[rng.randrange(len(pool))]
              for _ in range(n_kills - len(require))]
    rng.shuffle(kinds)
    kills: List[Optional[KillPoint]] = [None] * len(kinds)
    tick_slots = [i for i, k in enumerate(kinds)
                  if k in ("tick", "sidecar_publish")]
    lo, hi = 2, max(3, int(ticks) - 2)
    span = max(1, (hi - lo) // max(1, len(tick_slots)))
    for j, i in enumerate(tick_slots):
        at = min(hi, lo + j * span + rng.randrange(span))
        kills[i] = KillPoint(kinds[i], at)
    for i, kind in enumerate(kinds):
        if kills[i] is None:
            kills[i] = KillPoint(kind, rng.randrange(2, 12))
    return CrashSchedule(kills=tuple(kills), seed=int(seed))


# ------------------------------------------------------------- injection

def install(service, kill: KillPoint, *,
            action: Optional[Callable[[], None]] = None) -> Callable[[], None]:
    """Arm one kill point on a live (not yet driven) service.

    ``action`` defaults to ``os.kill(os.getpid(), SIGKILL)`` for the
    SIGKILL kinds — the real thing, no atexit, no buffered goodbye —
    and to raising ``OSError(ENOSPC)`` for ``"disk_full"``. In-process
    tests pass their own action (e.g. raising a simulated-kill
    exception) to exercise the same seams without losing the process.
    Returns the action installed (for introspection)."""
    kind, at = kill.kind, int(kill.at)
    if action is None:
        if kind == "disk_full":
            def action() -> None:
                raise OSError(28, "No space left on device (injected)")
        else:
            def action() -> None:
                os.kill(os.getpid(), signal.SIGKILL)
    if kind == "tick":
        def tick_fault(tick0: int) -> None:
            if int(tick0) >= at:
                action()
        service._tick_fault = tick_fault
    elif kind == "sidecar_publish":
        def publish_fault(tick: int) -> None:
            if int(tick) >= at:
                action()
        service._publish_fault = publish_fault
    else:  # journal_append / disk_full: Nth append of this life
        journal = service._journal
        if journal is None:
            raise ValueError(
                f"kill kind {kind!r} needs a journaled service "
                "(construct with store=... and journal enabled)")
        seen = {"n": 0}

        def hook(event: str, seq: int) -> None:
            if event != "append_mid":
                return
            seen["n"] += 1
            if seen["n"] >= at:
                action()
        journal.fault_hook = hook
    return action


# ------------------------------------------------------------ trail scan

def acked_tickets(directory: str) -> Set[str]:
    """Every ticket id the trail at ``directory`` proves was
    acknowledged: the sidecar's ticket table plus journaled submits
    past the sidecar's ``journal_seqno``. Pure stdlib reads — safe on
    a freshly killed child's trail, creates nothing."""
    side: dict = {}
    try:
        with open(os.path.join(directory, _SIDECAR),
                  "r", encoding="utf-8") as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            side = loaded
    except (OSError, ValueError):
        pass
    acked = {str(t) for t in (side.get("tickets") or {})}
    covered = int(side.get("journal_seqno", 0) or 0)
    records, _ = read_records(directory)
    for rec in records:
        if rec.get("kind") == "submit" and int(rec["seq"]) > covered:
            acked.add(str(rec["ticket"]))
    return acked


# -------------------------------------------------------- subprocess soak

#: The campaign workload (child-side construction; everything a pure
#: function of these values, so every child builds the identical run).
DEFAULT_CONFIG: Dict[str, object] = {
    "n_nodes": 512, "degree": 6, "rewire": 0.1, "graph_seed": 3,
    "ticks": 24, "rate": 2.0, "traffic_seed": 11,
    "grow_prob": 0.2, "grow_batch": 8, "churn_seed": 7,
    "capacity": 16, "chunk_rounds": 4, "service_seed": 0,
    "checkpoint_every_ticks": 4,
}

_CHILD = '''
import json, sys

sys.path.insert(0, {repo!r})
import jax  # noqa: F401  (fail fast if the runtime is absent)

from p2pnetwork_tpu.chaos import crashstorm
from p2pnetwork_tpu.chaos import storm as storm_mod
from p2pnetwork_tpu.serve import SimService
from p2pnetwork_tpu.serve import traffic as traffic_mod
from p2pnetwork_tpu.sim import graph as G

cfg_path, store_dir, kill_kind, kill_at = sys.argv[1:5]
with open(cfg_path, "r", encoding="utf-8") as f:
    cfg = json.load(f)

g = G.watts_strogatz(int(cfg["n_nodes"]), int(cfg["degree"]),
                     float(cfg["rewire"]), seed=int(cfg["graph_seed"]))
tp = traffic_mod.TrafficPattern(ticks=int(cfg["ticks"]),
                                rate=float(cfg["rate"]))
ts = traffic_mod.generate(tp, int(cfg["n_nodes"]),
                          seed=int(cfg["traffic_seed"]))
# GROW-ONLY churn: edge deltas would gate resume (GraphMismatch) —
# crashstorm module doc.
cp = storm_mod.ChurnPattern(ticks=int(cfg["ticks"]), join_prob=0.0,
                            leave_prob=0.0,
                            grow_prob=float(cfg["grow_prob"]),
                            grow_batch=int(cfg["grow_batch"]))
cs = storm_mod.generate(cp, int(cfg["n_nodes"]),
                        seed=int(cfg["churn_seed"]))
svc = SimService(g, capacity=int(cfg["capacity"]),
                 chunk_rounds=int(cfg["chunk_rounds"]),
                 seed=int(cfg["service_seed"]), store=store_dir,
                 checkpoint_every_ticks=int(
                     cfg["checkpoint_every_ticks"]),
                 record_seen_hash=True)
if kill_kind != "none":
    crashstorm.install(
        svc, crashstorm.KillPoint(kill_kind, int(kill_at)))
res = storm_mod.drive(svc, cs, traffic=ts)
table = svc.tickets()
svc.close()
print("DONE " + json.dumps(
    {{"tickets": table, "submitted": res["submitted"],
      "replayed": res["replayed"], "shed": len(res["shed"])}},
    sort_keys=True), flush=True)
'''


def _run_child(script: str, cfg_path: str, store_dir: str, kind: str,
               at: int, *, timeout: float,
               env: Optional[Dict[str, str]]) -> subprocess.CompletedProcess:
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    return subprocess.run(
        [sys.executable, script, cfg_path, str(store_dir),
         str(kind), str(int(at))],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=_REPO, env=child_env, timeout=timeout)


def _parse_done(proc: subprocess.CompletedProcess, what: str) -> dict:
    if proc.returncode != 0:
        raise CampaignError(
            f"{what} child exited {proc.returncode}: "
            f"{proc.stderr[-2000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("DONE "):
            return json.loads(line[len("DONE "):])
    raise CampaignError(f"{what} child printed no DONE line: "
                        f"{proc.stdout[-2000:]}")


def run_campaign(workdir: str, schedule: CrashSchedule, *,
                 config: Optional[Dict[str, object]] = None,
                 timeout: float = 900.0,
                 env: Optional[Dict[str, str]] = None) -> dict:
    """Run the subprocess crash-storm soak (module doc) under
    ``workdir``; raises :class:`CampaignError` on any acknowledged-
    ticket loss or reference divergence, else returns the report::

        {"kills": [{"kind", "at", "landed", "acked"}...],
         "acked_seen", "tickets", "replayed", "reference_submitted"}

    ``landed`` is False when a child finished its whole workload before
    the kill point fired (a too-fast box) — tolerated, the other kills
    still exercise their seams. ``env`` entries overlay ``os.environ``
    for the children (e.g. ``{"JAX_PLATFORMS": "cpu"}``)."""
    for kill in schedule.kills:
        if kill.kind == "disk_full":
            raise CampaignError(
                "disk_full is an availability fault, not a kill: the "
                "degraded life sheds arrivals loudly while its trail "
                "advances, so the final table legitimately diverges "
                "from the reference — drive it in-process instead "
                "(tests/test_graftdur.py)")
    cfg = dict(DEFAULT_CONFIG)
    cfg.update(config or {})
    workdir = os.path.abspath(workdir)
    os.makedirs(workdir, exist_ok=True)
    script = os.path.join(workdir, "crashstorm_child.py")
    with open(script, "w", encoding="utf-8") as f:
        f.write(_CHILD.format(repo=_REPO))
    cfg_path = os.path.join(workdir, "crashstorm_config.json")
    with open(cfg_path, "w", encoding="utf-8") as f:
        json.dump(cfg, f, sort_keys=True)
    ref_dir = os.path.join(workdir, "reference")
    trail_dir = os.path.join(workdir, "trail")

    ref = _parse_done(
        _run_child(script, cfg_path, ref_dir, "none", 0,
                   timeout=timeout, env=env), "reference")

    acked_seen: Set[str] = set()
    kills_report: List[dict] = []
    for kill in schedule.kills:
        proc = _run_child(script, cfg_path, trail_dir, kill.kind,
                          kill.at, timeout=timeout, env=env)
        landed = proc.returncode == -signal.SIGKILL
        if not landed and proc.returncode != 0:
            raise CampaignError(
                f"kill child ({kill.kind}@{kill.at}) exited "
                f"{proc.returncode} (expected -SIGKILL or clean "
                f"finish): {proc.stderr[-2000:]}")
        acked = acked_tickets(trail_dir)
        acked_seen |= acked
        kills_report.append({"kind": kill.kind, "at": kill.at,
                             "landed": landed, "acked": len(acked)})

    final = _parse_done(
        _run_child(script, cfg_path, trail_dir, "none", 0,
                   timeout=timeout, env=env), "final")

    lost = sorted(acked_seen - set(final["tickets"]))
    if lost:
        raise CampaignError(
            f"acknowledged tickets lost across the storm: {lost[:10]}"
            f"{'...' if len(lost) > 10 else ''} "
            f"({len(lost)} of {len(acked_seen)} acked)")
    if final["tickets"] != ref["tickets"]:
        ref_t, fin_t = ref["tickets"], final["tickets"]
        only_ref = sorted(set(ref_t) - set(fin_t))
        only_fin = sorted(set(fin_t) - set(ref_t))
        differing = sorted(t for t in set(ref_t) & set(fin_t)
                           if ref_t[t] != fin_t[t])
        raise CampaignError(
            "final table diverged from the uninterrupted reference: "
            f"missing={only_ref[:5]} extra={only_fin[:5]} "
            f"differing={differing[:5]} "
            f"(first diff: {differing[0] if differing else None} "
            f"ref={ref_t[differing[0]] if differing else None} "
            f"got={fin_t[differing[0]] if differing else None})")
    return {"kills": kills_report, "acked_seen": len(acked_seen),
            "tickets": len(final["tickets"]),
            "replayed": int(final["replayed"]),
            "reference_submitted": int(ref["submitted"])}
