"""Seeded open-loop traffic: "millions of users" as a reproducible workload.

The north star claims heavy traffic; a claim needs a generator. This
module turns one PRNG seed into a complete open-loop arrival schedule —
Poisson arrivals whose rate follows a diurnal sinusoid with seeded burst
ticks, source ids drawn from a hot-key set with Zipf skew (the
popular-content pattern) or uniformly from the long tail, tenants
round-tripped through the same stream — and drives a
:class:`~p2pnetwork_tpu.serve.service.SimService` with it, one schedule
tick per driver tick.

Everything is a pure function of ``(pattern, n_nodes, seed)``: the
schedule serializes to bytes (:meth:`TrafficSchedule.to_bytes`) and two
generations are byte-identical; driving two fresh services with the same
schedule produces identical per-ticket completion summaries (the service
stores no wall timestamps in records) — which is also what makes the
chaos soak's "resumed run == uninterrupted run" comparison meaningful.
tests/test_serve.py pins both.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from p2pnetwork_tpu.serve.service import (TERMINAL_STATES,
                                           Rejected, SimService)

__all__ = ["TrafficPattern", "TrafficSchedule", "generate", "drive"]


def _consume_replay(service: SimService, t: int) -> Optional[dict]:
    """Consume the service's journal-replay suffix positionally for ONE
    arrival slot at schedule tick ``t`` (graftdur resume): records for
    later ticks stay queued; non-arrival intents (cancel/grow/delta)
    due here replay in passing; an arrival record (submit/shed) due
    here replays and returns — the drive then SKIPS the fresh
    submission, because the crashed life already acknowledged exactly
    this arrival (same ticket id, same position). ``None`` means the
    arrival was never acknowledged: submit it fresh, and the persisted
    ticket counter re-issues the id it would have gotten."""
    while True:
        head = service.replay_peek()
        if head is None or int(head.get("tick", 0)) > t:
            return None
        if head.get("kind") in ("submit", "shed"):
            return service.replay_next()
        service.replay_next()


@dataclasses.dataclass(frozen=True)
class TrafficPattern:
    """Shape of the open-loop workload (all knobs deterministic given
    the seed; rates are per driver TICK, not per wall-second — the
    service's control plane advances in ticks, so a schedule replays
    identically at any wall speed).

    ``rate`` is the mean Poisson arrivals per tick; ``diurnal_*`` put a
    sinusoidal day-cycle on it (amplitude 0 disables); ``burst_prob``
    ticks spike the rate by ``burst_mult`` (flash crowds);
    ``hot_fraction`` of arrivals draw their source from ``hot_keys``
    Zipf(``zipf_s``)-weighted hot nodes, the rest uniformly from the
    whole graph; ``tenants`` are assigned per arrival from the same
    stream (quota-testing traffic mixes)."""

    ticks: int = 64
    rate: float = 4.0
    hot_fraction: float = 0.5
    hot_keys: int = 8
    zipf_s: float = 1.1
    diurnal_amplitude: float = 0.0
    diurnal_period: float = 24.0
    burst_prob: float = 0.0
    burst_mult: float = 4.0
    tenants: Tuple[str, ...] = ("default",)
    coverage_target: float = 0.99

    def __post_init__(self):
        if self.ticks < 1:
            raise ValueError("ticks must be >= 1")
        if self.rate < 0:
            raise ValueError("rate must be >= 0")
        if not 0.0 <= self.hot_fraction <= 1.0:
            raise ValueError("hot_fraction must be in [0, 1]")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError("diurnal_amplitude must be in [0, 1)")
        if not self.tenants:
            raise ValueError("need at least one tenant")
        if not 0.0 <= self.burst_prob <= 1.0:
            raise ValueError("burst_prob must be in [0, 1]")
        if self.burst_mult < 0:
            raise ValueError("burst_mult must be >= 0 "
                             "(< 1 models brownouts, > 1 flash crowds)")
        if self.zipf_s < 0:
            raise ValueError("zipf_s must be >= 0 (0 = uniform hot set)")
        if self.hot_keys < 1:
            raise ValueError("hot_keys must be >= 1")
        if self.diurnal_period <= 0:
            raise ValueError("diurnal_period must be > 0")
        if not 0.0 < self.coverage_target <= 1.0:
            # Validated here like every other knob: submit() would
            # reject it anyway, but only mid-drive after the service
            # already advanced — pattern construction is where a bad
            # workload should die.
            raise ValueError("coverage_target must be in (0, 1]")


@dataclasses.dataclass(frozen=True)
class TrafficSchedule:
    """A fully materialized arrival schedule: parallel arrays (one row
    per arrival, tick-ordered) plus the provenance that generated them."""

    pattern: TrafficPattern
    seed: int
    n_nodes: int
    tick: np.ndarray     # i32[arrivals], nondecreasing
    source: np.ndarray   # i32[arrivals]
    tenant: np.ndarray   # i32[arrivals] — index into pattern.tenants

    def __len__(self) -> int:
        return int(self.tick.size)

    @property
    def ticks(self) -> int:
        return self.pattern.ticks

    def arrivals_at(self, t: int) -> List[Tuple[int, str]]:
        """``[(source, tenant), ...]`` arriving at schedule tick ``t``."""
        idx = np.flatnonzero(self.tick == int(t))
        srcs = self.source[idx].tolist()
        tens = self.tenant[idx].tolist()
        return [(s, self.pattern.tenants[ti]) for s, ti in zip(srcs, tens)]

    def to_bytes(self) -> bytes:
        """Canonical serialization — the byte-identity witness the
        determinism tests compare (header JSON + the three arrays)."""
        header = json.dumps({
            "pattern": dataclasses.asdict(self.pattern),
            "seed": self.seed, "n_nodes": self.n_nodes,
            "arrivals": len(self),
        }, sort_keys=True).encode("utf-8")
        return b"\n".join([header, self.tick.tobytes(),
                           self.source.tobytes(), self.tenant.tobytes()])


def generate(pattern: TrafficPattern, n_nodes: int,
             seed: int = 0) -> TrafficSchedule:
    """Materialize the arrival schedule off ONE ``default_rng(seed)``
    stream (draw order is fixed: per tick — burst coin, count; per
    arrival — hot coin, source, tenant), so a run is byte-replayable."""
    if n_nodes < 1:
        raise ValueError("n_nodes must be >= 1")
    rng = np.random.default_rng(int(seed))
    n_hot = max(1, min(int(pattern.hot_keys), int(n_nodes)))
    hot_set = rng.choice(n_nodes, size=n_hot, replace=False).astype(np.int32)
    ranks = np.arange(1, n_hot + 1, dtype=np.float64)
    hot_w = ranks ** (-float(pattern.zipf_s))
    hot_w /= hot_w.sum()
    ticks: List[int] = []
    sources: List[int] = []
    tenants: List[int] = []
    n_tenants = len(pattern.tenants)
    for t in range(pattern.ticks):
        lam = pattern.rate * (1.0 + pattern.diurnal_amplitude * np.sin(
            2.0 * np.pi * t / max(pattern.diurnal_period, 1e-9)))
        if pattern.burst_prob > 0 and rng.random() < pattern.burst_prob:
            lam *= pattern.burst_mult
        count = int(rng.poisson(max(lam, 0.0)))
        for _ in range(count):
            if rng.random() < pattern.hot_fraction:
                src = int(hot_set[rng.choice(n_hot, p=hot_w)])
            else:
                src = int(rng.integers(0, n_nodes))
            ticks.append(t)
            sources.append(src)
            tenants.append(int(rng.integers(0, n_tenants)))
    return TrafficSchedule(
        pattern=pattern, seed=int(seed), n_nodes=int(n_nodes),
        tick=np.asarray(ticks, dtype=np.int32),
        source=np.asarray(sources, dtype=np.int32),
        tenant=np.asarray(tenants, dtype=np.int32))


def drive(service: SimService, schedule: TrafficSchedule, *,
          from_tick: Optional[int] = None, drain: bool = True,
          max_drain_ticks: int = 1024) -> Dict[str, object]:
    """Drive the service with the schedule, one schedule tick per
    driver tick, synchronously (the deterministic mode — the service's
    background thread must NOT be running).

    ``from_tick`` aligns a resumed service with the schedule: default
    ``service.tick_index``, so replaying the same schedule into a
    service restored from a checkpoint re-submits exactly the arrivals
    the killed run lost (ticket ids come from the service's persisted
    counter, so the re-submissions get the SAME ids). ``drain=True``
    keeps ticking (no new arrivals) until nothing is queued or running.

    Returns ``{"tickets": {tid: record}, "shed": [...], "submitted",
    "completed", "replayed", "drain_ticks", "peak_concurrent_lanes",
    "executed_rounds"}`` — every field deterministic for a given
    (schedule, service config). ``peak_concurrent_lanes`` is the most
    lanes in flight during any single engine chunk (the "sustains N
    concurrent lanes" number the bench and the acceptance soak
    publish)."""
    if service.driver_running:
        raise RuntimeError(
            "drive() needs exclusive control of the driver: the "
            "service's background thread is running (construct without "
            "start(), or close() it first) — concurrent ticks would "
            "race the driver-confined batch state")
    start = service.tick_index if from_tick is None else int(from_tick)
    submitted: List[str] = []
    pending: set = set()
    tickets: Dict[str, Optional[dict]] = {}
    shed: List[dict] = []
    peak = 0
    rounds = 0
    def _tick() -> None:
        # Harvest terminal records EVERY tick, not once at the end: a
        # run completing more tickets than the service's done_retention
        # would otherwise lose the oldest results to eviction before
        # the final poll (bench-scale drives routinely do).
        nonlocal peak, rounds
        info = service.tick()
        peak = max(peak, info["running"])
        rounds += info["executed_rounds"]
        # sorted: set iteration order is hash-randomized per process;
        # harvest order must not be. Poll only the PENDING ids — copying
        # the whole retained table every tick would be O(ticks x
        # done_retention) for records already harvested.
        for tid in sorted(pending):
            rec = service.poll(tid)
            if rec is not None and rec["status"] in TERMINAL_STATES:
                tickets[tid] = rec
                pending.discard(tid)

    replayed = 0
    for t in range(start, schedule.ticks):
        for source, tenant in schedule.arrivals_at(t):
            rec = _consume_replay(service, t)
            if rec is not None:
                # The crashed life acknowledged this arrival: its
                # journal record replayed in place of a fresh submit
                # (same ticket id), or its shed re-counted.
                replayed += 1
                if rec["kind"] == "submit":
                    tid = str(rec["ticket"])
                    submitted.append(tid)
                    pending.add(tid)
                else:
                    shed.append({"tick": t, "source": int(source),
                                 "tenant": tenant,
                                 "reason": str(rec.get("reason",
                                                       "replayed"))})
                continue
            try:
                tid = service.submit(
                    source,
                    target_coverage=schedule.pattern.coverage_target,
                    tenant=tenant)
                submitted.append(tid)
                pending.add(tid)
            except Rejected as e:
                shed.append({"tick": t, "source": int(source),
                             "tenant": tenant, "reason": e.reason})
        _tick()
    drained = 0
    while drain and service.busy() and drained < max_drain_ticks:
        _tick()
        drained += 1
    for tid in sorted(pending):  # never terminal (or evicted): last look
        tickets[tid] = service.poll(tid)
    completed = sum(1 for rec in tickets.values()
                    if rec is not None and rec["status"] == "done")
    return {"tickets": tickets, "shed": shed,
            "submitted": len(submitted), "completed": completed,
            "replayed": replayed,
            "drain_ticks": drained, "peak_concurrent_lanes": peak,
            "executed_rounds": rounds}
