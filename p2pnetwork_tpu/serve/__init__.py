"""graftserve: simulation-as-a-service over the batched message plane.

The serving front-end ROADMAP item 2 asks for — a submit/poll/stream
request plane (:class:`SimService`, also mountable on the telemetry
httpd as ``/submit`` / ``/poll/<ticket>`` / ``/cancel/<ticket>`` /
``/stats`` via ``MetricsServer(service=...)``), an admission-control
driver pacing ``BatchFlood.admit`` off live lane occupancy and observed
completion percentiles, bounded queueing with structured load shedding
and per-tenant token-bucket quotas, supervise-plane crash tolerance
(checkpointed batch + sidecar ticket table, bit-identical resume), and
a seeded open-loop traffic generator (:mod:`~p2pnetwork_tpu.serve.traffic`:
Poisson arrivals, hot-key skew, diurnal bursts — byte-replayable) that
makes "heavy traffic" a reproducible workload. See GETTING_STARTED.md
"Simulation as a service".

graftdur adds the durability plane: a write-ahead intent journal
(:class:`Journal`) closing the sub-boundary SIGKILL window, typed
degradation (:class:`DurabilityLost` 503s when the journal fails), and
hot-standby failover (:class:`Standby`, epoch-fenced ``promote()``
refusing a zombie primary's publish with :class:`FencedEpoch`). See
GETTING_STARTED.md "Durability & failover".
"""

from p2pnetwork_tpu.serve.journal import (
    FSYNC_POLICIES,
    Journal,
    RECORD_KINDS,
)
from p2pnetwork_tpu.serve.service import (
    DurabilityLost,
    FencedEpoch,
    GraphMismatch,
    MemoryBudgetExceeded,
    QueueFull,
    QuotaExceeded,
    Rejected,
    ServiceClosed,
    SimService,
    TERMINAL_STATES,
)
from p2pnetwork_tpu.serve.standby import Standby
from p2pnetwork_tpu.serve.traffic import (
    TrafficPattern,
    TrafficSchedule,
    drive,
    generate,
)

__all__ = [
    "DurabilityLost",
    "FSYNC_POLICIES",
    "FencedEpoch",
    "GraphMismatch",
    "Journal",
    "MemoryBudgetExceeded",
    "QueueFull",
    "QuotaExceeded",
    "RECORD_KINDS",
    "Rejected",
    "ServiceClosed",
    "SimService",
    "Standby",
    "TERMINAL_STATES",
    "TrafficPattern",
    "TrafficSchedule",
    "drive",
    "generate",
]
